"""Equivalence tests: device automaton matcher vs the host-trie oracle.

Mirrors the reference's oracle pattern (`emqx_ds_storage_reference` as a
trivially-correct stand-in, and the emqx_trie_search property suites):
randomized filter/topic sets over a tiny alphabet maximize wildcard
overlap and structural edge cases ('$'-topics, empty levels, '#'-parent
matching, deep '+' chains)."""

import random

import pytest

from emqx_tpu import topic as T
from emqx_tpu.engine import MatchEngine
from emqx_tpu.ops.automaton import build_automaton
from emqx_tpu.ops.dictionary import TokenDict
from emqx_tpu.ops.trie_host import HostTrie

WORDS = ["a", "b", "c", "", "dev", "$SYS", "$share-ish", "x"]


def random_filter(rng: random.Random) -> str:
    depth = rng.randint(1, 6)
    ws = []
    for i in range(depth):
        r = rng.random()
        if r < 0.18:
            ws.append("+")
        elif r < 0.28 and i == depth - 1:
            ws.append("#")
        else:
            ws.append(rng.choice(WORDS))
    return "/".join(ws)


def random_topic(rng: random.Random) -> str:
    depth = rng.randint(1, 7)
    return "/".join(rng.choice(WORDS) for _ in range(depth))


def check_engine_vs_oracle(engine, oracle_trie, exact_map, topics):
    got = engine.match_batch(topics)
    for t, g in zip(topics, got):
        ws = T.words(t)
        want = set(exact_map.get(t, set())) | oracle_trie.match_words(ws)
        assert g == want, (t, sorted(map(str, g)), sorted(map(str, want)))


@pytest.mark.parametrize("seed", range(8))
def test_randomized_equivalence(seed):
    rng = random.Random(seed)
    engine = MatchEngine(max_levels=8, f_width=8, m_cap=64)
    oracle = HostTrie()
    exact = {}
    for fid in range(300):
        flt = random_filter(rng)
        try:
            T.validate_filter(flt)
        except ValueError:
            continue
        engine.insert(flt, fid)
        if T.is_wildcard(flt):
            oracle.insert(flt, fid)
        else:
            exact.setdefault(flt, set()).add(fid)
    engine.rebuild()
    topics = [random_topic(rng) for _ in range(200)]
    # include every filter's concrete-ized form to force exact hits
    for _, ws in list(oracle.filters())[:50]:
        concrete = [rng.choice(WORDS) if w in "+#" else w for w in ws]
        topics.append("/".join(concrete))
    check_engine_vs_oracle(engine, oracle, exact, topics)


@pytest.mark.parametrize("seed", range(4))
def test_churn_delta_and_delete(seed):
    """Mutations after rebuild must be visible without a rebuild."""
    rng = random.Random(1000 + seed)
    engine = MatchEngine(max_levels=8, rebuild_threshold=10**9)
    oracle = HostTrie()
    exact = {}
    fid = 0
    live = {}
    for round_ in range(4):
        for _ in range(120):
            flt = random_filter(rng)
            try:
                T.validate_filter(flt)
            except ValueError:
                continue
            engine.insert(flt, fid)
            live[fid] = flt
            if T.is_wildcard(flt):
                oracle.insert(flt, fid)
            else:
                exact.setdefault(flt, set()).add(fid)
            fid += 1
        if round_ == 1:
            engine.rebuild()
        # delete a third of live filters
        for del_fid in list(live)[:: 3]:
            flt = live.pop(del_fid)
            engine.delete(del_fid)
            if T.is_wildcard(flt):
                oracle.delete_id(del_fid)
            else:
                exact[flt].discard(del_fid)
        topics = [random_topic(rng) for _ in range(80)]
        check_engine_vs_oracle(engine, oracle, exact, topics)


def test_dollar_topic_rules():
    engine = MatchEngine()
    engine.insert("#", 1)
    engine.insert("+/monitor", 2)
    engine.insert("$SYS/monitor", 3)
    engine.insert("$SYS/#", 4)
    engine.insert("$SYS/+", 5)
    engine.rebuild()
    assert engine.match("$SYS/monitor") == {3, 4, 5}
    assert engine.match("a/monitor") == {1, 2}
    assert engine.match("$SYS") == {4}


def test_hash_matches_parent_level():
    engine = MatchEngine()
    engine.insert("sport/tennis/#", 1)
    engine.rebuild()
    assert engine.match("sport/tennis") == {1}
    assert engine.match("sport/tennis/player1/score") == {1}
    assert engine.match("sport") == set()


def test_empty_levels():
    engine = MatchEngine()
    engine.insert("a//b", 1)
    engine.insert("a/+/b", 2)
    engine.insert("/+", 3)
    engine.rebuild()
    assert engine.match("a//b") == {1, 2}
    assert engine.match("/x") == {3}
    assert engine.match("/") == {3}  # ('', '')


def test_frontier_overflow_falls_back():
    """More live branches than f_width must still return exact results
    via the host fallback (overflow flag path)."""
    engine = MatchEngine(max_levels=8, f_width=2, m_cap=4)
    # many '+'-chains all alive at once
    for i in range(12):
        pat = ["+"] * 4
        pat[i % 4] = "w%d" % (i % 3)
        engine.insert("/".join(pat), i)
    engine.insert("w0/+/+/+", 100)
    engine.rebuild()
    topic = "w0/w1/w2/w0"
    want = {
        fid
        for fid, ws in engine._wild.filters()
        if T.match_words(T.words(topic), ws)
    }
    assert engine.match(topic) == want


def test_too_deep_topic_falls_back():
    engine = MatchEngine(max_levels=4)
    engine.insert("a/#", 1)
    engine.rebuild()
    deep = "a/" + "/".join("x%d" % i for i in range(10))
    assert engine.match(deep) == {1}


def test_automaton_structure_small():
    td = TokenDict()
    aut = build_automaton(
        [(1, ("a", "b")), (2, ("a", "#")), (3, ("a", "+"))], td, max_levels=4
    )
    # nodes: root, a, a/b, a/+  -> 4
    assert aut.n_nodes == 4
    assert (aut.node_rows[:, 1] > 0).sum() == 1
    assert (aut.node_rows[:, 2] > 0).sum() == 2  # a/b and a/+
    assert (aut.node_rows[:, 0] != 2**31 - 1).sum() == 1
    assert aut.kernel_levels == 3  # deepest body (2) + 1


def test_forced_hash_size_for_sharding():
    td = TokenDict()
    aut = build_automaton([(1, ("a", "b"))], td, hash_buckets=256)
    assert len(aut.fp_rows) == 256


def test_reinsert_changed_filter_after_rebuild():
    """ADVICE r1 (high): re-registering a fid with a different filter
    after a rebuild must not unmask the stale device entry."""
    eng = MatchEngine(use_device=True)
    eng.insert("a/+", 1)
    eng.rebuild()
    eng.insert("b/+", 1)
    assert eng.match("a/x") == set()
    assert eng.match("b/x") == {1}
    eng.rebuild()
    assert eng.match("a/x") == set()
    assert eng.match("b/x") == {1}


def test_delete_then_reinsert_same_filter_after_rebuild():
    eng = MatchEngine(use_device=True)
    eng.insert("a/+", 1)
    eng.rebuild()
    eng.delete(1)
    assert eng.match("a/x") == set()
    eng.insert("a/+", 1)
    assert eng.match("a/x") == {1}


def test_full_depth_filter_does_not_match_deeper_topic():
    """ADVICE r1 (high): body depth == max_levels must still scan one
    level past the body so deeper topics cannot falsely exact-match."""
    eng = MatchEngine(max_levels=4, use_device=True)
    eng.insert("a/b/c/+", 1)
    eng.rebuild()
    assert eng.match("a/b/c/d") == {1}
    assert eng.match("a/b/c/d/e") == set()
    assert eng.match("a/b/c") == set()
    # hash filter at full depth still matches arbitrarily deep
    eng.insert("a/b/c/#", 2)
    eng.rebuild()
    assert eng.match("a/b/c/d/e/f") == {2}


def test_background_rebuild_no_stop_the_world():
    """Mutations during a background rebuild stay correct through the
    swap (emqx_router_syncer-style batching, no synchronous rebuild)."""
    rng = random.Random(7)
    eng = MatchEngine(
        use_device=True, background_rebuild=True, rebuild_threshold=64
    )
    live = {}
    fid = 0
    for round_ in range(6):
        for _ in range(100):
            flt = random_filter(rng)
            try:
                T.validate_filter(flt)
            except ValueError:
                continue
            eng.insert(flt, fid)
            live[fid] = flt
            fid += 1
        # delete a few while a build may be in flight
        for victim in rng.sample(sorted(live), 10):
            eng.delete(victim)
            del live[victim]
        topics = [random_topic(rng) for _ in range(20)]
        got = eng.match_batch(topics)
        for t, g in zip(topics, got):
            want = {
                f for f, w in live.items() if T.match_words(T.words(t), T.words(w))
            }
            assert g == want, (round_, t, g, want)
    # drain: wait for any in-flight build and check again post-swap
    import time

    for _ in range(200):
        if eng._built is not None or not eng._building:
            break
        time.sleep(0.05)
    topics = [random_topic(rng) for _ in range(50)]
    got = eng.match_batch(topics)
    for t, g in zip(topics, got):
        want = {f for f, w in live.items() if T.match_words(T.words(t), T.words(w))}
        assert g == want, (t, g, want)


@pytest.mark.parametrize("seed", range(4))
def test_delta_automaton_churn_equivalence(seed):
    """With a tiny delta-automaton threshold, sustained churn runs
    through the two-tier device path (base automaton + delta automaton
    + host residual) and must stay oracle-equal, including deletes of
    delta-resident filters and a big rebuild dropping the delta tier."""
    rng = random.Random(2000 + seed)
    engine = MatchEngine(
        max_levels=8,
        rebuild_threshold=10**9,
        delta_aut_threshold=32,
    )
    oracle = HostTrie()
    exact = {}
    fid = 0
    live = {}
    built_delta = False
    for round_ in range(5):
        for _ in range(100):
            flt = random_filter(rng)
            try:
                T.validate_filter(flt)
            except ValueError:
                continue
            engine.insert(flt, fid)
            live[fid] = flt
            if T.is_wildcard(flt):
                oracle.insert(flt, fid)
            else:
                exact.setdefault(flt, set()).add(fid)
            fid += 1
        # folds are async and now warm the kernel BEFORE committing;
        # join so the round's checks (and the exercised-path assert)
        # see the committed delta automaton deterministically
        t = engine._fold_thread
        if t is not None and t.is_alive():
            t.join(60)
        built_delta = built_delta or engine._daut is not None
        if round_ == 0:
            engine.rebuild()  # establish a base; later rounds churn
        if round_ == 3:
            # deletes hitting base AND delta-automaton entries
            for del_fid in list(live)[::2]:
                flt = live.pop(del_fid)
                engine.delete(del_fid)
                if T.is_wildcard(flt):
                    oracle.delete_id(del_fid)
                else:
                    exact[flt].discard(del_fid)
        topics = [random_topic(rng) for _ in range(60)]
        check_engine_vs_oracle(engine, oracle, exact, topics)
    assert built_delta  # the two-tier path was actually exercised
    # a big rebuild folds everything and drops the delta tier
    engine.rebuild()
    assert engine._daut is None
    topics = [random_topic(rng) for _ in range(60)]
    check_engine_vs_oracle(engine, oracle, exact, topics)


def test_delta_fold_residual_bound():
    """The host residual stays geometrically bounded while the delta
    folds into the device tier (the churn cliff from VERDICT r2 weak
    #4), and table capacity classes keep the compiled-shape set small."""
    engine = MatchEngine(
        max_levels=8, rebuild_threshold=10**9, delta_aut_threshold=64
    )
    engine._fold_async = False  # strict bound needs inline folds
    shapes = set()
    for i in range(4000):
        engine.insert(f"churn/{i % 97}/+/x{i}", i)
        assert engine._residual_count <= max(64, len(engine._delta) // 2), i
        if engine._daut is not None:
            shapes.add(
                (
                    engine._daut.node_rows.shape,
                    engine._daut.kernel_levels,
                )
            )
    assert engine._daut is not None
    assert len(engine._daut_fids) + engine._residual_count >= 4000 - 64
    # pow2 node-capacity classes bound the traced-shape set
    assert len(shapes) <= 4


def test_async_fold_churn_equivalence():
    """Randomized churn with ASYNC folds (the production mode): after
    all in-flight folds drain, every match must agree with the oracle —
    covers the delete/reinsert-during-fold tombstone races."""
    import time as _t

    rng = random.Random(1234)
    engine = MatchEngine(
        max_levels=8, rebuild_threshold=10**9, delta_aut_threshold=32
    )
    oracle = HostTrie()
    live = {}
    fid = 0
    for step in range(3000):
        r = rng.random()
        if r < 0.70 or not live:
            flt = random_filter(rng)
            try:
                T.validate_filter(flt)
            except ValueError:
                continue
            fid += 1
            engine.insert(flt, fid)
            if fid in live:
                oracle.delete_id(fid)
            oracle.insert(flt, fid)
            live[fid] = flt
        elif r < 0.85:
            victim = rng.choice(list(live))
            engine.delete(victim)
            oracle.delete_id(victim)
            del live[victim]
        else:  # re-point an existing fid (delete+insert via replace)
            victim = rng.choice(list(live))
            flt = random_filter(rng)
            try:
                T.validate_filter(flt)
            except ValueError:
                continue
            engine.insert(flt, victim)
            oracle.delete_id(victim)
            oracle.insert(flt, victim)
            live[victim] = flt
    # drain in-flight folds
    from tests_fakes import drain_folds

    drain_folds(engine, timeout=20)
    topics = [random_topic(rng) for _ in range(200)]
    check_engine_vs_oracle(engine, oracle, {}, topics)
    assert engine._daut is not None  # async folds actually ran


def test_reinserted_fid_survives_fold():
    """A fid deleted and re-inserted with a different filter must keep
    matching after the delta fold: tombstones are per-generation (the
    base's stale entry is masked; the fold's current entry is not)."""
    engine = MatchEngine(
        max_levels=8, rebuild_threshold=10**9, delta_aut_threshold=16
    )
    engine._fold_async = False  # deterministic fold points
    for i in range(40):
        engine.insert(f"seed/{i}/+", i)
    engine.rebuild()  # all 40 in the base
    # re-point fid 7 at a different filter (delete+insert via replace)
    engine.insert("moved/here/#", 7)
    assert engine.match("moved/here/x") == {7}
    assert 7 not in engine.match("seed/7/q")
    # force folds until fid 7 lives in the delta automaton
    for i in range(100, 140):
        engine.insert(f"churn/{i}/+", i)
    assert engine._daut is not None and 7 in engine._daut_fids
    assert engine.match("moved/here/x") == {7}  # the r3 review regression
    assert 7 not in engine.match("seed/7/q")
    # and a deleted fid stays deleted across the fold
    engine.delete(8)
    for i in range(200, 240):
        engine.insert(f"churn2/{i}/+", i)
    assert 8 not in engine.match("seed/8/q")


def test_insert_many_equivalence():
    """insert_many must land in exactly the same state as per-item
    insert: same matches across exact/wild/deep/replaced entries."""
    import random

    rng = random.Random(99)
    pairs = []
    fid = 0
    for _ in range(400):
        flt = random_filter(rng)
        try:
            T.validate_filter(flt)
        except ValueError:
            continue
        pairs.append((flt, fid))
        fid += 1
    # replacements: re-list some fids with different filters
    for i in range(0, len(pairs), 7):
        if "#" not in pairs[i][0]:  # '#/x' would be invalid
            pairs.append((pairs[i][0] + "/x", pairs[i][1]))
    deep = "/".join(f"l{i}" for i in range(12)) + "/+"
    pairs.append((deep, 10_001))  # deep (max_levels=8) path

    one = MatchEngine(max_levels=8, rebuild_threshold=10**9,
                      delta_aut_threshold=10**9)
    many = MatchEngine(max_levels=8, rebuild_threshold=10**9,
                       delta_aut_threshold=10**9)
    for flt, f in pairs:
        one.insert(flt, f)
    for i in range(0, len(pairs), 64):  # windowed, as the syncer does
        many.insert_many(pairs[i:i + 64])

    topics = [random_topic(rng) for _ in range(200)]
    topics.append("l0/l1/l2/l3/l4/l5/l6/l7/l8/l9/l10/l11/zz")
    assert one.match_batch(topics) == many.match_batch(topics)
    assert one.index_stats()["exact"] == many.index_stats()["exact"]

    # an invalid filter anywhere in the window rejects the WHOLE
    # window before any mutation (atomic validation) — no half-applied
    # batches
    import pytest as _pytest
    with _pytest.raises(ValueError):
        many.insert_many([("ok/+", 20_000), ("bad/#/mid", 20_001)])
    assert 20_000 not in many._by_fid
    assert many.match("ok/x") == one.match("ok/x")


def test_insert_many_duplicate_fid_last_wins():
    """A fid listed twice in ONE window must end exactly as per-item
    inserts would: the LAST filter wins everywhere."""
    eng = MatchEngine(max_levels=8, rebuild_threshold=10**9,
                      delta_aut_threshold=10**9)
    eng.insert_many([("a/+", 1), ("b/+", 1)])
    assert eng.match("a/x") == set()
    assert eng.match("b/x") == {1}
    assert eng._by_fid[1] == "b/+"
    # and with a pre-existing registration in the same engine
    eng.insert_many([("c/+", 1), ("d/+", 1), ("e/+", 2)])
    assert eng.match("b/x") == set()
    assert eng.match("c/x") == set()
    assert eng.match("d/x") == {1}
    assert eng.match("e/x") == {2}
