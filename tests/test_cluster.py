"""Cluster-layer tests: multi-node brokers in one process over loopback
TCP — the `emqx_cth_cluster` pattern (peer nodes on the same host,
/root/reference/apps/emqx/test/emqx_cth_cluster.erl:44,334-349) without
spawning OS processes (pytest drives its own event loop)."""

import asyncio

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.cluster import ClusterNode
from emqx_tpu.config import BrokerConfig
from emqx_tpu.message import Message
from emqx_tpu.codec import mqtt as C
from mqtt_client import TestClient


FAST = dict(heartbeat_interval=0.05, down_after=0.25, flush_interval=0.002)


def run(coro):
    return asyncio.run(coro)


async def start_node(name, seeds=(), **kw):
    cfg = BrokerConfig()
    cfg.listeners[0].port = 0
    srv = BrokerServer(cfg)
    await srv.start()
    node = ClusterNode(name, srv.broker, **{**FAST, **kw})
    await node.start(seeds=list(seeds))
    return srv, node


async def stop_node(srv, node):
    await node.stop()
    await srv.stop()


async def settle(t=0.05):
    await asyncio.sleep(t)


def test_cross_node_pubsub():
    async def t():
        s1, n1 = await start_node("n1")
        s2, n2 = await start_node("n2", seeds=[("n1", "127.0.0.1", n1.port)])
        try:
            sub = TestClient(s1.listeners[0].port, "subA")
            await sub.connect()
            await sub.subscribe("fleet/+/temp", qos=1)
            await settle()  # route delta flush -> n2 replica

            assert n2.routes.nodes_for("fleet/+/temp") == {"n1"}

            pub = TestClient(s2.listeners[0].port, "pubB")
            await pub.connect()
            await pub.publish("fleet/v1/temp", b"22C", qos=1)
            msg = await sub.recv_publish(timeout=5)
            assert msg.topic == "fleet/v1/temp" and msg.payload == b"22C"
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await stop_node(s2, n2)
            await stop_node(s1, n1)

    run(t())


def test_route_replication_and_removal():
    async def t():
        s1, n1 = await start_node("n1")
        s2, n2 = await start_node("n2", seeds=[("n1", "127.0.0.1", n1.port)])
        try:
            c = TestClient(s1.listeners[0].port, "c1")
            await c.connect()
            await c.subscribe("a/b", qos=0)
            await c.subscribe("x/#", qos=0)
            await settle()
            assert n2.routes.nodes_for("a/b") == {"n1"}
            assert n2.routes.nodes_for("x/#") == {"n1"}

            await c.unsubscribe("a/b")
            await settle()
            assert n2.routes.nodes_for("a/b") == set()
            assert n2.routes.nodes_for("x/#") == {"n1"}
            await c.disconnect()
            await settle()  # session cleanup drops the last route too
            assert n2.routes.nodes_for("x/#") == set()
        finally:
            await stop_node(s2, n2)
            await stop_node(s1, n1)

    run(t())


def test_late_join_gets_existing_routes():
    async def t():
        s1, n1 = await start_node("n1")
        try:
            c = TestClient(s1.listeners[0].port, "c1")
            await c.connect()
            await c.subscribe("warehouse/+/door", qos=0)
            await settle()

            s2, n2 = await start_node(
                "n2", seeds=[("n1", "127.0.0.1", n1.port)]
            )
            try:
                # the sync exchange, not delta broadcast, carried this
                assert n2.routes.nodes_for("warehouse/+/door") == {"n1"}

                pub = TestClient(s2.listeners[0].port, "p1")
                await pub.connect()
                await pub.publish("warehouse/7/door", b"open", qos=0)
                msg = await c.recv_publish(timeout=5)
                assert msg.payload == b"open"
                await pub.disconnect()
            finally:
                await stop_node(s2, n2)
            await c.disconnect()
        finally:
            await stop_node(s1, n1)

    run(t())


def test_dead_node_routes_purged():
    async def t():
        s1, n1 = await start_node("n1")
        s2, n2 = await start_node("n2", seeds=[("n1", "127.0.0.1", n1.port)])
        n1.add_peer("n2", "127.0.0.1", n2.port)
        try:
            c2 = TestClient(s2.listeners[0].port, "c2")
            await c2.connect()
            await c2.subscribe("dead/+", qos=0)
            await settle()
            assert n1.routes.nodes_for("dead/+") == {"n2"}

            # kill n2 without cleanup: n1 must notice and purge
            await c2.close()
            await stop_node(s2, n2)
            for _ in range(40):
                if "n2" in n1._down:
                    break
                await asyncio.sleep(0.05)
            assert "n2" in n1._down
            assert n1.routes.nodes_for("dead/+") == set()
            # publishing on n1 no longer forwards (and does not error)
            s1.broker.publish_many([Message(topic="dead/x", payload=b"z")])
        finally:
            await stop_node(s1, n1)

    run(t())


def test_three_node_fanout():
    async def t():
        s1, n1 = await start_node("n1")
        seeds = [("n1", "127.0.0.1", n1.port)]
        s2, n2 = await start_node("n2", seeds=seeds)
        s3, n3 = await start_node(
            "n3", seeds=seeds + [("n2", "127.0.0.1", n2.port)]
        )
        n1.add_peer("n2", "127.0.0.1", n2.port)
        try:
            subs = []
            for srv, cid in ((s1, "sA"), (s2, "sB")):
                c = TestClient(srv.listeners[0].port, cid)
                await c.connect()
                await c.subscribe("news/#", qos=0)
                subs.append(c)
            await settle()

            pub = TestClient(s3.listeners[0].port, "p3")
            await pub.connect()
            await pub.publish("news/today", b"hi", qos=0)
            for c in subs:
                msg = await c.recv_publish(timeout=5)
                assert msg.payload == b"hi"
            await pub.disconnect()
            for c in subs:
                await c.disconnect()
        finally:
            await stop_node(s3, n3)
            await stop_node(s2, n2)
            await stop_node(s1, n1)

    run(t())


def test_forward_preserves_bytes_properties_and_skips_side_effects():
    """Code-review r2: bytes-valued MQTT 5 properties must survive the
    JSON transport, and a forwarded message must not re-run publish
    hooks/retain/rules on the receiving node."""

    async def t():
        s1, n1 = await start_node("n1")
        s2, n2 = await start_node("n2", seeds=[("n1", "127.0.0.1", n1.port)])
        try:
            hook_topics = []
            s1.broker.hooks.add(
                "message.publish", lambda m: hook_topics.append(m.topic) or m
            )
            sub = TestClient(s1.listeners[0].port, "subA")
            await sub.connect()
            await sub.subscribe("req/+", qos=1)
            await settle()

            pub = TestClient(s2.listeners[0].port, "pubB")
            await pub.connect()
            await pub.publish(
                "req/1",
                b"ask",
                qos=1,
                properties={
                    "correlation_data": b"\x00\x01\xff",
                    "response_topic": "resp/1",
                },
            )
            msg = await sub.recv_publish(timeout=5)
            assert msg.properties.get("correlation_data") == b"\x00\x01\xff"
            assert msg.properties.get("response_topic") == "resp/1"
            # publish hooks ran on the origin node only
            assert "req/1" not in hook_topics
            assert s1.broker.metrics.val("messages.forward.received") == 1
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await stop_node(s2, n2)
            await stop_node(s1, n1)

    run(t())


def test_sync_snapshot_does_not_lose_racing_route_add():
    """A full-sync purge must not drop a route whose add cast raced past
    the snapshot on the other connection: the seq-guarded re-apply in
    _apply_snapshot keeps it."""

    async def t():
        srv_a, a = await start_node("a")
        srv_b, b = await start_node("b", seeds=[("a", "127.0.0.1", a.port)])
        await settle(0.2)

        # simulate the race directly: B has applied an add from A at a
        # seq NEWER than the snapshot A would reply with
        await b._handle_route_ops(
            "a",
            {
                "node": "a",
                "epoch": a._epoch,
                "ops": [[a._op_seq + 1, "add", "raced/topic"]],
            },
        )
        assert "a" in b.routes.match_nodes(["raced/topic"])[0]
        # now a full sync with A's (older) snapshot runs: the purge must
        # re-apply the newer op from the log instead of dropping it
        await b._sync_with("a")
        assert "a" in b.routes.match_nodes(["raced/topic"])[0]
        # whereas an op INCLUDED in the snapshot window (seq <= snap) is
        # governed by the snapshot: a stale route is reconciled away
        await b._handle_route_ops(
            "a",
            {
                "node": "a",
                "epoch": a._epoch,
                "ops": [[a._op_seq, "add", "stale/topic"]]
                if a._op_seq > 0
                else [[0, "add", "stale/topic"]],
            },
        )
        if a._op_seq > 0:
            await b._sync_with("a")
            assert "a" not in b.routes.match_nodes(["stale/topic"])[0]

        await stop_node(srv_b, b)
        await stop_node(srv_a, a)

    run(t())


def test_restart_epoch_resets_op_log():
    """A peer restart (new epoch) must invalidate the buffered op log so
    old-incarnation ops are not replayed over the new snapshot."""

    async def t():
        srv_a, a = await start_node("a")
        srv_b, b = await start_node("b", seeds=[("a", "127.0.0.1", a.port)])
        await settle(0.2)
        await b._handle_route_ops(
            "a", {"node": "a", "epoch": 123, "ops": [[99, "add", "old/x"]]}
        )
        assert len(b._op_log["a"]) == 1
        # new epoch arrives: log resets, old op cannot resurrect
        b._check_epoch("a", 456)
        assert len(b._op_log["a"]) == 0
        b._apply_snapshot("a", [], 0)
        assert "a" not in b.routes.match_nodes(["old/x"])[0]
        await stop_node(srv_b, b)
        await stop_node(srv_a, a)

    run(t())


def test_restarted_node_advertises_boot_session_routes(tmp_path):
    """After a restart, a node's detached persistent-session filters
    must still be advertised as cluster routes so peers forward (and the
    home node persists) messages published in the restart→reconnect
    window."""

    async def t():
        # node A: durable broker; client subscribes and disconnects
        cfg = BrokerConfig()
        cfg.listeners[0].port = 0
        cfg.durable.enable = True
        cfg.durable.data_dir = str(tmp_path / "ds-a")
        srv_a = BrokerServer(cfg)
        await srv_a.start()
        c = TestClient(srv_a.listeners[0].port, "roamer")
        await c.connect(
            clean_start=False,
            properties={"session_expiry_interval": 3600},
        )
        await c.subscribe("fleet/+/pos", qos=1)
        await c.disconnect()
        await srv_a.stop()
        srv_a.broker.durable.close()

        # node A restarts (no client reconnect yet) and clusters with B
        cfg2 = BrokerConfig()
        cfg2.listeners[0].port = 0
        cfg2.durable.enable = True
        cfg2.durable.data_dir = str(tmp_path / "ds-a")
        srv_a2 = BrokerServer(cfg2)
        await srv_a2.start()
        node_a = ClusterNode("a", srv_a2.broker, **FAST)
        await node_a.start()
        srv_b, node_b = await start_node(
            "b", seeds=[("a", "127.0.0.1", node_a.port)]
        )
        await settle(0.3)

        # B sees A's boot-advertised route and forwards a publish
        assert "a" in node_b.routes.match_nodes(["fleet/7/pos"])[0]
        pub = TestClient(srv_b.listeners[0].port, "pub")
        await pub.connect()
        await pub.publish("fleet/7/pos", b"37.7,-122.4", qos=1)
        await pub.disconnect()
        await settle(0.2)

        # the reconnecting client replays the remote-origin message
        c2 = TestClient(srv_a2.listeners[0].port, "roamer")
        ack = await c2.connect(
            clean_start=False,
            properties={"session_expiry_interval": 3600},
        )
        assert ack.session_present
        pkt = await c2.recv_publish()
        assert pkt.topic == "fleet/7/pos"
        assert pkt.payload == b"37.7,-122.4"
        await c2.disconnect()

        await stop_node(srv_b, node_b)
        await node_a.stop()
        await srv_a2.stop()
        srv_a2.broker.durable.close()

    run(t())


def test_cross_node_session_takeover():
    """VERDICT r3 task 7: connect on A with QoS1 subs, disconnect,
    messages queue on A; reconnect on B with clean_start=false — the
    session (subs + queued messages) migrates and the client replays
    them on B (emqx_cm takeover semantics, emqx_cm.erl:276-317)."""

    async def t():
        srv_a, a = await start_node("a")
        srv_b, b = await start_node("b", seeds=[("a", "127.0.0.1", a.port)])
        await settle(0.3)

        c = TestClient(srv_a.listeners[0].port, "roam-1")
        await c.connect(
            clean_start=False,
            properties={"session_expiry_interval": 3600},
        )
        await c.subscribe("inbox/roam-1/#", qos=1)
        await c.disconnect()
        await settle(0.1)

        # messages arrive while detached: they queue in A's session
        pub = TestClient(srv_b.listeners[0].port, "pubx")
        await pub.connect()
        await pub.publish("inbox/roam-1/m1", b"one", qos=1)
        await pub.publish("inbox/roam-1/m2", b"two", qos=1)
        await pub.disconnect()
        await settle(0.2)
        assert len(srv_a.broker.cm.lookup("roam-1").mqueue) == 2

        # reconnect on B: takeover migrates the session
        c2 = TestClient(srv_b.listeners[0].port, "roam-1")
        ack = await c2.connect(
            clean_start=False,
            properties={"session_expiry_interval": 3600},
        )
        assert ack.session_present
        got = {(await c2.recv_publish()).payload for _ in range(2)}
        assert got == {b"one", b"two"}
        # the session is gone from A and live on B
        assert srv_a.broker.cm.lookup("roam-1") is None
        assert srv_b.broker.cm.lookup("roam-1") is not None
        assert srv_a.broker.metrics.val("session.takenover") == 1

        # subscriptions moved too: a new publish on A routes to B
        await settle(0.2)
        pub2 = TestClient(srv_a.listeners[0].port, "puby")
        await pub2.connect()
        await pub2.publish("inbox/roam-1/m3", b"three", qos=1)
        pkt = await c2.recv_publish()
        assert pkt.payload == b"three"
        await pub2.disconnect()
        await c2.disconnect()
        await stop_node(srv_b, b)
        await stop_node(srv_a, a)

    run(t())


def test_takeover_of_live_connection_kicks_old_channel():
    """A still-connected session on A reconnecting via B must close A's
    channel with the takeover reason and keep exactly one live session."""

    async def t():
        srv_a, a = await start_node("a")
        srv_b, b = await start_node("b", seeds=[("a", "127.0.0.1", a.port)])
        await settle(0.3)

        c1 = TestClient(srv_a.listeners[0].port, "dup-1")
        await c1.connect(
            clean_start=False,
            properties={"session_expiry_interval": 3600},
        )
        await c1.subscribe("d/#", qos=1)
        await settle(0.2)

        c2 = TestClient(srv_b.listeners[0].port, "dup-1")
        ack = await c2.connect(
            clean_start=False,
            properties={"session_expiry_interval": 3600},
        )
        assert ack.session_present  # session migrated from A
        await settle(0.2)
        assert srv_a.broker.cm.lookup("dup-1") is None
        # old connection got closed by the takeover
        pkt = await c1.recv(timeout=2.0)
        assert pkt is None or pkt.type == C.DISCONNECT
        await c2.disconnect()
        await stop_node(srv_b, b)
        await stop_node(srv_a, a)

    run(t())


def test_binary_wire_roundtrip():
    """Binary batch codec: bytes payloads, properties with bytes values
    (correlation_data), flags, and unicode topics all survive."""
    from emqx_tpu.cluster.wire import decode_messages, encode_messages

    msgs = [
        Message(
            topic="t/ü/1",
            payload=bytes(range(256)),
            qos=2,
            retain=True,
            from_client="c1",
            from_username="úser",
            properties={
                "correlation_data": b"\x00\xff",
                "user_property": [("k", "v")],
                "message_expiry_interval": 30,
            },
        ),
        Message(topic="t", payload=b"", qos=0, sys=True, dup=True),
    ]
    out = decode_messages(encode_messages(msgs))
    assert len(out) == 2
    a, b = out
    assert a.topic == "t/ü/1" and a.payload == bytes(range(256))
    assert a.qos == 2 and a.retain and a.from_username == "úser"
    assert a.properties["correlation_data"] == b"\x00\xff"
    assert a.properties["message_expiry_interval"] == 30
    assert b.sys and b.dup and b.payload == b""
    assert a.mid == msgs[0].mid


def test_forward_batching_coalesces_frames():
    """A burst of forwards to one peer leaves in (far) fewer frames than
    messages, and every message arrives."""

    async def t():
        # lww pinned: this test asserts the async cast_bin frame
        # coalescing; raft mode routes forwards through the
        # commit-confirmed forward_sync path instead
        srv_a, a = await start_node("a", consensus="lww")
        srv_b, b = await start_node(
            "b", seeds=[("a", "127.0.0.1", a.port)], consensus="lww"
        )
        await settle(0.3)

        sent_frames = [0]
        orig = a.transport.cast_bin

        async def counting(node, mtype, payload):
            if mtype == "forward_batch":
                sent_frames[0] += 1
            return await orig(node, mtype, payload)

        a.transport.cast_bin = counting

        sub = TestClient(srv_b.listeners[0].port, "s")
        await sub.connect()
        await sub.subscribe("burst/#", qos=0)
        await settle(0.2)

        pub = TestClient(srv_a.listeners[0].port, "p")
        await pub.connect()
        for i in range(200):
            await pub.send(
                C.Publish(topic=f"burst/{i}", payload=b"x", qos=0)
            )
        got = set()
        for _ in range(200):
            pkt = await sub.recv_publish()
            got.add(pkt.topic)
        assert got == {f"burst/{i}" for i in range(200)}
        assert 0 < sent_frames[0] < 50  # coalesced, not per-message
        await pub.disconnect()
        await sub.disconnect()
        await stop_node(srv_b, b)
        await stop_node(srv_a, a)

    run(t())


def test_clean_session_churn_does_not_leak_registry():
    """Zero-expiry sessions announce open AND close: churning clean
    clients must not grow the replicated client registry."""

    async def t():
        srv_a, a = await start_node("a")
        srv_b, b = await start_node("b", seeds=[("a", "127.0.0.1", a.port)])
        await settle(0.3)
        for i in range(10):
            c = TestClient(srv_a.listeners[0].port, f"churn-{i}")
            await c.connect(clean_start=True)
            await c.disconnect()
        await settle(0.3)
        assert not [
            cid for cid in a.clients if cid.startswith("churn-")
        ], a.clients
        assert not [
            cid for cid in b.clients if cid.startswith("churn-")
        ], b.clients
        await stop_node(srv_b, b)
        await stop_node(srv_a, a)

    run(t())


def test_clean_start_elsewhere_kicks_remote_duplicate():
    """Cluster-wide clientid uniqueness holds for clean_start=True too:
    the old node's live connection is kicked, no state transfers."""

    async def t():
        srv_a, a = await start_node("a")
        srv_b, b = await start_node("b", seeds=[("a", "127.0.0.1", a.port)])
        await settle(0.3)
        c1 = TestClient(srv_a.listeners[0].port, "uniq-1")
        await c1.connect(
            clean_start=False,
            properties={"session_expiry_interval": 3600},
        )
        await settle(0.2)
        c2 = TestClient(srv_b.listeners[0].port, "uniq-1")
        ack = await c2.connect(clean_start=True)
        assert not ack.session_present
        await settle(0.3)
        assert srv_a.broker.cm.lookup("uniq-1") is None  # kicked
        assert srv_b.broker.cm.lookup("uniq-1") is not None
        await c2.disconnect()
        await stop_node(srv_b, b)
        await stop_node(srv_a, a)

    run(t())


def test_cluster_wide_config_update():
    """A config update on one node journals to every node (emqx_conf /
    emqx_cluster_rpc multicall semantics), including late joiners via
    sync catch-up.  lww pinned: this validates the journal layer,
    including a POST-COMMIT late joiner — raft mode freezes membership
    at bootstrap (raft-mode config propagation is covered by
    test_raft_cluster / test_raft_partition)."""

    async def t():
        srv_a, a = await start_node("a", consensus="lww")
        srv_b, b = await start_node("b", seeds=[("a", "127.0.0.1", a.port)],
                                    consensus="lww")
        await settle(0.3)

        a.update_config("mqtt.max_inflight", 64)
        await settle(0.2)
        assert srv_a.broker.config.mqtt.max_inflight == 64
        assert srv_b.broker.config.mqtt.max_inflight == 64

        # a late joiner catches up from the journal at sync time
        srv_c, c = await start_node("c", seeds=[("a", "127.0.0.1", a.port)],
                                    consensus="lww")
        await settle(0.4)
        assert srv_c.broker.config.mqtt.max_inflight == 64

        # last-writer-wins across concurrent origins
        b.update_config("mqtt.max_inflight", 48)
        await settle(0.3)
        assert srv_a.broker.config.mqtt.max_inflight == 48
        assert srv_c.broker.config.mqtt.max_inflight == 48

        await stop_node(srv_c, c)
        await stop_node(srv_b, b)
        await stop_node(srv_a, a)

    run(t())


def test_session_survives_node_death_via_replication():
    """DS replication (simplified emqx_ds_builtin_raft): a persistent
    session's checkpoint and queued messages survive the death of the
    node that owned them — the client resumes on the buddy."""

    async def t():
        # lww pinned: buddy replication is the NON-raft DS path (raft
        # mode's quorum store is covered by test_raft_cluster)
        srv_a, a = await start_node("a", consensus="lww")
        srv_b, b = await start_node("b", seeds=[("a", "127.0.0.1", a.port)],
                                    consensus="lww")
        await settle(0.3)

        c = TestClient(srv_a.listeners[0].port, "phoenix")
        await c.connect(
            clean_start=False,
            properties={"session_expiry_interval": 3600},
        )
        await c.subscribe("ash/#", qos=1)
        await c.disconnect()
        await settle(0.2)
        # the checkpoint was replicated to B (the only peer)
        assert b.replicas.info()["checkpoints"] == 1

        # messages published while detached queue on A AND replicate
        pub = TestClient(srv_b.listeners[0].port, "p")
        await pub.connect()
        await pub.publish("ash/1", b"rise", qos=1)
        await pub.disconnect()
        await settle(0.3)
        assert b.replicas.info()["buffered_messages"] >= 1

        # node A dies hard
        await stop_node(srv_a, a)
        await settle(0.5)  # B declares A down

        # the client lands on B: session restored from the replica
        c2 = TestClient(srv_b.listeners[0].port, "phoenix")
        ack = await c2.connect(
            clean_start=False,
            properties={"session_expiry_interval": 3600},
        )
        assert ack.session_present
        pkt = await c2.recv_publish()
        assert pkt.topic == "ash/1" and pkt.payload == b"rise"
        assert srv_b.broker.metrics.val("session.replica_restored") == 1

        # subscriptions came back too: new publishes deliver live
        pub2 = TestClient(srv_b.listeners[0].port, "p2")
        await pub2.connect()
        await pub2.publish("ash/2", b"again", qos=1)
        assert (await c2.recv_publish()).payload == b"again"
        await pub2.disconnect()
        await c2.disconnect()
        await stop_node(srv_b, b)

    run(t())


def test_replica_dropped_when_client_returns_to_owner():
    """A live reconnect on the owner invalidates the buddy's replica
    (the cadd registry op), preventing a later stale double-restore."""

    async def t():
        # lww pinned: replica-drop-on-cadd is the NON-raft DS path
        srv_a, a = await start_node("a", consensus="lww")
        srv_b, b = await start_node("b", seeds=[("a", "127.0.0.1", a.port)],
                                    consensus="lww")
        await settle(0.3)
        c = TestClient(srv_a.listeners[0].port, "rt")
        await c.connect(
            clean_start=False,
            properties={"session_expiry_interval": 600},
        )
        await c.subscribe("rt/#", qos=1)
        await c.disconnect()
        await settle(0.2)
        assert b.replicas.info()["checkpoints"] == 1
        # reconnect on A: the cadd op reaches B and clears the replica
        c2 = TestClient(srv_a.listeners[0].port, "rt")
        ack = await c2.connect(
            clean_start=False,
            properties={"session_expiry_interval": 600},
        )
        assert ack.session_present
        await settle(0.2)
        assert b.replicas.info()["checkpoints"] == 0
        await c2.disconnect()
        await stop_node(srv_b, b)
        await stop_node(srv_a, a)

    run(t())
