"""Auth backends: pbkdf2 hashing, JWT (HS256), async HTTP authn
(emqx_auth_jwt / emqx_auth_http / authn hash options parity)."""

import asyncio
import time

from aiohttp import web

from emqx_tpu.auth_providers import (
    HttpAuthenticator,
    JwtAuthenticator,
    Pbkdf2Authenticator,
    make_jwt,
)
from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


def make_server():
    cfg = BrokerConfig()
    cfg.listeners = [ListenerConfig(port=0)]
    cfg.auth.allow_anonymous = False
    return BrokerServer(cfg)


def test_pbkdf2_over_socket():
    async def t():
        srv = make_server()
        auth = Pbkdf2Authenticator(iterations=1000)
        auth.add_user("bob", "hunter2")
        srv.broker.access.authenticators.append(auth)
        await srv.start()
        port = srv.listeners[0].port

        ok = TestClient(port, "c1")
        ack = await ok.connect(username="bob", password=b"hunter2")
        assert ack.reason_code == 0
        await ok.disconnect()

        bad = TestClient(port, "c2")
        ack2 = await bad.connect(username="bob", password=b"wrong")
        assert ack2.reason_code != 0
        await bad.close()
        await srv.stop()

    run(t())


def test_jwt_claims_and_expiry():
    async def t():
        srv = make_server()
        secret = b"tpu-secret"
        srv.broker.access.authenticators.append(
            JwtAuthenticator(secret, required_claims={"sub": "%c"})
        )
        await srv.start()
        port = srv.listeners[0].port

        good = make_jwt(
            secret, {"sub": "dev1", "exp": time.time() + 60}
        )
        c = TestClient(port, "dev1")
        ack = await c.connect(username="ignored", password=good.encode())
        assert ack.reason_code == 0
        await c.disconnect()

        # claim mismatch: token minted for another clientid
        c2 = TestClient(port, "dev2")
        ack2 = await c2.connect(username="x", password=good.encode())
        assert ack2.reason_code != 0
        await c2.close()

        # expired token
        old = make_jwt(secret, {"sub": "dev3", "exp": time.time() - 60})
        c3 = TestClient(port, "dev3")
        ack3 = await c3.connect(username="x", password=old.encode())
        assert ack3.reason_code != 0
        await c3.close()

        # garbage signature
        forged = good[:-4] + "AAAA"
        c4 = TestClient(port, "dev1")
        ack4 = await c4.connect(username="x", password=forged.encode())
        assert ack4.reason_code != 0
        await c4.close()
        await srv.stop()

    run(t())


def test_http_authenticator_async_path():
    async def t():
        calls = []

        async def handle(request):
            body = await request.json()
            calls.append(body)
            if body["username"] == "alice" and body["password"] == "pw":
                return web.json_response(
                    {"result": "allow", "is_superuser": True}
                )
            return web.json_response({"result": "deny"})

        app = web.Application()
        app.router.add_post("/auth", handle)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        auth_port = site._server.sockets[0].getsockname()[1]

        srv = make_server()
        http_auth = HttpAuthenticator(
            f"http://127.0.0.1:{auth_port}/auth"
        )
        srv.broker.access.authenticators.append(http_auth)
        await srv.start()
        port = srv.listeners[0].port

        c = TestClient(port, "web1")
        ack = await c.connect(username="alice", password=b"pw")
        assert ack.reason_code == 0
        assert calls and calls[0]["clientid"] == "web1"
        await c.disconnect()

        c2 = TestClient(port, "web2")
        ack2 = await c2.connect(username="eve", password=b"x")
        assert ack2.reason_code != 0
        await c2.close()

        await http_auth.close()
        await srv.stop()
        await runner.cleanup()

    run(t())
