"""Unit tests: mqueue, inflight, session QoS machines, hooks, access,
retainer, shared-sub strategies, router, connection manager."""

import time

import pytest

from emqx_tpu.access import (
    ALLOW,
    DENY,
    AccessControl,
    AclProvider,
    AclRule,
    ClientInfo,
    DictAuthenticator,
    PUBLISH,
    SUBSCRIBE,
)
from emqx_tpu.broker.cm import ConnectionManager
from emqx_tpu.broker.inflight import Inflight
from emqx_tpu.broker.mqueue import MQueue
from emqx_tpu.broker.session import Session, SubOpts
from emqx_tpu.broker.shared import SharedSubManager
from emqx_tpu.codec import mqtt as C
from emqx_tpu.hooks import HookRegistry, STOP, STOP_WITH
from emqx_tpu.message import Message
from emqx_tpu.retainer import Retainer
from emqx_tpu.router import Router


# ---------------------------------------------------------------- mqueue


def test_mqueue_bounded_drop_oldest():
    q = MQueue(max_len=3)
    for i in range(3):
        assert q.insert(Message(topic=f"t{i}", qos=1)) is None
    dropped = q.insert(Message(topic="t3", qos=1))
    assert dropped is not None and dropped.topic == "t0"
    assert q.dropped == 1
    assert [m.topic for m in q] == ["t1", "t2", "t3"]


def test_mqueue_priorities():
    q = MQueue(max_len=10, priorities={"hi": 5})
    q.insert(Message(topic="lo1", qos=1))
    q.insert(Message(topic="hi", qos=1))
    q.insert(Message(topic="lo2", qos=1))
    assert q.pop().topic == "hi"
    assert q.pop().topic == "lo1"


def test_mqueue_qos0_bypass():
    q = MQueue(max_len=10, store_qos0=False)
    m = Message(topic="a", qos=0)
    assert q.insert(m) is m
    assert len(q) == 0


# --------------------------------------------------------------- inflight


def test_inflight_window():
    w = Inflight(max_size=2)
    w.insert(1, "a")
    w.insert(2, "b")
    assert w.is_full()
    with pytest.raises(KeyError):
        w.insert(1, "dup")
    assert w.delete(1) == "a"
    assert not w.is_full()
    assert [k for k, _ in w.items()] == [2]


# ---------------------------------------------------------------- session


def _mk_session(**kw):
    kw.setdefault("max_inflight", 2)
    kw.setdefault("max_mqueue_len", 10)
    return Session("c1", **kw)


def test_session_qos0_direct():
    s = _mk_session()
    out = s.deliver([(Message(topic="a", qos=0), SubOpts(qos=0))])
    assert len(out) == 1 and out[0].qos == 0 and out[0].packet_id is None


def test_session_qos1_flow():
    s = _mk_session()
    out = s.deliver([(Message(topic="a", qos=1), SubOpts(qos=1))])
    pid = out[0].packet_id
    assert out[0].qos == 1 and pid is not None
    ok, more = s.puback(pid)
    assert ok and more == []
    # unknown pid is rejected
    ok, _ = s.puback(99)
    assert not ok


def test_session_window_overflow_queues():
    s = _mk_session()
    msgs = [(Message(topic=f"t{i}", qos=1), SubOpts(qos=1)) for i in range(4)]
    out = s.deliver(msgs)
    assert len(out) == 2 and len(s.mqueue) == 2
    ok, more = s.puback(out[0].packet_id)
    assert ok and len(more) == 1  # dequeued into the freed slot
    assert more[0].topic == "t2"


def test_session_qos2_out_flow():
    s = _mk_session()
    out = s.deliver([(Message(topic="a", qos=2), SubOpts(qos=2))])
    pid = out[0].packet_id
    ok, pubrels = s.pubrec(pid)
    assert ok and isinstance(pubrels[0], C.Pubrel)
    # duplicate PUBREC is rejected in PUBREL phase
    ok2, _ = s.pubrec(pid)
    assert not ok2
    ok3, _ = s.pubcomp(pid)
    assert ok3 and len(s.inflight) == 0


def test_session_qos2_in_dedup():
    s = _mk_session(max_awaiting_rel=2)
    assert s.awaiting_rel_add(10) == "ok"
    assert s.awaiting_rel_add(10) == "in_use"
    assert s.awaiting_rel_add(11) == "ok"
    assert s.awaiting_rel_add(12) == "full"
    assert s.pubrel(10)
    assert not s.pubrel(10)


def test_session_effective_qos_and_no_local():
    s = _mk_session()
    out = s.deliver([(Message(topic="a", qos=2), SubOpts(qos=1))])
    assert out[0].qos == 1  # min(msg, sub)
    out = s.deliver(
        [(Message(topic="a", qos=0, from_client="c1"), SubOpts(no_local=True))]
    )
    assert out == []


def test_session_retry_redelivers_dup():
    s = _mk_session(retry_interval=0.0)
    out = s.deliver([(Message(topic="a", qos=1), SubOpts(qos=1))])
    pid = out[0].packet_id
    again = s.retry(now=time.time() + 1)
    assert len(again) == 1 and again[0].dup and again[0].packet_id == pid


def test_session_resume_replays_in_order():
    s = _mk_session()
    out = s.deliver(
        [
            (Message(topic="a", qos=1), SubOpts(qos=1)),
            (Message(topic="b", qos=2), SubOpts(qos=2)),
            (Message(topic="c", qos=1), SubOpts(qos=1)),
        ]
    )
    s.pubrec(out[1].packet_id)  # b advances to PUBREL phase
    replay = s.resume()
    # a re-published dup, b as PUBREL; c stays queued (window still full)
    assert replay[0].topic == "a" and replay[0].dup
    assert isinstance(replay[1], C.Pubrel)
    assert len(replay) == 2 and len(s.mqueue) == 1
    ok, more = s.pubcomp(out[1].packet_id)  # freeing a slot releases c
    assert ok and more[0].topic == "c"


# ------------------------------------------------------------------ hooks


def test_hooks_priority_and_stop():
    h = HookRegistry()
    calls = []
    h.add("t", lambda x: calls.append(("lo", x)), priority=0)
    h.add("t", lambda x: calls.append(("hi", x)), priority=10)
    h.run("t", 1)
    assert calls == [("hi", 1), ("lo", 1)]

    calls.clear()
    h.add("s", lambda x: STOP, priority=5)
    h.add("s", lambda x: calls.append("never"), priority=0)
    h.run("s", 1)
    assert calls == []


def test_hooks_run_fold():
    h = HookRegistry()
    h.add("f", lambda base, acc: acc + 1)
    h.add("f", lambda base, acc: None)  # pass-through
    h.add("f", lambda base, acc: acc * 2)
    assert h.run_fold("f", (0,), 3) == 8

    h2 = HookRegistry()
    h2.add("f", lambda acc: STOP_WITH("done"))
    h2.add("f", lambda acc: "never")
    assert h2.run_fold("f", (), "x") == "done"


def test_hooks_delete():
    h = HookRegistry()
    fn = lambda: None  # noqa: E731
    h.add("t", fn)
    assert h.delete("t", fn)
    assert not h.delete("t", fn)


# ----------------------------------------------------------------- access


def test_dict_authenticator():
    ac = AccessControl(allow_anonymous=False)
    auth = DictAuthenticator()
    auth.add_user("alice", "secret", is_superuser=True)
    ac.authenticators.append(auth)

    ok, ci = ac.authenticate(ClientInfo("c1", "alice", b"secret"))
    assert ok and ci.is_superuser
    ok, _ = ac.authenticate(ClientInfo("c1", "alice", b"wrong"))
    assert not ok
    # unknown user falls through to allow_anonymous=False
    ok, _ = ac.authenticate(ClientInfo("c1", "bob", b"x"))
    assert not ok
    ok, _ = ac.authenticate(ClientInfo("c1"))
    assert not ok


def test_acl_rules_placeholders_and_order():
    ac = AccessControl(authz_default=DENY)
    ac.authz_sources.append(
        AclProvider(
            [
                AclRule(DENY, "all", PUBLISH, ["forbidden/#"]),
                AclRule(ALLOW, ("username", "u1"), "all", ["dev/%u/#"]),
                AclRule(ALLOW, "all", SUBSCRIBE, ["public/+"]),
            ]
        )
    )
    u1 = ClientInfo("c1", "u1")
    assert ac.authorize(u1, PUBLISH, "dev/u1/x")
    assert not ac.authorize(u1, PUBLISH, "dev/u2/x")
    assert not ac.authorize(u1, PUBLISH, "forbidden/x")
    assert ac.authorize(u1, SUBSCRIBE, "public/a")
    assert not ac.authorize(u1, SUBSCRIBE, "private/a")  # default deny
    su = ClientInfo("c2", is_superuser=True)
    assert ac.authorize(su, PUBLISH, "forbidden/x")


def test_acl_eq_rule():
    ac = AccessControl(authz_default=DENY)
    ac.authz_sources.append(
        AclProvider([AclRule(ALLOW, "all", SUBSCRIBE, [{"eq": "a/#"}])])
    )
    ci = ClientInfo("c")
    assert ac.authorize(ci, SUBSCRIBE, "a/#")
    assert not ac.authorize(ci, SUBSCRIBE, "a/b")


# --------------------------------------------------------------- retainer


def test_retainer_store_match_delete():
    r = Retainer()
    r.store(Message(topic="a/b", payload=b"1", retain=True))
    r.store(Message(topic="a/c", payload=b"2", retain=True))
    r.store(Message(topic="x", payload=b"3", retain=True))
    assert {m.topic for m in r.match("a/+")} == {"a/b", "a/c"}
    assert {m.topic for m in r.match("#")} == {"a/b", "a/c", "x"}
    assert [m.topic for m in r.match("a/b")] == ["a/b"]
    # empty payload deletes
    r.store(Message(topic="a/b", payload=b"", retain=True))
    assert r.match("a/b") == []
    assert len(r) == 2


def test_retainer_hash_matches_parent():
    r = Retainer()
    r.store(Message(topic="a", payload=b"p", retain=True))
    r.store(Message(topic="a/b/c", payload=b"q", retain=True))
    assert {m.topic for m in r.match("a/#")} == {"a", "a/b/c"}


def test_retainer_dollar_exclusion():
    r = Retainer()
    r.store(Message(topic="$SYS/up", payload=b"1", retain=True))
    r.store(Message(topic="n", payload=b"2", retain=True))
    assert [m.topic for m in r.match("#")] == ["n"]
    assert [m.topic for m in r.match("+/up")] == []
    assert [m.topic for m in r.match("$SYS/up")] == ["$SYS/up"]
    assert [m.topic for m in r.match("$SYS/#")] == ["$SYS/up"]


def test_retainer_limits_and_expiry():
    r = Retainer(max_retained_messages=1, msg_expiry_interval=100.0)
    assert r.store(Message(topic="a", payload=b"1", retain=True))
    assert not r.store(Message(topic="b", payload=b"2", retain=True))
    # replacing an existing topic is allowed at the cap
    assert r.store(Message(topic="a", payload=b"3", retain=True))
    old = Message(topic="a", payload=b"4", retain=True)
    old.timestamp -= 1000
    r.store(old)
    assert r.match("a") == []  # expired via store-level interval


def test_retainer_message_expiry_property():
    r = Retainer()
    m = Message(
        topic="a",
        payload=b"1",
        retain=True,
        properties={"message_expiry_interval": 1},
    )
    m.timestamp -= 10
    r.store(m)
    assert r.match("a") == []


# ----------------------------------------------------------- shared subs


def _msg(topic="t", frm="pub"):
    return Message(topic=topic, from_client=frm)


def test_shared_round_robin():
    s = SharedSubManager(strategy="round_robin")
    s.join("g", "t", "a")
    s.join("g", "t", "b")
    picks = [s.pick("g", "t", _msg()) for _ in range(4)]
    assert picks == ["a", "b", "a", "b"]


def test_shared_sticky():
    s = SharedSubManager(strategy="sticky", seed=1)
    s.join("g", "t", "a")
    s.join("g", "t", "b")
    first = s.pick("g", "t", _msg())
    assert all(s.pick("g", "t", _msg()) == first for _ in range(5))
    s.leave("g", "t", first)
    nxt = s.pick("g", "t", _msg())
    assert nxt != first


def test_shared_hash_strategies():
    s = SharedSubManager(strategy="hash_clientid")
    s.join("g", "t", "a")
    s.join("g", "t", "b")
    p1 = s.pick("g", "t", _msg(frm="x"))
    assert all(s.pick("g", "t", _msg(frm="x")) == p1 for _ in range(5))
    st = SharedSubManager(strategy="hash_topic")
    st.join("g", "t", "a")
    st.join("g", "t", "b")
    q1 = st.pick("g", "t", _msg(topic="z"))
    assert all(st.pick("g", "t", _msg(topic="z")) == q1 for _ in range(5))


def test_shared_exclude_and_leave_all():
    s = SharedSubManager(strategy="random", seed=2)
    assert s.join("g", "t", "a")  # first member => route add
    assert not s.join("g", "t", "b")
    assert s.pick("g", "t", _msg(), exclude={"a"}) == "b"
    assert s.pick("g", "t", _msg(), exclude={"a", "b"}) is None
    emptied = s.leave_all("a")
    assert emptied == []
    assert s.leave_all("b") == [("g", "t")]


# ----------------------------------------------------------------- router


def test_router_subscribe_match_unsubscribe():
    r = Router()
    r.subscribe("c1", "a/+", SubOpts(qos=1))
    r.subscribe("c2", "a/b", SubOpts(qos=0))
    matched = r.match_batch(["a/b"])[0]
    assert matched == {"a/+", "a/b"}
    subs = dict(r.subscribers("a/+"))
    assert "c1" in subs
    r.unsubscribe("c1", "a/+")
    assert r.match_batch(["a/b"])[0] == {"a/b"}


def test_router_shared_and_direct_same_filter():
    r = Router()
    r.subscribe("c1", "t/x", SubOpts(qos=1))
    r.subscribe("c2", "$share/g/t/x", SubOpts(qos=1))
    assert r.match_batch(["t/x"])[0] == {"t/x"}
    assert r.shared.members("g", "t/x") == ["c2"]
    # dropping the direct sub keeps the route for the shared group
    r.unsubscribe("c1", "t/x")
    assert r.match_batch(["t/x"])[0] == {"t/x"}
    r.unsubscribe("c2", "$share/g/t/x")
    assert r.match_batch(["t/x"])[0] == set()


def test_router_cleanup_client():
    r = Router()
    r.subscribe("c1", "a/#", SubOpts())
    r.subscribe("c1", "$share/g/b", SubOpts())
    r.subscribe("c2", "a/#", SubOpts())
    r.cleanup_client("c1")
    assert r.subscriptions_of("c1") == set()
    assert r.match_batch(["b"])[0] == set()
    assert r.match_batch(["a/x"])[0] == {"a/#"}


# --------------------------------------------------------------------- cm


class FakeChannel:
    def __init__(self):
        self.sent = []
        self.closed = None

    def send_packets(self, pkts):
        self.sent.extend(pkts)

    def close(self, reason):
        self.closed = reason


def test_cm_open_resume_takeover():
    cm = ConnectionManager(lambda clientid, clean_start, **kw: Session(
        clientid, clean_start=clean_start,
        expiry_interval=kw.get("expiry_interval", 0.0)))
    ch1 = FakeChannel()
    s1, present = cm.open_session(False, "c", ch1, expiry_interval=60.0)
    assert not present
    # second connection takes over the live session
    ch2 = FakeChannel()
    s2, present = cm.open_session(False, "c", ch2)
    assert present and s2 is s1 and ch1.closed == "takenover"
    # clean start discards
    ch3 = FakeChannel()
    s3, present = cm.open_session(True, "c", ch3)
    assert not present and s3 is not s1


def test_cm_disconnect_and_expiry():
    cm = ConnectionManager(lambda clientid, clean_start, **kw: Session(
        clientid, clean_start=clean_start,
        expiry_interval=kw.get("expiry_interval", 0.0)))
    ch = FakeChannel()
    s, _ = cm.open_session(False, "c", ch, expiry_interval=0.5)
    cm.disconnect("c", ch)
    assert cm.lookup("c") is s and not cm.connected("c")
    assert cm.expire_sessions(now=time.time() + 1) == ["c"]
    assert cm.lookup("c") is None
    # zero-expiry sessions drop immediately on disconnect
    ch2 = FakeChannel()
    cm.open_session(True, "d", ch2)
    cm.disconnect("d", ch2)
    assert cm.lookup("d") is None
