"""GB/T 32960 gateway (gateway/gbt32960.py): framing/BCC, login flow,
realtime vehicle-state decoding, downlink passthrough — written from
the public GB/T 32960.3-2016 spec (the emqx_gateway_gbt32960 role)."""

import asyncio
import json
import struct

import pytest

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from emqx_tpu.gateway.gbt32960 import (
    ACK_SUCCESS,
    CMD_HEARTBEAT,
    CMD_LOGIN,
    CMD_REALTIME,
    GbtCodec,
    GbtMessage,
    decode_realtime,
)
from mqtt_client import TestClient

VIN = "LSVNV2182E2100001"


def run(coro):
    return asyncio.run(coro)


def test_gbt_codec_roundtrip_and_bcc():
    codec = GbtCodec()
    m = GbtMessage(CMD_REALTIME, 0xFE, VIN, b"\x26\x07\x31\x01\x02\x03xyz")
    wire = codec.serialize(m)
    assert wire[:2] == b"##"
    frames, rest = codec.parse(codec.initial_state(), wire)
    assert rest == b"" and len(frames) == 1
    out = frames[0]
    assert (out.cmd, out.vin) == (CMD_REALTIME, VIN)
    assert out.body.endswith(b"xyz")

    # split delivery; BCC corruption raises
    frames, state = codec.parse(codec.initial_state(), wire[:10])
    assert frames == []
    frames, _ = codec.parse(state, wire[10:])
    assert len(frames) == 1
    bad = bytearray(wire)
    bad[-1] ^= 0xFF
    with pytest.raises(ValueError):
        codec.parse(codec.initial_state(), bytes(bad))


def test_gbt_realtime_decode():
    body = bytes.fromhex("260731102530")  # time
    body += bytes([0x01]) + struct.pack(
        ">BBBHIHHBBBH",
        1, 1, 1,          # started, charging, electric
        605,              # speed x0.1
        123456,           # mileage x0.1
        3501,             # voltage x0.1
        10250,            # current offset 1000A x0.1
        87,               # soc
        1, 0x1D,          # dcdc, gear (drive + flags)
        5000,             # insulation
    )
    out = decode_realtime(body)
    assert out["time"] == "2026-07-31 10:25:30"
    info = out["infos"][0]
    assert info["type"] == "vehicle_state"
    assert info["speed_kmh"] == 60.5
    assert info["mileage_km"] == 12345.6
    assert info["current_a"] == 25.0
    assert info["soc_pct"] == 87 and info["gear"] == 13


class EvTerminal:
    def __init__(self, port):
        self.port = port
        self.codec = GbtCodec()
        self.state = b""

    async def connect(self):
        self.r, self.w = await asyncio.open_connection(
            "127.0.0.1", self.port
        )
        return self

    def send(self, cmd, body=b"", ack=0xFE):
        self.w.write(self.codec.serialize(
            GbtMessage(cmd, ack, VIN, body)
        ))

    async def recv(self, timeout=3.0):
        while True:
            frames, self.state = self.codec.parse(
                self.state,
                await asyncio.wait_for(self.r.read(4096), timeout),
            )
            if frames:
                return frames[0]

    def close(self):
        self.w.close()


def test_gbt_login_realtime_downlink():
    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.gateways = [
            {"type": "gbt32960", "bind": "127.0.0.1", "port": 0}
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        gw = srv.broker.gateways.get("gbt32960")

        app = TestClient(srv.listeners[0].port, "ev-app")
        await app.connect()
        await app.subscribe("gbt32960/+/up", qos=1)

        ev = await EvTerminal(gw.port).connect()

        # data before login is refused
        ev.send(CMD_HEARTBEAT)
        ack = await ev.recv()
        assert ack.ack == 0x02

        # login: time + serial + iccid
        login = (bytes.fromhex("260731090000")
                 + struct.pack(">H", 3)
                 + b"89860000000000000001")
        ev.send(CMD_LOGIN, login)
        ack = await ev.recv()
        assert ack.cmd == CMD_LOGIN and ack.ack == ACK_SUCCESS
        up = json.loads((await app.recv_publish()).payload)
        assert up["type"] == "login" and up["serial"] == 3
        assert up["iccid"].startswith("8986")

        # realtime frame decodes to the up topic
        body = bytes.fromhex("260731091500") + bytes([0x01]) + \
            struct.pack(">BBBHIHHBBBH",
                        1, 3, 1, 420, 100, 3400, 10000, 64, 1, 14,
                        800)
        ev.send(CMD_REALTIME, body)
        ack = await ev.recv()
        assert ack.ack == ACK_SUCCESS
        up = json.loads((await app.recv_publish()).payload)
        assert up["type"] == "realtime"
        assert up["infos"][0]["speed_kmh"] == 42.0
        assert up["infos"][0]["soc_pct"] == 64

        # downlink command passthrough
        await app.publish(f"gbt32960/{VIN}/dn", json.dumps({
            "cmd": 0x80, "body_hex": "2607310916000101",
        }).encode(), qos=1)
        dn = await ev.recv()
        assert dn.cmd == 0x80 and dn.body == bytes.fromhex(
            "2607310916000101"
        )

        ev.close()
        await app.disconnect()
        await srv.stop()

    run(t())
