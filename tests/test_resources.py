"""Resource layer: rule -> buffered HTTP sink with injected failures —
no loss within buffer bounds (emqx_resource_buffer_worker semantics)."""

import asyncio
import json

from aiohttp import web

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from emqx_tpu.resources import CONNECTED, DISCONNECTED, HttpSink, Resource
from emqx_tpu.rules.engine import SinkAction
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


class FlakyServer:
    """Local HTTP server that fails the first `fail_first` POSTs."""

    def __init__(self, fail_first: int = 0):
        self.fail_first = fail_first
        self.requests = 0
        self.bodies = []
        self.port = None
        self._runner = None

    async def start(self):
        app = web.Application()

        async def handle(request):
            self.requests += 1
            if self.requests <= self.fail_first:
                return web.Response(status=503)
            self.bodies.append(await request.text())
            return web.Response(status=200)

        async def head(request):
            return web.Response(status=200)

        app.router.add_post("/ingest", handle)
        app.router.add_head("/ingest", head)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]

    async def stop(self):
        await self._runner.cleanup()


def test_rule_to_http_sink_with_failures():
    async def t():
        http = FlakyServer(fail_first=3)
        await http.start()

        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        srv = BrokerServer(cfg)
        await srv.start()
        broker = srv.broker
        await broker.resources.create(
            "wh1",
            HttpSink(f"http://127.0.0.1:{http.port}/ingest"),
            retry_base=0.01,
        )
        broker.rules.add_rule(
            "fwd",
            'SELECT payload.v AS v, topic FROM "ing/#" WHERE payload.v > 0',
            actions=[SinkAction(resource_id="wh1")],
        )

        pub = TestClient(srv.listeners[0].port, "p")
        await pub.connect()
        for v in range(1, 6):
            await pub.publish("ing/a", json.dumps({"v": v}).encode(), qos=1)
        await pub.disconnect()

        # the first 3 POSTs fail; retries must deliver ALL 5 in order
        for _ in range(200):
            if len(http.bodies) == 5:
                break
            await asyncio.sleep(0.02)
        assert [json.loads(b)["v"] for b in http.bodies] == [1, 2, 3, 4, 5]
        worker = broker.resources.get("wh1")
        assert worker.stats["success"] == 5
        assert worker.stats["retried"] >= 3
        assert worker.stats["dropped"] == 0
        assert worker.status == CONNECTED
        assert broker.resources.info()["wh1"]["buffered"] == 0

        await srv.stop()
        await http.stop()

    run(t())


def test_buffer_bound_drops_oldest():
    class Black(Resource):
        async def on_query(self, q):
            raise RuntimeError("down")

        async def health_check(self):
            return False

    async def t():
        from emqx_tpu.resources import BufferWorker

        w = BufferWorker(Black(), max_buffer=3, retry_base=0.01)
        await w.start()
        for i in range(5):
            w.enqueue(f"q{i}")
        assert len(w) == 3
        assert w.stats["dropped"] == 2
        assert list(w._buf) == ["q2", "q3", "q4"]
        await asyncio.sleep(0.05)
        assert w.status == DISCONNECTED
        await w.stop()

    run(t())


def test_sink_payload_template():
    async def t():
        http = FlakyServer()
        await http.start()
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        srv = BrokerServer(cfg)
        await srv.start()
        await srv.broker.resources.create(
            "wh2", HttpSink(f"http://127.0.0.1:{http.port}/ingest")
        )
        srv.broker.rules.add_rule(
            "fmt",
            'SELECT payload.name AS name FROM "fmt/#"',
            actions=[
                SinkAction(resource_id="wh2", payload="hello ${name}")
            ],
        )
        pub = TestClient(srv.listeners[0].port, "p2")
        await pub.connect()
        await pub.publish("fmt/x", b'{"name": "ada"}', qos=1)
        await pub.disconnect()
        for _ in range(100):
            if http.bodies:
                break
            await asyncio.sleep(0.02)
        assert http.bodies == ["hello ada"]
        await srv.stop()
        await http.stop()

    run(t())
