"""Deterministic race reproduction via tracepoints (SURVEY §5.2 — the
snabbkaffe ?tp / ?force_ordering role): pin the async-fold adoption
into exact windows of a concurrent match and assert oracle equality,
instead of hoping a wall-clock stress test hits the interleaving."""

import random
import threading

from emqx_tpu import topic as T
from emqx_tpu import tp
from emqx_tpu.engine import MatchEngine
from emqx_tpu.ops.trie_host import HostTrie


def build_engine(n=400, threshold=64):
    eng = MatchEngine(
        max_levels=8, rebuild_threshold=10**9,
        delta_aut_threshold=threshold,
        # pinned: these tests force interleavings on the DEVICE match
        # path (snapshot/overlay vs fold adoption); auto would route
        # the small windows to the host and never reach them
        use_device=True,
    )
    oracle = HostTrie()
    for i in range(n):
        eng.insert(f"seed/{i % 23}/+/s{i}", i)
        oracle.insert(f"seed/{i % 23}/+/s{i}", i)
    eng.rebuild()
    return eng, oracle


def oracle_check(eng, oracle, topics):
    got = eng.match_batch(topics)
    for t, g in zip(topics, got):
        want = oracle.match_words(T.words(t))
        assert g == want, (t, sorted(map(str, g)), sorted(map(str, want)))


def churn(eng, oracle, start, count):
    for i in range(start, start + count):
        eng.insert(f"churn/{i % 97}/+/c{i}", i)
        oracle.insert(f"churn/{i % 97}/+/c{i}", i)


from tests_fakes import drain_folds  # noqa: E402  (shared drain util)


def test_fold_adopts_inside_match_window():
    """The adoption is forced to land between a match's snapshot and
    its overlay — the exact interleaving where a count-based residual
    skip-check once dropped filters folded mid-batch."""
    eng, oracle = build_engine()
    churn(eng, oracle, 1000, 200)  # enough residual to trigger a fold
    drain_folds(eng)
    topics = [f"churn/{i % 97}/x/y" for i in range(60)] + [
        f"seed/{i % 23}/q/r" for i in range(40)
    ]
    with tp.collect() as trace, tp.force_ordering(
        after="match_overlay", block="fold_adopt"
    ):
        # the fold assembles concurrently but may only adopt once the
        # match below has passed its overlay tracepoint.  Churn until a
        # fold actually captures: the geometric threshold depends on
        # where the previous fold's watermark landed.
        for round_ in range(50):
            if tp.events_of(trace, "fold_capture"):
                break
            churn(eng, oracle, 2000 + round_ * 100, 100)
        else:
            raise AssertionError("fold never captured")
        oracle_check(eng, oracle, topics)
        drain_folds(eng)
    tp.assert_present(trace, "fold_commit")
    tp.assert_order(trace, "match_overlay", "fold_commit")
    # and matches AFTER adoption are equally correct
    oracle_check(eng, oracle, topics)


def test_fold_adopts_before_overlay_of_older_snapshot():
    """Mirror image: a match snapshots, the fold adopts, THEN the
    match overlays against its (older) snapshot — entries between the
    two watermarks must come from the residual view, not be lost."""
    eng, oracle = build_engine()
    churn(eng, oracle, 1000, 200)
    drain_folds(eng)
    topics = [f"churn/{i % 97}/x/y" for i in range(60)]

    adopted = threading.Event()

    def matcher():
        oracle_check(eng, oracle, topics)

    with tp.collect() as trace:
        with tp.force_ordering(after="match_snapshot", block="fold_adopt"):
            with tp.force_ordering(after="fold_commit", block="match_overlay"):
                t = threading.Thread(target=matcher)
                for round_ in range(50):
                    if tp.events_of(trace, "fold_capture"):
                        break
                    churn(eng, oracle, 2000 + round_ * 100, 100)
                else:
                    raise AssertionError("fold never captured")
                t.start()
                t.join(30)
                assert not t.is_alive()
        drain_folds(eng)
    tp.assert_present(trace, "fold_commit")
    tp.assert_order(trace, "match_snapshot", "fold_commit")
    tp.assert_order(trace, "fold_commit", "match_overlay")
    oracle_check(eng, oracle, topics)


def test_base_swap_discards_inflight_fold():
    """A base rebuild swapping mid-fold must discard the fold (its
    inputs predate the new base), and matching stays oracle-equal."""
    eng, oracle = build_engine()
    eng.background_rebuild = True
    eng.rebuild_threshold = 250
    topics = [f"churn/{i % 97}/x/y" for i in range(60)]
    with tp.collect() as trace:
        with tp.force_ordering(after="daut_drop", block="fold_assemble_done"):
            # cross BOTH thresholds: a fold starts, then the base
            # rebuild (threshold 250) starts and swaps while the fold
            # is pinned pre-adoption
            churn(eng, oracle, 3000, 400)
            import time
            deadline = time.time() + 15
            while time.time() < deadline and not tp.events_of(
                trace, "daut_drop"
            ):
                eng.match_batch(["churn/1/x/y"])  # polls the swap
                time.sleep(0.02)
        drain_folds(eng)
    tp.assert_present(trace, "daut_drop")
    tp.assert_present(trace, "fold_discard")
    tp.assert_absent(
        trace, "fold_commit",
        gen=tp.assert_present(trace, "fold_discard")["gen"],
    )
    oracle_check(eng, oracle, topics)


def test_fold_failure_injection_keeps_matching():
    """An injected crash in the fold thread must leave matching on the
    residual overlay, oracle-equal, and a later fold recovers."""
    eng, oracle = build_engine()
    topics = [f"churn/{i % 97}/x/y" for i in range(60)]
    with tp.collect() as trace:
        with tp.inject("fold_assemble_done", RuntimeError("injected")):
            churn(eng, oracle, 1000, 200)
            drain_folds(eng)
            oracle_check(eng, oracle, topics)
        # next fold (no injection) recovers the device tier
        churn(eng, oracle, 5000, 200)
        drain_folds(eng)
    assert eng._daut is not None
    oracle_check(eng, oracle, topics)
