"""DS streaming reads (VERDICT r3 missing #2): beamformer grouped
long-poll — many coherent readers parked on iterators wake together
from one store sweep — and durable shared subscriptions: a $share
group's offline interval replays exactly once ACROSS the group's
persistent members, surviving a broker restart
(emqx_ds_beamformer.erl:16-60, emqx_ds_shared_sub)."""

import asyncio

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from emqx_tpu.ds.persist import DurableSessions
from emqx_tpu.message import Message
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


def make_server(data_dir):
    cfg = BrokerConfig()
    cfg.listeners = [ListenerConfig(port=0)]
    cfg.durable.enable = True
    cfg.durable.data_dir = str(data_dir)
    return BrokerServer(cfg)


def test_poll_returns_existing_then_parks(tmp_path):
    async def t():
        ds = DurableSessions(str(tmp_path / "ds"))
        ds.add_filter("tele/#")
        ds.persist([Message(topic="tele/a", payload=b"0", qos=1)])
        streams = ds.storage.get_streams("tele/#")
        assert streams
        it = ds.storage.make_iterator(streams[0], "tele/#")

        # existing data returns immediately
        it, msgs = await ds.beamformer.poll(it, timeout=1.0)
        assert [m.payload for m in msgs] == [b"0"]

        # nothing new: a short poll times out empty
        it2, msgs = await ds.beamformer.poll(it, timeout=0.2)
        assert msgs == []

        # parked poll wakes on a store
        async def later():
            await asyncio.sleep(0.2)
            ds.persist([Message(topic="tele/a", payload=b"1", qos=1)])  # same stream (2-level prefix hash)

        task = asyncio.get_running_loop().create_task(later())
        it3, msgs = await ds.beamformer.poll(it2, timeout=5.0)
        assert [m.payload for m in msgs] == [b"1"]
        await task
        ds.close()

    run(t())


def test_many_coherent_readers_one_beam(tmp_path):
    """N readers parked on the same stream are served by ONE beam from
    one store sweep (the beamformer's whole reason to exist)."""

    async def t():
        ds = DurableSessions(str(tmp_path / "ds"))
        ds.add_filter("tele/#")
        ds.persist([Message(topic="tele/seed", payload=b"s", qos=1)])
        stream = ds.storage.get_streams("tele/#")[0]

        n = 20
        its = []
        for _ in range(n):
            it = ds.storage.make_iterator(stream, "tele/#")
            it, msgs = await ds.beamformer.poll(it, timeout=0.5)
            assert len(msgs) == 1  # drain the seed
            its.append(it)

        polls = [
            asyncio.get_running_loop().create_task(
                ds.beamformer.poll(it, timeout=10.0)
            )
            for it in its
        ]
        await asyncio.sleep(0.2)  # all parked
        assert ds.beamformer.info()["parked_now"] == n
        ds.persist([Message(topic="tele/seed", payload=b"beam", qos=1)])  # same stream
        results = await asyncio.gather(*polls)
        assert all(
            [m.payload for m in msgs] == [b"beam"]
            for _, msgs in results
        )
        info = ds.beamformer.info()
        assert info["beams"] == 1  # ONE sweep woke all n readers
        assert info["woken"] == n
        ds.close()

    run(t())


def test_durable_shared_group_survives_restart(tmp_path):
    """Two persistent members of $share/g/jobs/# go offline; the
    broker restarts; publishes land while everyone is away; on
    reconnect each message is delivered to EXACTLY ONE member."""

    async def t():
        srv1 = make_server(tmp_path / "ds")
        await srv1.start()
        port = srv1.listeners[0].port

        members = ["w1", "w2"]
        for cid in members:
            c = TestClient(port, cid)
            await c.connect(
                clean_start=False,
                properties={"session_expiry_interval": 3600},
            )
            await c.subscribe("$share/g/jobs/#", qos=1)
            await c.disconnect()

        pub = TestClient(port, "ctl")
        await pub.connect()
        # spread across many second-level topics => many streams
        for i in range(40):
            await pub.publish(f"jobs/q{i}/t", str(i).encode(), qos=1)
        await pub.disconnect()

        await srv1.stop()
        srv1.broker.durable.close()

        srv2 = make_server(tmp_path / "ds")
        await srv2.start()
        port2 = srv2.listeners[0].port

        got = {}
        for cid in members:
            c = TestClient(port2, cid)
            await c.connect(clean_start=False)
            while True:
                try:
                    m = await c.recv_publish(timeout=1.0)
                except asyncio.TimeoutError:
                    break
                got.setdefault(int(m.payload), []).append(cid)
            await c.close()

        # exactly-once across the group: every message delivered, none
        # twice
        assert sorted(got) == list(range(40)), sorted(got)
        dupes = {k: v for k, v in got.items() if len(v) > 1}
        assert not dupes, dupes
        # and the work actually split (both members got a share)
        loads = {
            cid: sum(1 for v in got.values() if v == [cid])
            for cid in members
        }
        assert all(loads[cid] > 0 for cid in members), loads
        await srv2.stop()
        srv2.broker.durable.close()

    run(t())
