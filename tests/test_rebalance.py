"""Node evacuation: bounded-rate eviction with cross-node session
migration (emqx_node_rebalance / emqx_eviction_agent parity)."""

import asyncio
import tempfile

# auto-cleaned parent for per-test mgmt stores (finalized at interpreter exit)
_MGMT_TMP = tempfile.TemporaryDirectory(prefix="emqx-mgmt-")

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.cluster import ClusterNode
from emqx_tpu.codec import mqtt as C
from emqx_tpu.config import BrokerConfig, ListenerConfig
from mqtt_client import TestClient

FAST = dict(heartbeat_interval=0.05, down_after=0.25, flush_interval=0.002)


def run(coro):
    return asyncio.run(coro)


def test_evacuation_drains_and_signals_clients():
    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.api.enable = True
        cfg.api.data_dir = tempfile.mkdtemp(dir=_MGMT_TMP.name)
        cfg.api.port = 0
        srv = BrokerServer(cfg)
        await srv.start()
        port = srv.listeners[0].port

        clients = [TestClient(port, f"ev-{i}") for i in range(6)]
        for c in clients:
            await c.connect(
                clean_start=False,
                properties={"session_expiry_interval": 600},
            )
        await srv.broker.eviction.start_evacuation(conn_evict_rate=100)
        # v5 clients get USE_ANOTHER_SERVER before the close
        pkt = await clients[0].recv(timeout=3)
        assert pkt is not None and pkt.type == C.DISCONNECT
        assert pkt.reason_code == 0x9C
        for _ in range(100):
            if srv.broker.eviction.info()["status"] == "evacuated":
                break
            await asyncio.sleep(0.05)
        info = srv.broker.eviction.info()
        assert info["status"] == "evacuated" and info["evicted"] == 6
        # persistent sessions survive detached (takeover-able)
        assert srv.broker.cm.lookup("ev-0") is not None
        assert not srv.broker.cm.connected("ev-0")
        for c in clients:
            await c.close()
        await srv.stop()

    run(t())


def test_plan_rebalance_donors_and_recipients():
    from emqx_tpu.rebalance import plan_rebalance

    plan = plan_rebalance({"a": 90, "b": 10, "c": 20})
    assert plan["avg"] == 40
    assert plan["donors"] == {"a": 50}
    assert plan["recipients"] == ["b", "c"]
    # balanced cluster -> no donors
    assert plan_rebalance({"a": 10, "b": 10})["donors"] == {}
    assert plan_rebalance({})["donors"] == {}
    # threshold guards small skews
    assert plan_rebalance({"a": 11, "b": 10}, threshold=1.2)["donors"] == {}


def test_cluster_rebalance_sheds_overloaded_node():
    async def t():
        async def start_node(name, seeds=()):
            cfg = BrokerConfig()
            cfg.listeners = [ListenerConfig(port=0)]
            srv = BrokerServer(cfg)
            await srv.start()
            node = ClusterNode(name, srv.broker, **FAST)
            await node.start(seeds=list(seeds))
            return srv, node

        srv_a, a = await start_node("a")
        srv_b, b = await start_node("b", seeds=[("a", "127.0.0.1", a.port)])
        await asyncio.sleep(0.3)

        # 8 connections on A, none on B: A is the donor
        clients = [TestClient(srv_a.listeners[0].port, f"rb-{i}")
                   for i in range(8)]
        for c in clients:
            await c.connect()

        plan = await srv_a.broker.rebalance.start(
            conn_evict_rate=100, rel_conn_threshold=1.05
        )
        assert plan["donors"].get("a", 0) >= 3  # shed down toward avg=4
        assert "b" in plan["recipients"]

        for _ in range(100):
            info = srv_a.broker.rebalance.info()
            if info["status"] == "balanced":
                break
            await asyncio.sleep(0.05)
        live = sum(1 for c in srv_a.broker.cm.clients()
                   if srv_a.broker.cm.connected(c))
        assert live <= 8 - plan["donors"]["a"]

        for c in clients:
            await c.close()
        await b.stop()
        await srv_b.stop()
        await a.stop()
        await srv_a.stop()

    run(t())


def test_rebalance_remote_donor_shed_via_cast():
    """The coordinator on a balanced node still drives a remote donor."""

    async def t():
        async def start_node(name, seeds=()):
            cfg = BrokerConfig()
            cfg.listeners = [ListenerConfig(port=0)]
            srv = BrokerServer(cfg)
            await srv.start()
            node = ClusterNode(name, srv.broker, **FAST)
            await node.start(seeds=list(seeds))
            return srv, node

        srv_a, a = await start_node("a")
        srv_b, b = await start_node("b", seeds=[("a", "127.0.0.1", a.port)])
        await asyncio.sleep(0.3)

        clients = [TestClient(srv_a.listeners[0].port, f"rr-{i}")
                   for i in range(6)]
        for c in clients:
            await c.connect()

        # start from B (a recipient): it must tell A to shed remotely
        plan = await srv_b.broker.rebalance.start(
            conn_evict_rate=100, rel_conn_threshold=1.05
        )
        assert plan["donors"].get("a", 0) >= 2

        for _ in range(100):
            live = sum(1 for c in srv_a.broker.cm.clients()
                       if srv_a.broker.cm.connected(c))
            if live <= 6 - plan["donors"]["a"]:
                break
            await asyncio.sleep(0.05)
        live = sum(1 for c in srv_a.broker.cm.clients()
                   if srv_a.broker.cm.connected(c))
        assert live <= 6 - plan["donors"]["a"]

        for c in clients:
            await c.close()
        await b.stop()
        await srv_b.stop()
        await a.stop()
        await srv_a.stop()

    run(t())


def test_purge_drops_detached_sessions_only():
    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        srv = BrokerServer(cfg)
        await srv.start()
        port = srv.listeners[0].port

        # three persistent sessions; two go detached, one stays live
        clients = [TestClient(port, f"pg-{i}") for i in range(3)]
        for c in clients:
            await c.connect(
                clean_start=False,
                properties={"session_expiry_interval": 600},
            )
        await clients[0].disconnect()
        await clients[1].disconnect()
        await asyncio.sleep(0.05)
        assert not srv.broker.cm.connected("pg-0")
        assert srv.broker.cm.lookup("pg-0") is not None

        await srv.broker.purger.start_purge(purge_rate=100)
        for _ in range(100):
            if srv.broker.purger.info()["status"] == "purged":
                break
            await asyncio.sleep(0.05)
        info = srv.broker.purger.info()
        assert info["status"] == "purged" and info["purged"] == 2
        assert srv.broker.cm.lookup("pg-0") is None
        assert srv.broker.cm.lookup("pg-1") is None
        # the live client is untouched
        assert srv.broker.cm.connected("pg-2")
        await clients[2].disconnect()
        for c in clients:
            await c.close()
        await srv.stop()

    run(t())


def test_purge_refused_while_evacuating():
    async def t():
        import pytest

        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        srv = BrokerServer(cfg)
        await srv.start()
        c = TestClient(srv.listeners[0].port, "busy")
        await c.connect(
            clean_start=False,
            properties={"session_expiry_interval": 600},
        )
        await srv.broker.eviction.start_evacuation(conn_evict_rate=1)
        with pytest.raises(RuntimeError):
            await srv.broker.purger.start_purge()
        await srv.broker.eviction.stop_evacuation()
        await c.close()
        await srv.stop()

    run(t())


def test_eviction_refused_while_purging():
    """The exclusion is bidirectional: a running purge blocks
    evacuation/shed (which would park sessions the purge destroys)."""

    async def t():
        import pytest

        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        srv = BrokerServer(cfg)
        await srv.start()
        # a detached session keeps the purge loop alive
        c = TestClient(srv.listeners[0].port, "pp")
        await c.connect(
            clean_start=False,
            properties={"session_expiry_interval": 600},
        )
        await c.disconnect()
        await asyncio.sleep(0.05)
        srv.broker.purger.status = "purging"  # freeze mid-purge
        with pytest.raises(RuntimeError):
            await srv.broker.eviction.start_evacuation()
        srv.broker.rebalance.start_shed(5, 10)
        assert not srv.broker.rebalance.shedding
        srv.broker.purger.status = "disabled"
        await c.close()
        await srv.stop()

    run(t())


def test_rebalance_stop_reaches_remote_donors():
    async def t():
        async def start_node(name, seeds=()):
            cfg = BrokerConfig()
            cfg.listeners = [ListenerConfig(port=0)]
            srv = BrokerServer(cfg)
            await srv.start()
            node = ClusterNode(name, srv.broker, **FAST)
            await node.start(seeds=list(seeds))
            return srv, node

        srv_a, a = await start_node("a")
        srv_b, b = await start_node("b", seeds=[("a", "127.0.0.1", a.port)])
        await asyncio.sleep(0.3)

        clients = [TestClient(srv_a.listeners[0].port, f"rs-{i}")
                   for i in range(6)]
        for c in clients:
            await c.connect()

        # coordinate from B with a slow rate so the shed is still
        # running on A when the stop arrives
        plan = await srv_b.broker.rebalance.start(
            conn_evict_rate=1, rel_conn_threshold=1.05
        )
        assert plan["donors"].get("a", 0) >= 2
        for _ in range(50):
            if srv_a.broker.rebalance.shedding:
                break
            await asyncio.sleep(0.05)
        assert srv_a.broker.rebalance.shedding

        await srv_b.broker.rebalance.stop()
        for _ in range(50):
            if not srv_a.broker.rebalance.shedding:
                break
            await asyncio.sleep(0.05)
        assert not srv_a.broker.rebalance.shedding
        assert srv_a.broker.rebalance.status == "idle"

        for c in clients:
            await c.close()
        await b.stop()
        await srv_b.stop()
        await a.stop()
        await srv_a.stop()

    run(t())


def test_cluster_purge_fans_out():
    async def t():
        async def start_node(name, seeds=()):
            cfg = BrokerConfig()
            cfg.listeners = [ListenerConfig(port=0)]
            srv = BrokerServer(cfg)
            await srv.start()
            node = ClusterNode(name, srv.broker, **FAST)
            await node.start(seeds=list(seeds))
            return srv, node

        srv_a, a = await start_node("a")
        srv_b, b = await start_node("b", seeds=[("a", "127.0.0.1", a.port)])
        await asyncio.sleep(0.3)

        c = TestClient(srv_b.listeners[0].port, "pg-remote")
        await c.connect(
            clean_start=False,
            properties={"session_expiry_interval": 600},
        )
        await c.disconnect()
        await asyncio.sleep(0.05)
        assert srv_b.broker.cm.lookup("pg-remote") is not None

        # the fan-out path the REST handler uses: cast to peers
        await srv_a.broker.purger.start_purge(100)
        for peer in a.peers_alive():
            await a.transport.cast(
                peer, {"type": "session_purge", "rate": 100}
            )
        for _ in range(100):
            if srv_b.broker.purger.info()["status"] == "purged":
                break
            await asyncio.sleep(0.05)
        assert srv_b.broker.cm.lookup("pg-remote") is None
        assert srv_b.broker.purger.info()["status"] == "purged"

        await c.close()
        await b.stop()
        await srv_b.stop()
        await a.stop()
        await srv_a.stop()

    run(t())


def test_evacuated_client_migrates_to_peer():
    async def t():
        async def start_node(name, seeds=()):
            cfg = BrokerConfig()
            cfg.listeners = [ListenerConfig(port=0)]
            srv = BrokerServer(cfg)
            await srv.start()
            node = ClusterNode(name, srv.broker, **FAST)
            await node.start(seeds=list(seeds))
            return srv, node

        srv_a, a = await start_node("a")
        srv_b, b = await start_node("b", seeds=[("a", "127.0.0.1", a.port)])
        await asyncio.sleep(0.3)

        c = TestClient(srv_a.listeners[0].port, "mover")
        await c.connect(
            clean_start=False,
            properties={"session_expiry_interval": 600},
        )
        await c.subscribe("m/#", qos=1)
        await srv_a.broker.eviction.start_evacuation(conn_evict_rate=100)
        await asyncio.sleep(0.3)
        assert not srv_a.broker.cm.connected("mover")

        # the client follows USE_ANOTHER_SERVER to node B: takeover
        c2 = TestClient(srv_b.listeners[0].port, "mover")
        ack = await c2.connect(
            clean_start=False,
            properties={"session_expiry_interval": 600},
        )
        assert ack.session_present  # migrated with subscriptions
        pub = TestClient(srv_b.listeners[0].port, "pub")
        await pub.connect()
        await pub.publish("m/1", b"hello", qos=1)
        pkt = await c2.recv_publish()
        assert pkt.payload == b"hello"
        await pub.disconnect()
        await c2.disconnect()
        await c.close()
        await b.stop()
        await srv_b.stop()
        await a.stop()
        await srv_a.stop()

    run(t())
