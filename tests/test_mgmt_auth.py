"""Management-plane authentication (emqx_mgmt_auth +
emqx_dashboard_admin/RBAC parity): 401 without credentials on every
/api/v5 route, JWT admin login, API keys with hashed secrets and
roles, viewer read-only enforcement, and an audit log that survives a
broker restart."""

import asyncio
import json

import aiohttp
import pytest

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from emqx_tpu.mgmt_auth import MgmtAuth
from api_helper import auth_session
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


def make_server(tmp_path=None):
    cfg = BrokerConfig()
    cfg.listeners = [ListenerConfig(port=0)]
    cfg.api.enable = True
    cfg.api.port = 0
    if tmp_path is not None:
        cfg.api.data_dir = str(tmp_path)
    return BrokerServer(cfg)


def test_unauthenticated_requests_rejected(tmp_path):
    """kick/publish/config (and reads) answer 401 with no credentials
    — the round-3 verdict's security defect."""

    async def t():
        srv = make_server(tmp_path)
        await srv.start()
        api = f"http://127.0.0.1:{srv.api.port}"
        c = TestClient(srv.listeners[0].port, "victim")
        await c.connect()

        async with aiohttp.ClientSession() as http:
            for method, path, body in (
                ("DELETE", "/api/v5/clients/victim", None),
                ("POST", "/api/v5/publish",
                 {"topic": "t", "payload": "x"}),
                ("PUT", "/api/v5/configs",
                 {"path": "mqtt.max_qos_allowed", "value": 1}),
                ("GET", "/api/v5/clients", None),
                ("GET", "/api/v5/audit", None),
                ("POST", "/api/v5/api_key", {"name": "x"}),
            ):
                async with http.request(method, api + path,
                                        json=body) as r:
                    assert r.status == 401, (method, path, r.status)
            # wrong credentials are 401 too
            async with http.post(api + "/api/v5/login", json={
                "username": "admin", "password": "wrong",
            }) as r:
                assert r.status == 401
            # garbage tokens / unknown api keys
            for hdr in ("Bearer not.a.token", "Basic bm9wZTpub3Bl"):
                async with http.get(
                    api + "/api/v5/clients",
                    headers={"Authorization": hdr},
                ) as r:
                    assert r.status == 401, hdr

        # the client was NOT kicked by the unauthenticated DELETE
        assert srv.broker.cm.connected("victim")
        await c.close()
        await srv.stop()

    run(t())


def test_login_token_and_api_key_flows(tmp_path):
    async def t():
        srv = make_server(tmp_path)
        await srv.start()
        http, api = await auth_session(srv)
        async with http:
            # authenticated reads and writes work
            async with http.get(api + "/api/v5/clients") as r:
                assert r.status == 200
            async with http.post(api + "/api/v5/publish", json={
                "topic": "t/x", "payload": "hi",
            }) as r:
                assert r.status == 200

            # mint an API key; its secret authenticates via Basic
            async with http.post(api + "/api/v5/api_key", json={
                "name": "ci", "role": "administrator",
            }) as r:
                assert r.status == 201
                kd = await r.json()
        import base64
        basic = base64.b64encode(
            f"{kd['api_key']}:{kd['api_secret']}".encode()
        ).decode()
        async with aiohttp.ClientSession(
            headers={"Authorization": f"Basic {basic}"}
        ) as keyed:
            async with keyed.get(api + "/api/v5/stats") as r:
                assert r.status == 200
            # delete the key (with the key itself); it stops working
            async with keyed.delete(
                api + f"/api/v5/api_key/{kd['api_key']}"
            ) as r:
                assert r.status == 204
            async with keyed.get(api + "/api/v5/stats") as r:
                assert r.status == 401
        await srv.stop()

    run(t())


def test_viewer_role_is_read_only(tmp_path):
    async def t():
        srv = make_server(tmp_path)
        await srv.start()
        http, api = await auth_session(srv)
        async with http:
            async with http.post(api + "/api/v5/users", json={
                "username": "auditor", "password": "s3cret",
                "role": "viewer",
            }) as r:
                assert r.status == 201
        viewer, api = await auth_session(
            srv, username="auditor", password="s3cret"
        )
        async with viewer:
            async with viewer.get(api + "/api/v5/metrics") as r:
                assert r.status == 200
            async with viewer.post(api + "/api/v5/publish", json={
                "topic": "t", "payload": "x",
            }) as r:
                assert r.status == 403
            async with viewer.delete(api + "/api/v5/clients/any") as r:
                assert r.status == 403
        await srv.stop()

    run(t())


def test_audit_log_persists_across_restart(tmp_path):
    async def t():
        srv = make_server(tmp_path)
        await srv.start()
        http, api = await auth_session(srv)
        async with http:
            async with http.post(api + "/api/v5/publish", json={
                "topic": "a/b", "payload": "x",
            }) as r:
                assert r.status == 200
            async with http.get(api + "/api/v5/audit") as r:
                entries = (await r.json())["data"]
        assert any(
            e["path"] == "/api/v5/publish" and e["actor"] == "admin"
            for e in entries
        )
        await srv.stop()

        # a fresh broker over the same data dir still has the entry
        srv2 = make_server(tmp_path)
        await srv2.start()
        http2, api2 = await auth_session(srv2)
        async with http2:
            async with http2.get(api2 + "/api/v5/audit") as r:
                entries2 = (await r.json())["data"]
        assert any(
            e["path"] == "/api/v5/publish" and e["actor"] == "admin"
            for e in entries2
        )
        await srv2.stop()

    run(t())


def test_password_change_and_store_hashing(tmp_path):
    auth = MgmtAuth(str(tmp_path), default_password="public")
    # secrets at rest are salted hashes, never plaintext
    raw = (tmp_path / "admins.json").read_text()
    assert "public" not in raw
    assert auth.login("admin", "public")
    assert not auth.change_password("admin", "wrong", "next")
    assert auth.change_password("admin", "public", "next")
    assert auth.login("admin", "public") is None
    assert auth.login("admin", "next")

    key, secret = auth.create_api_key("ci", role="viewer")
    raw = (tmp_path / "api_keys.json").read_text()
    assert secret not in raw
    ident = auth.verify_api_key(key, secret)
    assert ident is not None and ident.role == "viewer"
    assert auth.verify_api_key(key, "bad") is None
    # expired keys are rejected
    key2, secret2 = auth.create_api_key("old", expires_in=-1)
    assert auth.verify_api_key(key2, secret2) is None
    # disabled keys are rejected
    auth.set_api_key_enabled(key, False)
    assert auth.verify_api_key(key, secret) is None


def test_deleted_user_token_invalidated(tmp_path):
    auth = MgmtAuth(str(tmp_path), default_password="public")
    auth.add_admin("temp", "pw", role="administrator")
    token = auth.login("temp", "pw")
    assert auth.verify_token(token) is not None
    auth.delete_admin("temp")
    assert auth.verify_token(token) is None
    with pytest.raises(ValueError):
        auth.add_admin("x", "pw", role="root")  # unknown role


def test_last_admin_undeletable_and_corrupt_store_refused(tmp_path):
    auth = MgmtAuth(str(tmp_path), default_password="public")
    with pytest.raises(ValueError):
        auth.delete_admin("admin")
    # with a second administrator, deleting one is fine
    auth.add_admin("two", "pw", role="administrator")
    assert auth.delete_admin("admin")
    with pytest.raises(ValueError):
        auth.delete_admin("two")

    # a corrupt store must be a hard error, not a silent re-bootstrap
    # of default credentials
    (tmp_path / "admins.json").write_text("{truncated")
    with pytest.raises(RuntimeError):
        MgmtAuth(str(tmp_path), default_password="public")


def test_viewer_can_rotate_own_password(tmp_path):
    async def t():
        srv = make_server(tmp_path)
        await srv.start()
        http, api = await auth_session(srv)
        async with http:
            async with http.post(api + "/api/v5/users", json={
                "username": "v", "password": "old", "role": "viewer",
            }) as r:
                assert r.status == 201
        viewer, api = await auth_session(srv, username="v",
                                         password="old")
        async with viewer:
            # someone else's password: forbidden for a viewer
            async with viewer.put(
                api + "/api/v5/users/admin/change_pwd",
                json={"old_pwd": "public", "new_pwd": "x"},
            ) as r:
                assert r.status == 403
            # own password: allowed despite read-only role
            async with viewer.put(
                api + "/api/v5/users/v/change_pwd",
                json={"old_pwd": "old", "new_pwd": "new"},
            ) as r:
                assert r.status == 204
            # rotation invalidates tokens minted before it — including
            # the one that just performed the change
            async with viewer.get(api + "/api/v5/stats") as r:
                assert r.status == 401
        relog, api = await auth_session(srv, username="v",
                                        password="new")
        async with relog:
            async with relog.get(api + "/api/v5/stats") as r:
                assert r.status == 200
        await srv.stop()

    run(t())


def test_publisher_role_publish_only(tmp_path):
    """The publisher role (emqx EE api-key rbac): POST /api/v5/publish
    works; every other endpoint — reads included — answers 403."""

    async def t():
        import base64

        srv = make_server(tmp_path)
        await srv.start()
        http, api = await auth_session(srv)
        async with http:
            async with http.post(api + "/api/v5/api_key", json={
                "name": "ingest", "role": "publisher",
            }) as r:
                assert r.status == 201
                kd = await r.json()
        basic = base64.b64encode(
            f"{kd['api_key']}:{kd['api_secret']}".encode()
        ).decode()
        async with aiohttp.ClientSession(
            headers={"Authorization": f"Basic {basic}"}
        ) as keyed:
            async with keyed.post(api + "/api/v5/publish", json={
                "topic": "ingest/x", "payload": "hi",
            }) as r:
                assert r.status == 200
            for method, path in (
                ("GET", "/api/v5/clients"),
                ("GET", "/api/v5/stats"),
                ("POST", "/api/v5/users"),
                ("DELETE", "/api/v5/api_key/zzz"),
                ("POST", "/api/v5/data/export"),
            ):
                async with keyed.request(
                    method, api + path, json={}
                ) as r:
                    assert r.status == 403, (method, path, r.status)
        await srv.stop()

    run(t())
