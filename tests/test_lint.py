"""brokerlint (tools/brokerlint): per-rule fixtures — each rule family
fires on a known-bad snippet, stays silent on the fixed shape, and
honors `# brokerlint: ignore[...]` — plus the tier-1 GATE: the repo
must produce zero findings beyond the checked-in baseline, and the
baseline must match a fresh run exactly (no stale entries: burned-down
debt leaves the file too).

The gate is why this lives in tests/: `python -m pytest tests/` and
`python -m tools.brokerlint` enforce the identical contract (same
run_lint/diff_baseline code path)."""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

from emqx_tpu import failpoints
from tools.brokerlint import (
    DEFAULT_BASELINE, DEFAULT_PATHS, DISPATCH_FUNCS, DispatchFn,
    SEAM_FUNCS, Seam, analyze_program, analyze_source, diff_baseline,
    load_baseline, run_lint,
)


def rules_of(src, path="fixture.py", seams=(), dispatch=()):
    return [f.rule for f in analyze_source(src, path, seams=seams,
                                           dispatch=dispatch)]


def prog_rules(sources, seams=(), dispatch=()):
    """[(path, rule), ...] over a multi-module fixture tree."""
    return [(f.path, f.rule) for f in analyze_program(
        sources, seams=seams, dispatch=dispatch
    )]


# ----------------------------------------------------------- ASYNC101

def test_async101_blocking_call():
    bad = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)\n"
    )
    assert "ASYNC101" in rules_of(bad)
    # sync function: fine
    ok = "import time\ndef f():\n    time.sleep(1)\n"
    assert "ASYNC101" not in rules_of(ok)
    # the async equivalent: fine
    ok2 = "import asyncio\nasync def f():\n    await asyncio.sleep(1)\n"
    assert rules_of(ok2) == []
    # a sync closure INSIDE an async def is sync code
    ok3 = (
        "import time\n"
        "async def f():\n"
        "    def cb():\n"
        "        time.sleep(1)\n"
        "    return cb\n"
    )
    assert "ASYNC101" not in rules_of(ok3)


def test_async101_suppression_comment():
    bad = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # brokerlint: ignore[ASYNC101]\n"
    )
    assert rules_of(bad) == []
    above = (
        "import time\n"
        "async def f():\n"
        "    # justified because fixture\n"
        "    # brokerlint: ignore[*]\n"
        "    time.sleep(1)\n"
    )
    assert rules_of(above) == []
    # suppressing a DIFFERENT rule does not silence this one
    wrong = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # brokerlint: ignore[ASYNC102]\n"
    )
    assert "ASYNC101" in rules_of(wrong)


# ----------------------------------------------------------- ASYNC102

def test_async102_sync_wait():
    bad = (
        "async def f(fut):\n"
        "    return fut.result()\n"
    )
    assert "ASYNC102" in rules_of(bad)
    bad_join = "async def f(t):\n    t.join()\n"
    assert "ASYNC102" in rules_of(bad_join)
    bad_join_to = "async def f(t):\n    t.join(5)\n"
    assert "ASYNC102" in rules_of(bad_join_to)
    # str.join shapes must NOT fire (their signature differs)
    ok = (
        "async def f(parts):\n"
        "    return ', '.join(parts)\n"
    )
    assert "ASYNC102" not in rules_of(ok)
    # a done-callback (sync def nested in async) legally calls result()
    ok2 = (
        "async def f(task):\n"
        "    def done(t):\n"
        "        return t.result()\n"
        "    task.add_done_callback(done)\n"
    )
    assert "ASYNC102" not in rules_of(ok2)


# ----------------------------------------------------------- ASYNC103

def test_async103_lock_across_io():
    bad = (
        "import asyncio\n"
        "class C:\n"
        "    async def send(self, w):\n"
        "        async with self._lock:\n"
        "            w.write(b'x')\n"
        "            await w.drain()\n"
    )
    assert "ASYNC103" in rules_of(bad)
    # one level of same-module indirection resolves
    indirect = (
        "import asyncio\n"
        "class C:\n"
        "    async def _ensure(self):\n"
        "        await asyncio.open_connection('h', 1)\n"
        "    async def send(self):\n"
        "        async with self._lock:\n"
        "            await self._ensure()\n"
    )
    assert "ASYNC103" in rules_of(indirect)
    # lock around pure computation: fine
    ok = (
        "import asyncio\n"
        "class C:\n"
        "    async def bump(self):\n"
        "        async with self._lock:\n"
        "            self.n += 1\n"
    )
    assert "ASYNC103" not in rules_of(ok)
    # suppression on the async-with line
    suppressed = (
        "import asyncio\n"
        "class C:\n"
        "    async def send(self, w):\n"
        "        # brokerlint: ignore[ASYNC103]\n"
        "        async with self._lock:\n"
        "            await w.drain()\n"
    )
    assert rules_of(suppressed) == []


def test_async103_nested_def_under_lock_not_flagged():
    """An IO-awaiting closure DEFINED (not run) under the lock is not
    a lock-across-IO: the subtree is pruned."""
    ok = (
        "import asyncio\n"
        "class C:\n"
        "    async def send(self, w):\n"
        "        async with self._lock:\n"
        "            async def helper():\n"
        "                await w.drain()\n"
        "            self.h = helper\n"
    )
    assert "ASYNC103" not in rules_of(ok)


# ----------------------------------------------------------- ASYNC104

def test_async104_cancel_then_await_in_stop():
    bad = (
        "import asyncio\n"
        "class C:\n"
        "    async def stop(self):\n"
        "        self._task.cancel()\n"
        "        try:\n"
        "            await self._task\n"
        "        except asyncio.CancelledError:\n"
        "            pass\n"
    )
    assert "ASYNC104" in rules_of(bad)
    bad_wf = (
        "import asyncio\n"
        "class C:\n"
        "    async def close(self):\n"
        "        self._task.cancel()\n"
        "        await asyncio.wait_for(self._task, 2)\n"
    )
    assert "ASYNC104" in rules_of(bad_wf)
    # the fixed shape: aio.cancel_and_wait
    ok = (
        "from emqx_tpu.aio import cancel_and_wait\n"
        "class C:\n"
        "    async def stop(self):\n"
        "        await cancel_and_wait(self._task)\n"
    )
    assert "ASYNC104" not in rules_of(ok)
    # wait_for around a fresh COROUTINE (not a stored task): fine
    ok2 = (
        "import asyncio\n"
        "class C:\n"
        "    async def stop(self):\n"
        "        self._server.close()\n"
        "        await asyncio.wait_for(self._server.wait_closed(), 2)\n"
    )
    assert "ASYNC104" not in rules_of(ok2)
    # same pattern OUTSIDE a stop path: not this rule's business
    ok3 = (
        "import asyncio\n"
        "class C:\n"
        "    async def rotate(self):\n"
        "        self._task.cancel()\n"
        "        await self._task\n"
    )
    assert "ASYNC104" not in rules_of(ok3)


# ----------------------------------------------------------- ASYNC105

def test_async105_dropped_task():
    bad = (
        "import asyncio\n"
        "def kick(loop):\n"
        "    loop.create_task(work())\n"
    )
    assert "ASYNC105" in rules_of(bad)
    ok_kept = (
        "import asyncio\n"
        "def kick(self, loop):\n"
        "    self._t = loop.create_task(work())\n"
    )
    assert "ASYNC105" not in rules_of(ok_kept)
    ok_cb = (
        "import asyncio\n"
        "def kick(loop, tasks):\n"
        "    loop.create_task(work()).add_done_callback(tasks.discard)\n"
    )
    assert "ASYNC105" not in rules_of(ok_cb)


# ---------------------------------------------------------- DEVICE2xx

def test_device201_host_sync_in_jit():
    bad = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.sum().item()\n"
    )
    assert "DEVICE201" in rules_of(bad)
    bad_cast = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(x)\n"
    )
    assert "DEVICE201" in rules_of(bad_cast)
    # float() of a STATIC arg is host math at trace time: fine
    ok = (
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnames=('n',))\n"
        "def f(x, *, n):\n"
        "    return x * float(n)\n"
    )
    assert "DEVICE201" not in rules_of(ok)
    # .item() outside jit is ordinary host code
    ok2 = "def g(x):\n    return x.item()\n"
    assert rules_of(ok2) == []


def test_device202_tracer_branch_in_jit():
    bad = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        return x\n"
        "    return -x\n"
    )
    assert "DEVICE202" in rules_of(bad)
    # branching on shape or a static arg is resolved at trace time
    ok = (
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnames=('n',))\n"
        "def f(x, *, n):\n"
        "    if n > 0 and x.shape[0] > 1:\n"
        "        return x\n"
        "    return -x\n"
    )
    assert "DEVICE202" not in rules_of(ok)


def test_device203_host_numpy_in_jit():
    bad = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return np.asarray(x)\n"
    )
    assert "DEVICE203" in rules_of(bad)
    # np on static/constant values builds trace-time constants: fine
    # (the match kernel's `h0 & np.uint32(nb - 1)` shape)
    ok = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    nb = x.shape[0]\n"
        "    return x & np.uint32(nb - 1)\n"
    )
    assert "DEVICE203" not in rules_of(ok)


def test_device204_unhashable_static():
    bad_default = (
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnames=('caps',))\n"
        "def f(x, caps=[1, 2]):\n"
        "    return x\n"
    )
    assert "DEVICE204" in rules_of(bad_default)
    bad_call = (
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnames=('caps',))\n"
        "def f(x, *, caps=(1, 2)):\n"
        "    return x\n"
        "def g(x):\n"
        "    return f(x, caps=[1, 2])\n"
    )
    assert "DEVICE204" in rules_of(bad_call)
    ok = bad_call.replace("caps=[1, 2]", "caps=(1, 2)")
    assert "DEVICE204" not in rules_of(ok)


def test_device_rules_cover_jit_wrapped_functions():
    """`self._jit = jax.jit(fn)` (rules/predicate.py shape) marks `fn`
    as device code without a decorator."""
    bad = (
        "import jax\n"
        "def fn(x):\n"
        "    return x.item()\n"
        "g = jax.jit(fn)\n"
    )
    assert "DEVICE201" in rules_of(bad)


# -------------------------------------------------------------- FP301

_SEAM = [Seam("pkg/mod.py", "C.send", "test.seam")]


def test_fp301_seam_coverage():
    bad = (
        "class C:\n"
        "    async def send(self):\n"
        "        return 1\n"
    )
    assert "FP301" in rules_of(bad, path="pkg/mod.py", seams=_SEAM)
    ok = (
        "from . import failpoints\n"
        "class C:\n"
        "    async def send(self):\n"
        "        await failpoints.evaluate_async('test.seam')\n"
    )
    assert "FP301" not in rules_of(ok, path="pkg/mod.py", seams=_SEAM)
    # one level of indirection through a helper resolves
    ok2 = (
        "from . import failpoints\n"
        "class C:\n"
        "    async def _seam(self):\n"
        "        return await failpoints.evaluate_async('test.seam')\n"
        "    async def send(self):\n"
        "        await self._seam()\n"
    )
    assert "FP301" not in rules_of(ok2, path="pkg/mod.py", seams=_SEAM)
    # an unrelated module is not checked
    assert "FP301" not in rules_of(bad, path="pkg/other.py",
                                   seams=_SEAM)
    # a renamed/deleted seam function is itself a finding, so the
    # declaration list cannot silently rot
    gone = "class C:\n    async def send2(self):\n        return 1\n"
    assert "FP301" in rules_of(gone, path="pkg/mod.py", seams=_SEAM)


def test_seam_declarations_match_failpoints_tuple():
    """Every declared seam name exists in failpoints.SEAMS (the
    disabled-guard test iterates that tuple), and vice versa for the
    function-level seams."""
    declared = {s.seam for s in SEAM_FUNCS}
    assert declared <= set(failpoints.SEAMS), (
        declared - set(failpoints.SEAMS)
    )
    # ...and the reverse: a name added to failpoints.SEAMS without a
    # SEAM_FUNCS entry would leave FP301 blind to its function — the
    # "coverage grows by construction" guarantee requires both
    assert set(failpoints.SEAMS) <= declared, (
        set(failpoints.SEAMS) - declared
    )


# ------------------------------------------------------------- PERF401

_DISPATCH = [DispatchFn("pkg/disp.py", "B.fan_out")]


def test_perf401_per_subscriber_encode():
    bad = (
        "from codec import serialize\n"
        "class B:\n"
        "    def fan_out(self, subs, pkt):\n"
        "        for s in subs:\n"
        "            s.write(serialize(pkt, s.version))\n"
    )
    assert "PERF401" in rules_of(bad, path="pkg/disp.py",
                                 dispatch=_DISPATCH)
    # encode OUTSIDE the loop (the single-encode shape): fine
    ok = (
        "from codec import serialize\n"
        "class B:\n"
        "    def fan_out(self, subs, pkt):\n"
        "        wire = serialize(pkt, 5)\n"
        "        for s in subs:\n"
        "            s.write(wire)\n"
    )
    assert "PERF401" not in rules_of(ok, path="pkg/disp.py",
                                     dispatch=_DISPATCH)
    # a closure DEFINED in the loop is not a per-subscriber encode
    ok2 = (
        "from codec import serialize\n"
        "class B:\n"
        "    def fan_out(self, subs, pkt):\n"
        "        for s in subs:\n"
        "            def render():\n"
        "                return serialize(pkt, 5)\n"
        "            s.renderer = render\n"
    )
    assert "PERF401" not in rules_of(ok2, path="pkg/disp.py",
                                     dispatch=_DISPATCH)
    # an unrelated module is not checked
    assert "PERF401" not in rules_of(bad, path="pkg/other.py",
                                     dispatch=_DISPATCH)
    # suppression works like every other rule
    sup = bad.replace(
        "s.write(serialize(pkt, s.version))",
        "s.write(serialize(pkt, s.version))"
        "  # brokerlint: ignore[PERF401]",
    )
    assert "PERF401" not in rules_of(sup, path="pkg/disp.py",
                                     dispatch=_DISPATCH)


def test_perf401_declared_function_must_exist():
    """A renamed/deleted dispatch function is itself a finding, so the
    declaration list cannot silently rot."""
    gone = "class B:\n    def other(self):\n        return 1\n"
    assert "PERF401" in rules_of(gone, path="pkg/disp.py",
                                 dispatch=_DISPATCH)


# ------------------------------------------------------------- PERF402

def test_perf402_per_delivery_clock():
    bad = (
        "import time\n"
        "class B:\n"
        "    def fan_out(self, subs):\n"
        "        for s in subs:\n"
        "            s.ts = time.time()\n"
    )
    assert "PERF402" in rules_of(bad, path="pkg/disp.py",
                                 dispatch=_DISPATCH)
    # datetime-shaped per-iteration clocks fire too
    bad2 = bad.replace("time.time()", "datetime.now()")
    assert "PERF402" in rules_of(bad2, path="pkg/disp.py",
                                 dispatch=_DISPATCH)
    # the clock hoisted above the loop (one read per run): fine
    ok = (
        "import time\n"
        "class B:\n"
        "    def fan_out(self, subs):\n"
        "        now = time.time()\n"
        "        for s in subs:\n"
        "            s.ts = now\n"
    )
    assert "PERF402" not in rules_of(ok, path="pkg/disp.py",
                                     dispatch=_DISPATCH)
    # a closure DEFINED in the loop is not a per-delivery clock
    ok2 = (
        "import time\n"
        "class B:\n"
        "    def fan_out(self, subs):\n"
        "        for s in subs:\n"
        "            def stamp():\n"
        "                return time.time()\n"
        "            s.stamp = stamp\n"
    )
    assert "PERF402" not in rules_of(ok2, path="pkg/disp.py",
                                     dispatch=_DISPATCH)
    # an unrelated module is not checked
    assert "PERF402" not in rules_of(bad, path="pkg/other.py",
                                     dispatch=_DISPATCH)


def test_perf402_suppression_comment():
    sup = (
        "import time\n"
        "class B:\n"
        "    def fan_out(self, subs):\n"
        "        for s in subs:\n"
        "            s.ts = time.time()"
        "  # brokerlint: ignore[PERF402]\n"
    )
    assert "PERF402" not in rules_of(sup, path="pkg/disp.py",
                                     dispatch=_DISPATCH)
    # suppressing PERF402 does not silence a PERF401 on the same line
    both = (
        "from codec import serialize\n"
        "import time\n"
        "class B:\n"
        "    def fan_out(self, subs, pkt):\n"
        "        for s in subs:\n"
        "            s.write(serialize(pkt, time.time()))"
        "  # brokerlint: ignore[PERF402]\n"
    )
    assert "PERF401" in rules_of(both, path="pkg/disp.py",
                                 dispatch=_DISPATCH)
    assert "PERF402" not in rules_of(both, path="pkg/disp.py",
                                     dispatch=_DISPATCH)


def test_perf401_declared_functions_exist_in_repo():
    """The shipped DISPATCH_FUNCS point at real functions (the repo
    gate below would fail with `missing` findings otherwise — this
    just localizes the failure)."""
    repo = Path(__file__).resolve().parents[1]
    for d in DISPATCH_FUNCS:
        assert (repo / d.path_suffix).exists(), d


# ------------------------------------------------------------- PERF403

def test_perf403_per_delivery_opts_read():
    """With the window decision columns in place, a per-delivery
    SubOpts attribute read inside a dispatch loop is a finding."""
    bad = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        for msg, opts in deliveries:\n"
        "            if opts.no_local and msg.from_client == 'c':\n"
        "                continue\n"
        "            q = opts.qos\n"
    )
    rules = rules_of(bad, path="pkg/disp.py", dispatch=_DISPATCH)
    assert rules.count("PERF403") == 2
    # attr-chained opts bindings (self.last_opts) fire too
    chained = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        for msg in deliveries:\n"
        "            s = self.last_opts.subid\n"
    )
    assert "PERF403" in rules_of(chained, path="pkg/disp.py",
                                 dispatch=_DISPATCH)
    # MESSAGE attribute reads are not findings (only opts bindings)
    ok = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        for msg in deliveries:\n"
        "            q = msg.qos\n"
    )
    assert "PERF403" not in rules_of(ok, path="pkg/disp.py",
                                     dispatch=_DISPATCH)
    # the columns shape — per-run hoist + vectorized consumption: fine
    ok2 = (
        "class B:\n"
        "    def fan_out(self, eff, opts, deliveries):\n"
        "        oq = opts.qos\n"
        "        for t, msg in enumerate(deliveries):\n"
        "            q = eff[t] if eff is not None else oq\n"
    )
    assert "PERF403" not in rules_of(ok2, path="pkg/disp.py",
                                     dispatch=_DISPATCH)
    # a for statement's ITERABLE evaluates once per loop, not per
    # iteration — no finding at function level...
    ok3 = (
        "class B:\n"
        "    def fan_out(self, opts):\n"
        "        for t in range(opts.qos):\n"
        "            self.emit(t)\n"
    )
    assert "PERF403" not in rules_of(ok3, path="pkg/disp.py",
                                     dispatch=_DISPATCH)
    # ...but nested inside an outer loop it IS per-delivery, and a
    # while test re-evaluates every iteration
    bad2 = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        for msg, opts in deliveries:\n"
        "            for t in range(opts.qos):\n"
        "                self.emit(t)\n"
    )
    assert "PERF403" in rules_of(bad2, path="pkg/disp.py",
                                 dispatch=_DISPATCH)
    bad3 = (
        "class B:\n"
        "    def fan_out(self, opts):\n"
        "        while opts.qos:\n"
        "            self.step()\n"
    )
    assert "PERF403" in rules_of(bad3, path="pkg/disp.py",
                                 dispatch=_DISPATCH)
    # a for-else suite executes once per LOOP, not per iteration
    ok4 = (
        "class B:\n"
        "    def fan_out(self, opts, deliveries):\n"
        "        for msg in deliveries:\n"
        "            self.emit(msg)\n"
        "        else:\n"
        "            last = opts.qos\n"
    )
    assert "PERF403" not in rules_of(ok4, path="pkg/disp.py",
                                     dispatch=_DISPATCH)
    # an unrelated module is not checked
    assert "PERF403" not in rules_of(bad, path="pkg/other.py",
                                     dispatch=_DISPATCH)


def test_perf403_suppression_comment():
    sup = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        for msg, opts in deliveries:\n"
        "            q = opts.qos  # brokerlint: ignore[PERF403]\n"
    )
    assert "PERF403" not in rules_of(sup, path="pkg/disp.py",
                                     dispatch=_DISPATCH)
    # suppressing PERF403 does not silence a PERF402 on the same line
    both = (
        "import time\n"
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        for msg, opts in deliveries:\n"
        "            q = (opts.qos, time.time())"
        "  # brokerlint: ignore[PERF403]\n"
    )
    assert "PERF402" in rules_of(both, path="pkg/disp.py",
                                 dispatch=_DISPATCH)
    assert "PERF403" not in rules_of(both, path="pkg/disp.py",
                                     dispatch=_DISPATCH)


# ------------------------------------------------------------- OBS601

def test_obs601_unguarded_tracer_in_dispatch_loop():
    bad = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        for m, opts in deliveries:\n"
        "            self.tracer.start('message.deliver', m.topic)\n"
    )
    assert "OBS601" in rules_of(bad, path="pkg/disp.py",
                                dispatch=_DISPATCH)
    # trace-context ALLOCATION in the loop fires too
    ctor = bad.replace(
        "self.tracer.start('message.deliver', m.topic)",
        "m.ctx = TraceContext('t', 's')",
    )
    assert "OBS601" in rules_of(ctor, path="pkg/disp.py",
                                dispatch=_DISPATCH)
    # deep receiver chains resolve (`self.broker.lifecycle.emit`)
    chain = bad.replace(
        "self.tracer.start('message.deliver', m.topic)",
        "self.broker.lifecycle.emit(m)",
    )
    assert "OBS601" in rules_of(chain, path="pkg/disp.py",
                                dispatch=_DISPATCH)
    # an unrelated module is not checked
    assert "OBS601" not in rules_of(bad, path="pkg/other.py",
                                    dispatch=_DISPATCH)
    # a loop that is a DIRECT child of a (non-sampling) if body is
    # still a dispatch loop — the walker must flip in_loop for it
    loop_under_if = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        if self.enabled:\n"
        "            for m, opts in deliveries:\n"
        "                self.tracer.start('deliver', m.topic)\n"
    )
    assert "OBS601" in rules_of(loop_under_if, path="pkg/disp.py",
                                dispatch=_DISPATCH)


def test_obs601_sampled_guard_and_hoist_pass():
    # the sampled-check idiom: per-message ctx probe, tracer work only
    # inside `if ctx is not None:`
    guarded = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        for m, opts in deliveries:\n"
        "            ctx = getattr(m, '_trace_ctx', None)\n"
        "            if ctx is not None:\n"
        "                self.tracer.start('deliver', m.topic)\n"
    )
    assert "OBS601" not in rules_of(guarded, path="pkg/disp.py",
                                    dispatch=_DISPATCH)
    # guard NESTED under an unrelated if still counts (walker descends
    # ifs at entry, not only as direct children)
    nested = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        for m, opts in deliveries:\n"
        "            if self.tracer is not None:\n"
        "                span = getattr(m, '_span', None)\n"
        "                if span is not None:\n"
        "                    self.tracer.end(span)\n"
    )
    assert "OBS601" not in rules_of(nested, path="pkg/disp.py",
                                    dispatch=_DISPATCH)
    # the else branch of a guard is NOT guarded
    unguarded_else = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        for m, opts in deliveries:\n"
        "            if m.sampled:\n"
        "                pass\n"
        "            else:\n"
        "                self.tracer.start('deliver', m.topic)\n"
    )
    assert "OBS601" in rules_of(unguarded_else, path="pkg/disp.py",
                                dispatch=_DISPATCH)
    # once-per-window emission OUTSIDE the loop: fine
    hoisted = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        for m, opts in deliveries:\n"
        "            m.deliver()\n"
        "        self.lifecycle.window_spans(deliveries)\n"
    )
    assert "OBS601" not in rules_of(hoisted, path="pkg/disp.py",
                                    dispatch=_DISPATCH)


def test_obs601_suppression_comment():
    sup = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        for m, opts in deliveries:\n"
        "            self.tracer.start('deliver')"
        "  # brokerlint: ignore[OBS601]\n"
    )
    assert "OBS601" not in rules_of(sup, path="pkg/disp.py",
                                    dispatch=_DISPATCH)


def test_obs601_instrumented_dispatch_path_clean():
    """The acceptance gate: the PR's own instrumentation of the
    dispatch path (ingress stamping, window_spans emission, slow-subs
    trace ids) introduces NO unguarded tracing work in the dispatch
    hot loops."""
    findings = [
        f for f in run_lint(["emqx_tpu/broker"])
        if f.rule == "OBS601"
    ]
    assert not findings, "\n".join(f.render() for f in findings)


# ------------------------------------------------------------- OBS602

def test_obs602_cold_path_flight_call_in_dispatch_loop():
    # `note`/`trigger`/`status` are cold-path API: a finding in a loop
    bad = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        fl = self.flight\n"
        "        for m, opts in deliveries:\n"
        "            fl.note('deliver', topic=m.topic)\n"
    )
    assert "OBS602" in rules_of(bad, path="pkg/disp.py",
                                dispatch=_DISPATCH)
    # the un-hoisted receiver spelling fires too
    attr = bad.replace("fl.note('deliver', topic=m.topic)",
                       "self.flight.trigger('storm')")
    assert "OBS602" in rules_of(attr, path="pkg/disp.py",
                                dispatch=_DISPATCH)
    # an unrelated module is not checked
    assert "OBS602" not in rules_of(bad, path="pkg/other.py",
                                    dispatch=_DISPATCH)
    # UNLIKE OBS601 there is no sampled-guard exemption: the recorder
    # is always on, so an enclosing if cannot make the work free
    guarded = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        fl = self.flight\n"
        "        for m, opts in deliveries:\n"
        "            ctx = getattr(m, '_trace_ctx', None)\n"
        "            if ctx is not None:\n"
        "                fl.note('deliver', topic=m.topic)\n"
    )
    assert "OBS602" in rules_of(guarded, path="pkg/disp.py",
                                dispatch=_DISPATCH)


def test_obs602_record_scalar_args_pass():
    # the approved shape: the preallocated O(1) ring append with
    # scalar-coercion args only
    ok = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        fl = self.flight\n"
        "        for m, opts in deliveries:\n"
        "            fl.record(13, float(len(opts)), float(m.seq))\n"
    )
    assert "OBS602" not in rules_of(ok, path="pkg/disp.py",
                                    dispatch=_DISPATCH)
    # arithmetic on names/attributes is scalar too
    arith = ok.replace("fl.record(13, float(len(opts)), float(m.seq))",
                       "fl.record(13, (m.t1 - m.t0) * 1e6, m.seq + 1)")
    assert "OBS602" not in rules_of(arith, path="pkg/disp.py",
                                    dispatch=_DISPATCH)
    # cold-path emission OUTSIDE the loop: fine
    hoisted = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        for m, opts in deliveries:\n"
        "            m.deliver()\n"
        "        self.flight.note('window', n=len(deliveries))\n"
    )
    assert "OBS602" not in rules_of(hoisted, path="pkg/disp.py",
                                    dispatch=_DISPATCH)


def test_obs602_allocating_record_args():
    base = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        fl = self.flight\n"
        "        for m, opts in deliveries:\n"
        "            CALL\n"
    )
    for call in (
        "fl.record(13, len([m.topic]))",          # list display
        "fl.record(13, d={'topic': m.topic})",    # dict display (kwarg)
        "fl.record(13, float(str(m.seq)))",       # str() allocates
        "fl.record(13, sum(x.n for x in opts))",  # genexp + non-scalar
    ):
        src = base.replace("CALL", call)
        assert "OBS602" in rules_of(src, path="pkg/disp.py",
                                    dispatch=_DISPATCH), call


def test_obs602_suppression_comment():
    sup = (
        "class B:\n"
        "    def fan_out(self, deliveries):\n"
        "        fl = self.flight\n"
        "        for m, opts in deliveries:\n"
        "            fl.note('deliver')"
        "  # brokerlint: ignore[OBS602]\n"
    )
    assert "OBS602" not in rules_of(sup, path="pkg/disp.py",
                                    dispatch=_DISPATCH)


def test_obs602_instrumented_dispatch_path_clean():
    """The acceptance gate: the flight recorder's own dispatch-path
    instrumentation (the per-peer EV_FWD append in _flush_forwards,
    ring samples, window hooks) satisfies the O(1) no-allocation
    contract it imposes."""
    findings = [
        f for f in run_lint(["emqx_tpu"])
        if f.rule == "OBS602"
    ]
    assert not findings, "\n".join(f.render() for f in findings)


# ------------------------------------------------------------ the gate

def test_repo_has_no_findings_beyond_baseline():
    """The tier-1 gate: zero NEW findings over the whole default
    surface — emqx_tpu/ AND tools/ AND bench.py (the analyzer eats
    its own dog food) — and zero STALE baseline entries (fixed debt
    must leave the baseline so it only ever shrinks)."""
    findings = run_lint(list(DEFAULT_PATHS))
    baseline = load_baseline(DEFAULT_BASELINE)
    new, stale = diff_baseline(findings, baseline)
    assert not new, "new brokerlint findings:\n" + "\n".join(
        f.render() for f in new
    )
    assert not stale, (
        "stale baseline entries (fixed? remove them):\n"
        + "\n".join(sorted(stale))
    )


def test_default_paths_cover_tools_and_bench():
    assert "tools" in DEFAULT_PATHS and "bench.py" in DEFAULT_PATHS


def test_cached_whole_tree_run_stays_fast():
    """The mtime cache keeps the tier-1 gate cheap: a warm whole-tree
    run (parse+index cached per file) must finish well under the
    budget.  Generous bound — the point is catching an accidental
    O(tree²) regression, not micro-benchmarking."""
    run_lint(list(DEFAULT_PATHS))  # warm the per-file caches
    t0 = time.perf_counter()
    run_lint(list(DEFAULT_PATHS))
    warm = time.perf_counter() - t0
    assert warm < 12.0, f"warm whole-tree lint took {warm:.1f}s"


def test_baseline_diff_is_count_aware():
    """Fingerprints are line-number free, so two identical-shape
    violations in one function collide — the diff must compare COUNTS
    or one baseline entry would mask a newly added duplicate."""
    from collections import Counter

    src = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)\n"
        "    time.sleep(2)\n"
    )
    findings = analyze_source(src)
    assert [f.rule for f in findings] == ["ASYNC101", "ASYNC101"]
    fp = findings[0].fingerprint
    assert findings[1].fingerprint == fp
    # one baselined, a second added later: the second is NEW
    new, stale = diff_baseline(findings, Counter({fp: 1}))
    assert len(new) == 1 and not stale
    # two baselined, one fixed: the burned-down copy reads stale
    new, stale = diff_baseline(findings[:1], Counter({fp: 2}))
    assert not new and stale == {fp}


def test_baseline_is_empty():
    """PR 3 burned the baseline to ZERO (the kafka/mongo serialized
    round-trips now pipeline).  It must stay empty: new debt takes a
    justified inline `# brokerlint: ignore[..]` at the site — or gets
    fixed — never a baseline entry."""
    lines = Path(DEFAULT_BASELINE).read_text().splitlines()
    entries = [l for l in lines if l.strip()
               and not l.strip().startswith("#")]
    assert entries == [], (
        "brokerlint baseline must stay empty:\n" + "\n".join(entries)
    )


def test_cli_matches_gate():
    """`python -m tools.brokerlint` (what CI/dev runs) agrees with the
    pytest gate: exit 0, and --json round-trips."""
    repo = Path(__file__).resolve().parents[1]
    proc = subprocess.run(
        [sys.executable, "-m", "tools.brokerlint", "--json"],
        cwd=repo, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    import json

    out = json.loads(proc.stdout)
    assert out["new"] == []
    assert out["stale_baseline"] == []


# ======================================================= interprocedural
# The PR-7 layer: whole-program call graph (callgraph.py), bottom-up
# SCC summaries (dataflow.py), and the rule families built on them.

# ------------------------------------------- transitive ASYNC101

def test_async101_transitive_two_levels():
    """async -> sync helper -> sync helper2 -> time.sleep: invisible
    to the intra rule, flagged by the summary chain."""
    src = (
        "import time\n"
        "def helper2():\n"
        "    time.sleep(1)\n"
        "def helper():\n"
        "    helper2()\n"
        "async def f():\n"
        "    helper()\n"
    )
    assert "ASYNC101" in rules_of(src)
    # each module alone is clean; the PROGRAM is not
    mods = {
        "pkg/util.py": (
            "import time\n"
            "def helper2():\n"
            "    time.sleep(1)\n"
            "def helper():\n"
            "    helper2()\n"
        ),
        "pkg/srv.py": (
            "from .util import helper\n"
            "async def f():\n"
            "    helper()\n"
        ),
    }
    for path, m in mods.items():
        assert rules_of(m, path=path) == [], path
    assert ("pkg/srv.py", "ASYNC101") in prog_rules(mods)


def test_async101_transitive_base_site_suppression():
    """An inline ignore at the BLOCKING SITE stops the fact from
    propagating: one annotation, not one per caller."""
    src = (
        "import time\n"
        "def helper():\n"
        "    # justified: one-time init\n"
        "    time.sleep(1)  # brokerlint: ignore[ASYNC101]\n"
        "async def f():\n"
        "    helper()\n"
    )
    assert "ASYNC101" not in rules_of(src)


def test_async101_transitive_call_site_suppression():
    src = (
        "import time\n"
        "def helper():\n"
        "    time.sleep(1)\n"
        "async def f():\n"
        "    helper()  # brokerlint: ignore[ASYNC101]\n"
    )
    assert "ASYNC101" not in rules_of(src)


def test_async101_sleep_zero_is_gil_yield_not_block():
    """time.sleep(0) is the GIL-yield idiom (engine chunked copies);
    neither the intra rule nor the summary counts it."""
    direct = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(0)\n"
    )
    assert "ASYNC101" not in rules_of(direct)
    via = (
        "import time\n"
        "def helper():\n"
        "    time.sleep(0)\n"
        "async def f():\n"
        "    helper()\n"
    )
    assert "ASYNC101" not in rules_of(via)
    # a non-zero sleep still fires both ways
    assert "ASYNC101" in rules_of(direct.replace("sleep(0)", "sleep(1)"))


def test_async101_transitive_async_callee_not_flagged():
    """Calling an async function only builds a coroutine — the
    blocking body is the CALLEE's intra finding, not the caller's."""
    src = (
        "import time\n"
        "async def bad():\n"
        "    time.sleep(1)\n"
        "async def f():\n"
        "    await bad()\n"
    )
    rules = [x.rule for x in analyze_source(src)]
    # exactly one ASYNC101 (inside `bad`), not a second at the await
    assert rules.count("ASYNC101") == 1


# ------------------------------------------- transitive DEVICE201/203

_DEV_TREE = {
    "pkg/kern.py": (
        "import jax\n"
        "from .helpers import helper1\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return helper1(x)\n"
    ),
    "pkg/helpers.py": (
        "def helper2(y):\n"
        "    return y.item()\n"
        "def helper1(z):\n"
        "    return helper2(z)\n"
    ),
}


def test_device201_transitive_two_modules_deep():
    """The acceptance fixture: a jit-called helper two levels deep
    (across modules) does a host sync."""
    for path, m in _DEV_TREE.items():
        assert rules_of(m, path=path) == [], path  # intra: clean
    assert ("pkg/kern.py", "DEVICE201") in prog_rules(_DEV_TREE)


def test_device203_transitive_param_aware():
    """np.* on a helper param flags only when the jit call site feeds
    a TRACED value into THAT param — a trace-time constant does not
    propagate (parameter-aware taint)."""
    bad = {
        "pkg/kern.py": (
            "import jax\n"
            "from .helpers import norm\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return norm(x)\n"
        ),
        "pkg/helpers.py": (
            "import numpy as np\n"
            "def norm(a):\n"
            "    return np.asarray(a)\n"
        ),
    }
    assert ("pkg/kern.py", "DEVICE203") in prog_rules(bad)
    # constant fed to the syncing param: no finding
    ok = dict(bad)
    ok["pkg/kern.py"] = (
        "import jax\n"
        "from .helpers import norm\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x + norm((1, 2))\n"
    )
    assert ("pkg/kern.py", "DEVICE203") not in prog_rules(ok)
    # traced value into an UNRELATED param of a two-param helper:
    # still no finding (the sync touches only `cfg`)
    split = {
        "pkg/kern.py": (
            "import jax\n"
            "from .helpers import mix\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return mix((1, 2), x)\n"
        ),
        "pkg/helpers.py": (
            "import numpy as np\n"
            "def mix(cfg, data):\n"
            "    return data * np.asarray(cfg)\n"
        ),
    }
    assert ("pkg/kern.py", "DEVICE203") not in prog_rules(split)


def test_device_transitive_suppression():
    sup = dict(_DEV_TREE)
    sup["pkg/helpers.py"] = (
        "def helper2(y):\n"
        "    return y.item()  # brokerlint: ignore[DEVICE201]\n"
        "def helper1(z):\n"
        "    return helper2(z)\n"
    )
    assert ("pkg/kern.py", "DEVICE201") not in prog_rules(sup)


# --------------------------------------------------------- NATIVE501

_ENC = (
    "class Enc:\n"
    "    def __init__(self):\n"
    "        self.arena = bytearray()\n"
    "    def slot_for(self, m):\n"
    "        self.arena += m\n"
    "        return 0\n"
    "    def native_views(self):\n"
    "        return ()\n"
)


def test_native501_views_held_across_arena_growth():
    bad = _ENC + (
        "def run(enc: \"Enc\", msgs, lib):\n"
        "    views = enc.native_views()\n"
        "    for m in msgs:\n"
        "        enc.slot_for(m)\n"
        "    lib.da_go(views)\n"
    )
    assert "NATIVE501" in rules_of(bad)
    # views taken AFTER the last slot miss (deliver_run_native shape)
    ok = _ENC + (
        "def run(enc: \"Enc\", msgs, lib):\n"
        "    for m in msgs:\n"
        "        enc.slot_for(m)\n"
        "    views = enc.native_views()\n"
        "    lib.da_go(views)\n"
    )
    assert "NATIVE501" not in rules_of(ok)
    # dead views (no use after the growth) are not a finding
    dead = _ENC + (
        "def run(enc: \"Enc\", msgs, lib):\n"
        "    views = enc.native_views()\n"
        "    for m in msgs:\n"
        "        enc.slot_for(m)\n"
    )
    assert "NATIVE501" not in rules_of(dead)


def test_native501_invalidation_through_helper():
    """The growth hides one call deep: enc.slot_for reached through a
    module helper still invalidates the cached views."""
    bad = _ENC + (
        "def fill(enc: \"Enc\", msgs):\n"
        "    for m in msgs:\n"
        "        enc.slot_for(m)\n"
        "def run(enc: \"Enc\", msgs, lib):\n"
        "    views = enc.native_views()\n"
        "    fill(enc, msgs)\n"
        "    lib.da_go(views)\n"
    )
    assert "NATIVE501" in rules_of(bad)


def test_native501_suppression():
    sup = _ENC + (
        "def run(enc: \"Enc\", msgs, lib):\n"
        "    views = enc.native_views()\n"
        "    for m in msgs:\n"
        "        enc.slot_for(m)  # brokerlint: ignore[NATIVE501]\n"
        "    lib.da_go(views)\n"
    )
    assert "NATIVE501" not in rules_of(sup)


# --------------------------------------------------------- NATIVE502

def test_native502_temp_buffers_at_ctypes_boundary():
    tmp_ptr = (
        "import numpy as np\n"
        "def f(x, p, lib):\n"
        "    lib.su_go(np.asarray(x).ctypes.data_as(p))\n"
    )
    assert "NATIVE502" in rules_of(tmp_ptr)
    tmp_buf = (
        "import ctypes\n"
        "def f(n, lib):\n"
        "    lib.su_go((ctypes.c_uint8 * n).from_buffer(bytearray(n)))\n"
    )
    assert "NATIVE502" in rules_of(tmp_buf)
    raw_addr = (
        "def f(arr):\n"
        "    return arr.ctypes.data\n"
    )
    assert "NATIVE502" in rules_of(raw_addr)
    # the safe shapes: pointer/pin from a bound local
    ok = (
        "import ctypes\n"
        "import numpy as np\n"
        "def f(x, p, lib):\n"
        "    a = np.asarray(x)\n"
        "    out = bytearray(8)\n"
        "    lib.su_go(a.ctypes.data_as(p),\n"
        "              (ctypes.c_uint8 * len(out)).from_buffer(out))\n"
    )
    assert "NATIVE502" not in rules_of(ok)


def test_native502_resizable_arena_export_needs_justification():
    bad = (
        "import ctypes\n"
        "class Enc:\n"
        "    def export(self):\n"
        "        return (ctypes.c_uint8 * 4).from_buffer(self.arena)\n"
    )
    assert "NATIVE502" in rules_of(bad)
    sup = bad.replace(
        "return (ctypes.c_uint8 * 4).from_buffer(self.arena)",
        "# release-before-growth\n"
        "        # brokerlint: ignore[NATIVE502]\n"
        "        return (ctypes.c_uint8 * 4).from_buffer(self.arena)",
    )
    assert "NATIVE502" not in rules_of(sup)


# ----------------------------------------------------------- LOCK401

_LOCKS_MOD = (
    "import threading\n"
    "la = threading.Lock()\n"
    "lb = threading.Lock()\n"
)


def test_lock401_cross_module_inversion():
    """The acceptance fixture: two modules acquire the same pair of
    locks in opposite order — flagged at both edges."""
    mods = {
        "pkg/locks.py": _LOCKS_MOD,
        "pkg/m1.py": (
            "from .locks import la, lb\n"
            "def f():\n"
            "    with la:\n"
            "        with lb:\n"
            "            pass\n"
        ),
        "pkg/m2.py": (
            "from .locks import la, lb\n"
            "def g():\n"
            "    with lb:\n"
            "        with la:\n"
            "            pass\n"
        ),
    }
    got = prog_rules(mods)
    assert ("pkg/m1.py", "LOCK401") in got
    assert ("pkg/m2.py", "LOCK401") in got
    # consistent order everywhere: clean
    ok = dict(mods)
    ok["pkg/m2.py"] = ok["pkg/m1.py"].replace("def f", "def g")
    assert not [r for r in prog_rules(ok) if r[1] == "LOCK401"]


def test_lock401_inversion_through_callee():
    """One side of the cycle hides inside a called function: the
    callee's `acquires` summary closes the loop."""
    mods = {
        "pkg/locks.py": _LOCKS_MOD,
        "pkg/m1.py": (
            "from .locks import la, lb\n"
            "def inner():\n"
            "    with lb:\n"
            "        pass\n"
            "def f():\n"
            "    with la:\n"
            "        inner()\n"
        ),
        "pkg/m2.py": (
            "from .locks import la, lb\n"
            "def g():\n"
            "    with lb:\n"
            "        with la:\n"
            "            pass\n"
        ),
    }
    got = prog_rules(mods)
    assert ("pkg/m1.py", "LOCK401") in got
    assert ("pkg/m2.py", "LOCK401") in got


def test_lock401_suppression():
    mods = {
        "pkg/locks.py": _LOCKS_MOD,
        "pkg/m1.py": (
            "from .locks import la, lb\n"
            "def f():\n"
            "    with la:\n"
            "        # brokerlint: ignore[LOCK401]\n"
            "        with lb:\n"
            "            pass\n"
        ),
        "pkg/m2.py": (
            "from .locks import la, lb\n"
            "def g():\n"
            "    with lb:\n"
            "        # brokerlint: ignore[LOCK401]\n"
            "        with la:\n"
            "            pass\n"
        ),
    }
    assert not [r for r in prog_rules(mods) if r[1] == "LOCK401"]


# ----------------------------------------------------------- LOCK402

def test_lock402_lock_across_native_call():
    direct = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def f(self, lib, x):\n"
        "        with self._lock:\n"
        "            lib.td_add(x)\n"
    )
    assert "LOCK402" in rules_of(direct)
    # one helper deep: the callee's `native` summary carries it
    via = (
        "import threading\n"
        "def _go(lib, x):\n"
        "    lib.td_add(x)\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def f(self, lib, x):\n"
        "        with self._lock:\n"
        "            _go(lib, x)\n"
    )
    assert "LOCK402" in rules_of(via)
    # native call OUTSIDE the lock: clean
    ok = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def f(self, lib, x):\n"
        "        with self._lock:\n"
        "            n = x + 1\n"
        "        lib.td_add(n)\n"
    )
    assert "LOCK402" not in rules_of(ok)
    sup = direct.replace(
        "lib.td_add(x)",
        "lib.td_add(x)  # brokerlint: ignore[LOCK402]",
    )
    assert "LOCK402" not in rules_of(sup)


def test_lock402_transitive_io_await_beyond_async103():
    """The awaited helper's helper does the IO — one level past what
    ASYNC103's class-blind map resolves, so LOCK402 reports it (and
    ASYNC103 does not double-report)."""
    mods = {
        "pkg/io2.py": (
            "import asyncio\n"
            "async def dial():\n"
            "    await asyncio.open_connection('h', 1)\n"
        ),
        "pkg/io1.py": (
            "from .io2 import dial\n"
            "async def ensure():\n"
            "    await dial()\n"
        ),
        "pkg/srv.py": (
            "from .io1 import ensure\n"
            "class C:\n"
            "    async def send(self):\n"
            "        async with self._lock:\n"
            "            await ensure()\n"
        ),
    }
    got = prog_rules(mods)
    assert ("pkg/srv.py", "LOCK402") in got
    assert ("pkg/srv.py", "ASYNC103") not in got


def test_lock402_sync_with_lock_across_io_await():
    """A sync `with` lock wrapping an IO await is invisible to
    ASYNC103 (which only sees async-with) — LOCK402's beat."""
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    async def send(self, w):\n"
        "        with self._lock:\n"
        "            await w.drain()\n"
    )
    got = rules_of(src)
    assert "LOCK402" in got and "ASYNC103" not in got


def test_lock402_does_not_double_report_async103_territory():
    """Direct lock-across-IO in an async-with belongs to ASYNC103
    alone."""
    src = (
        "import asyncio\n"
        "class C:\n"
        "    async def send(self, w):\n"
        "        async with self._lock:\n"
        "            await w.drain()\n"
    )
    got = rules_of(src)
    assert got.count("ASYNC103") == 1 and "LOCK402" not in got


# ----------------------------------------------------------- LOCK403

_DUAL = (
    "import threading\n"
    "class C:\n"
    "    def __init__(self):\n"
    "        self._state_lock = threading.Lock()\n"
    "    def worker(self):\n"
    "        with self._state_lock:\n"
    "            pass\n"
    "    async def on_loop(self):\n"
    "        with self._state_lock:\n"
    "            pass\n"
)


def test_lock403_dual_context_lock():
    assert "LOCK403" in rules_of(_DUAL)
    # one context only: clean
    sync_only = _DUAL.replace("async def on_loop", "def on_loop")
    assert "LOCK403" not in rules_of(sync_only)


def test_lock403_ownership_comment_documents():
    doc = _DUAL.replace(
        "    async def on_loop(self):\n"
        "        with self._state_lock:\n",
        "    async def on_loop(self):\n"
        "        # lock-ownership: loop reads, worker writes; held\n"
        "        # for O(1) dict ops only\n"
        "        with self._state_lock:\n",
    )
    assert "LOCK403" not in rules_of(doc)
    sup = _DUAL.replace(
        "    async def on_loop(self):\n"
        "        with self._state_lock:\n",
        "    async def on_loop(self):\n"
        "        # brokerlint: ignore[LOCK403]\n"
        "        with self._state_lock:\n",
    )
    assert "LOCK403" not in rules_of(sup)


# ------------------------------------------------- call-graph layer

def test_callgraph_cycle_summaries_converge():
    """Mutual recursion: the SCC fixpoint terminates and both
    members carry the blocking fact."""
    src = (
        "import time\n"
        "def even(n):\n"
        "    time.sleep(1)\n"
        "    return n == 0 or odd(n - 1)\n"
        "def odd(n):\n"
        "    return n != 0 and even(n - 1)\n"
        "async def f():\n"
        "    odd(3)\n"
        "    even(2)\n"
    )
    rules = [x.rule for x in analyze_source(src)]
    # both call sites flagged: the fact crossed the cycle both ways
    assert rules.count("ASYNC101") == 2


def test_callgraph_one_level_aliasing():
    """`h = self._m; h()`, `self.x = self._m; self.x()`, and
    functools.partial all resolve to the method."""
    alias_local = (
        "import time\n"
        "class C:\n"
        "    def _m(self):\n"
        "        time.sleep(1)\n"
        "    async def f(self):\n"
        "        h = self._m\n"
        "        h()\n"
    )
    assert "ASYNC101" in rules_of(alias_local)
    alias_attr = (
        "import time\n"
        "class C:\n"
        "    def _m(self):\n"
        "        time.sleep(1)\n"
        "    def __init__(self):\n"
        "        self.cb = self._m\n"
        "    async def f(self):\n"
        "        self.cb()\n"
    )
    assert "ASYNC101" in rules_of(alias_attr)
    partial = (
        "import time\n"
        "from functools import partial\n"
        "def _m(flag):\n"
        "    time.sleep(1)\n"
        "go = partial(_m, True)\n"
        "async def f():\n"
        "    go()\n"
    )
    assert "ASYNC101" in rules_of(partial)


def test_callgraph_mtime_cache_invalidation(tmp_path):
    from tools.brokerlint import callgraph

    p = tmp_path / "mod.py"
    p.write_text("def one():\n    return 1\n")
    idx1 = callgraph.index_file(str(p), "mod.py")
    assert "one" in idx1.funcs
    # unchanged (mtime, size): the SAME index object comes back
    assert callgraph.index_file(str(p), "mod.py") is idx1
    # edit the file (force a distinct mtime even on coarse clocks)
    p.write_text("def two():\n    return 2\n")
    st = p.stat()
    os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
    idx2 = callgraph.index_file(str(p), "mod.py")
    assert idx2 is not idx1
    assert "two" in idx2.funcs and "one" not in idx2.funcs


def test_callgraph_intra_clean_interprocedural_dirty():
    """The acceptance fixture tree: every module passes the
    intra-function pass alone, and the program pass finds NATIVE,
    DEVICE and ASYNC violations across the seams."""
    mods = {
        "pkg/enc.py": (
            "class Enc:\n"
            "    def __init__(self):\n"
            "        self.arena = bytearray()\n"
            "    def slot_for(self, m):\n"
            "        self.arena += m\n"
            "        return 0\n"
            "    def native_views(self):\n"
            "        return ()\n"
        ),
        "pkg/disp.py": (
            "from .enc import Enc\n"
            "def run(enc: \"Enc\", msgs, lib):\n"
            "    views = enc.native_views()\n"
            "    for m in msgs:\n"
            "        enc.slot_for(m)\n"
            "    lib.da_go(views)\n"
        ),
        "pkg/kern.py": (
            "import jax\n"
            "from .helpers import helper1\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return helper1(x)\n"
        ),
        "pkg/helpers.py": (
            "import time\n"
            "def helper2(y):\n"
            "    return y.item()\n"
            "def helper1(z):\n"
            "    return helper2(z)\n"
            "def slow():\n"
            "    time.sleep(1)\n"
        ),
        "pkg/srv.py": (
            "from .helpers import slow\n"
            "async def handle():\n"
            "    slow()\n"
        ),
    }
    for path, m in mods.items():
        assert rules_of(m, path=path) == [], path
    got = prog_rules(mods)
    assert ("pkg/disp.py", "NATIVE501") in got
    assert ("pkg/kern.py", "DEVICE201") in got
    assert ("pkg/srv.py", "ASYNC101") in got


# ------------------------------------- suppression: decorated defs

def test_suppression_on_decorator_line(monkeypatch):
    """FP301 reports at the (decorated) function: an ignore on the
    decorator line, or a comment line above the decorator, must
    attach to the function's findings."""
    on_dec = (
        "def deco(f):\n"
        "    return f\n"
        "class C:\n"
        "    @deco  # brokerlint: ignore[FP301]\n"
        "    async def send(self):\n"
        "        return 1\n"
    )
    assert "FP301" not in rules_of(on_dec, path="pkg/mod.py",
                                   seams=_SEAM)
    above_dec = (
        "def deco(f):\n"
        "    return f\n"
        "class C:\n"
        "    # justified: seam evaluated by the wrapper\n"
        "    # brokerlint: ignore[FP301]\n"
        "    @deco\n"
        "    async def send(self):\n"
        "        return 1\n"
    )
    assert "FP301" not in rules_of(above_dec, path="pkg/mod.py",
                                   seams=_SEAM)
    # an unrelated rule's ignore on the decorator does NOT silence it
    wrong = on_dec.replace("ignore[FP301]", "ignore[ASYNC101]")
    assert "FP301" in rules_of(wrong, path="pkg/mod.py", seams=_SEAM)


def test_suppression_on_multiline_def_header():
    """The ignore sits on the closing-paren line of a long signature;
    the finding line is the `def` line — it must still attach."""
    src = (
        "class C:\n"
        "    async def send(\n"
        "        self,\n"
        "        payload,\n"
        "    ):  # brokerlint: ignore[FP301]\n"
        "        return 1\n"
    )
    assert "FP301" not in rules_of(src, path="pkg/mod.py",
                                   seams=_SEAM)


# --------------------------------------------------- CLI round-trips

def test_cli_sarif_output():
    repo = Path(__file__).resolve().parents[1]
    proc = subprocess.run(
        [sys.executable, "-m", "tools.brokerlint", "--sarif"],
        cwd=repo, capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "brokerlint"
    # the tree is clean, so results must be empty — and the schema
    # shape stable
    assert isinstance(run["results"], list)


def test_cli_changed_mode(tmp_path):
    """--changed REF lints the whole program but reports only files
    changed vs the ref: with a clean tree vs HEAD there can be no
    findings at all, and the flag must round-trip exit 0."""
    repo = Path(__file__).resolve().parents[1]
    proc = subprocess.run(
        [sys.executable, "-m", "tools.brokerlint",
         "--changed", "HEAD", "--json"],
        cwd=repo, capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["new"] == []
    # every reported finding (if any) names a changed .py file
    changed = subprocess.run(
        ["git", "diff", "--name-only", "HEAD", "--"],
        cwd=repo, capture_output=True, text=True, timeout=30,
    ).stdout.split()
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        cwd=repo, capture_output=True, text=True, timeout=30,
    ).stdout.split()
    allowed = set(changed) | set(untracked)
    for f in out["findings"]:
        assert f["path"] in allowed, f


def test_native501_rebind_after_misses_is_clean():
    """The remediation the rule message recommends — re-take the
    views into the SAME local after the last slot miss — must not
    itself trigger the finding (a rebind ends the previous window)."""
    ok = _ENC + (
        "def run(enc: \"Enc\", msgs, lib):\n"
        "    views = enc.native_views()\n"
        "    for m in msgs:\n"
        "        enc.slot_for(m)\n"
        "    views = enc.native_views()\n"
        "    lib.da_go(views)\n"
    )
    assert "NATIVE501" not in rules_of(ok)
    # ... but a USE of the stale binding before the rebind still fires
    bad = _ENC + (
        "def run(enc: \"Enc\", msgs, lib):\n"
        "    views = enc.native_views()\n"
        "    for m in msgs:\n"
        "        enc.slot_for(m)\n"
        "    lib.da_go(views)\n"
        "    views = enc.native_views()\n"
        "    lib.da_go(views)\n"
    )
    assert "NATIVE501" in rules_of(bad)


def test_write_baseline_ignores_changed_filter(tmp_path):
    """--changed --write-baseline must write the UNFILTERED run: the
    filter scopes the report, never the baseline (a truncated rewrite
    would drop every unchanged file's accepted entries)."""
    repo = Path(__file__).resolve().parents[1]
    out = tmp_path / "baseline.txt"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.brokerlint",
         "--changed", "HEAD", "--write-baseline",
         "--baseline", str(out)],
        cwd=repo, capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    full = subprocess.run(
        [sys.executable, "-m", "tools.brokerlint",
         "--write-baseline", "--baseline", str(tmp_path / "b2.txt")],
        cwd=repo, capture_output=True, text=True, timeout=240,
    )
    assert full.returncode == 0
    entries = [l for l in out.read_text().splitlines()
               if l.strip() and not l.startswith("#")]
    entries2 = [l for l in (tmp_path / "b2.txt").read_text()
                .splitlines() if l.strip() and not l.startswith("#")]
    assert entries == entries2


def test_device_transitive_class_qualified_call_mapping():
    """`Cls.m(obj, x)` carries the receiver IN call.args — the taint
    mapping must not shift positions as if it were a bound call
    (receiver-in-args vs `obj.m(x)`)."""
    src = (
        "import jax\n"
        "import numpy as np\n"
        "class Helper:\n"
        "    def compute(self, v):\n"
        "        return np.asarray(v)\n"
        "@jax.jit\n"
        "def f(x, h):\n"
        "    return Helper.compute(h, 0.0)\n"
    )
    # only a static 0.0 feeds the syncing param `v`: clean
    assert "DEVICE203" not in rules_of(src)
    # traced x into `v` through the class-qualified call: finding
    bad = src.replace("Helper.compute(h, 0.0)", "Helper.compute(h, x)")
    assert "DEVICE203" in rules_of(bad)


# ------------------------------------------------------------- DUR701


def test_dur701_bare_meta_write_in_ds():
    """A bare text-mode write to a non-.tmp path inside emqx_tpu/ds/
    is a finding: sidecars must go through the atomic-write helper."""
    bad = (
        "import json\n"
        "class S:\n"
        "    def save(self):\n"
        "        with open(self._path, 'w') as f:\n"
        "            json.dump({'a': 1}, f)\n"
    )
    assert "DUR701" in rules_of(bad, path="emqx_tpu/ds/store.py")
    # the inlined json.dump(obj, open(...)) form fires too
    inline = (
        "import json\n"
        "def save(path, obj):\n"
        "    json.dump(obj, open(path, 'w'))\n"
    )
    rules = rules_of(inline, path="emqx_tpu/ds/store.py")
    assert rules.count("DUR701") == 2  # the open AND the dump


def test_dur701_tmp_staging_and_scope_pass():
    # the helper's own staging write (tmp name, atomic replace): clean
    ok = (
        "import os\n"
        "def atomic(path, doc):\n"
        "    tmp = path + '.tmp'\n"
        "    with open(tmp, 'w') as f:\n"
        "        f.write(doc)\n"
        "    os.replace(tmp, path)\n"
    )
    assert "DUR701" not in rules_of(ok, path="emqx_tpu/ds/atomicio.py")
    # a literal + '.tmp' concatenation inline: clean
    ok2 = (
        "def atomic(path, doc):\n"
        "    with open(path + '.tmp', 'w') as f:\n"
        "        f.write(doc)\n"
    )
    assert "DUR701" not in rules_of(ok2, path="emqx_tpu/ds/x.py")
    # binary segment writes are the log engine's domain: clean
    ok3 = (
        "def write_seg(path, b):\n"
        "    with open(path, 'wb') as f:\n"
        "        f.write(b)\n"
    )
    assert "DUR701" not in rules_of(ok3, path="emqx_tpu/ds/x.py")
    # reads are never findings
    ok4 = (
        "def load(path):\n"
        "    with open(path) as f:\n"
        "        return f.read()\n"
    )
    assert "DUR701" not in rules_of(ok4, path="emqx_tpu/ds/x.py")
    # outside emqx_tpu/ds/ the rule does not apply
    bad_elsewhere = (
        "def save(path):\n"
        "    with open(path, 'w') as f:\n"
        "        f.write('x')\n"
    )
    assert "DUR701" not in rules_of(
        bad_elsewhere, path="emqx_tpu/retainer.py"
    )


def test_dur701_suppression_comment():
    src = (
        "def save(path):\n"
        "    # justified: operator-facing dump, not a load-bearing\n"
        "    # sidecar  # brokerlint: ignore[DUR701]\n"
        "    with open(path, 'w') as f:\n"
        "        f.write('x')\n"
    )
    assert "DUR701" not in rules_of(src, path="emqx_tpu/ds/x.py")


def test_dur701_repo_ds_package_is_clean():
    """The refactor left no bare sidecar writes in the real ds/
    package (the gate run also asserts this; this is the targeted
    check)."""
    import pathlib
    base = pathlib.Path(__file__).resolve().parent.parent
    for p in sorted((base / "emqx_tpu" / "ds").glob("*.py")):
        rel = f"emqx_tpu/ds/{p.name}"
        rules = rules_of(p.read_text(), path=rel)
        assert "DUR701" not in rules, rel


# ------------------------------------------------------------- DUR702


def test_dur702_direct_snapshot_write_in_ds():
    """A direct atomic_write_json call in a ds/ store module is a
    finding: store-metadata snapshots go through MetaJournal.fold."""
    bad = (
        "from . import atomicio\n"
        "class S:\n"
        "    def save_meta(self):\n"
        "        atomicio.atomic_write_json(self._path, {'a': 1})\n"
    )
    assert "DUR702" in rules_of(bad, path="emqx_tpu/ds/store.py")
    # ...including a bare-name import form
    bad2 = (
        "from .atomicio import atomic_write_json\n"
        "def save(path, obj):\n"
        "    atomic_write_json(path, obj)\n"
    )
    assert "DUR702" in rules_of(bad2, path="emqx_tpu/ds/store.py")


def test_dur702_fold_path_and_allowlist_pass():
    # the fold itself owns the snapshot write: clean
    fold = (
        "from . import atomicio\n"
        "class MetaJournal:\n"
        "    def fold(self, path, obj):\n"
        "        atomicio.atomic_write_json(path, obj)\n"
        "        self.truncate()\n"
    )
    assert "DUR702" not in rules_of(
        fold, path="emqx_tpu/ds/journal.py"
    )
    # audited session-checkpoint writers in persist.py: clean
    sess = (
        "from . import atomicio\n"
        "class DurableSessions:\n"
        "    def save(self, cid):\n"
        "        atomicio.atomic_write_json(self._p(cid), {})\n"
    )
    assert "DUR702" not in rules_of(
        sess, path="emqx_tpu/ds/persist.py"
    )
    # ...but an UNaudited persist.py writer fires
    stray = (
        "from . import atomicio\n"
        "class DurableSessions:\n"
        "    def _save_census(self):\n"
        "        atomicio.atomic_write_json(self._c, {})\n"
    )
    assert "DUR702" in rules_of(stray, path="emqx_tpu/ds/persist.py")
    # outside emqx_tpu/ds/ the rule does not apply
    assert "DUR702" not in rules_of(
        stray, path="emqx_tpu/retainer.py"
    )


def test_dur702_suppression_comment():
    src = (
        "from . import atomicio\n"
        "def export(path, obj):\n"
        "    # justified: operator-facing export, no journal to sync\n"
        "    # with  # brokerlint: ignore[DUR702]\n"
        "    atomicio.atomic_write_json(path, obj)\n"
    )
    assert "DUR702" not in rules_of(src, path="emqx_tpu/ds/x.py")


def test_dur702_repo_ds_package_is_clean():
    """Every real snapshot write in ds/ goes through the fold (or the
    audited persist.py session checkpoints)."""
    import pathlib
    base = pathlib.Path(__file__).resolve().parent.parent
    for p in sorted((base / "emqx_tpu" / "ds").glob("*.py")):
        rel = f"emqx_tpu/ds/{p.name}"
        rules = rules_of(p.read_text(), path=rel)
        assert "DUR702" not in rules, rel


# ----------------------------------------------------------- RACE8xx

from tools.brokerlint.racerules import (  # noqa: E402
    SHARED_CLASSES, SharedClass,
)

# fixtures roster their own Hub class instead of the real one, so the
# shapes stay minimal and independent of the production tree
_HUB = [SharedClass("svc/hub.py", "Hub")]


def race_rules(src, path="svc/hub.py"):
    return [f.rule for f in analyze_source(src, path, shared=_HUB)]


def race_prog(sources):
    return [(f.path, f.rule) for f in analyze_program(
        sources, shared=_HUB
    )]


def test_race801_check_then_act_across_await():
    bad = (
        "import asyncio\n"
        "class Hub:\n"
        "    def add(self, k, v):\n"
        "        self.pending[k] = v\n"
        "    async def take(self, k):\n"
        "        if k in self.pending:\n"
        "            await asyncio.sleep(0)\n"
        "            return self.pending.pop(k)\n"
        "        return None\n"
    )
    assert race_rules(bad) == ["RACE801"]
    # the act re-validated AFTER the suspension: clean
    ok = (
        "import asyncio\n"
        "class Hub:\n"
        "    def add(self, k, v):\n"
        "        self.pending[k] = v\n"
        "    async def take(self, k):\n"
        "        await asyncio.sleep(0)\n"
        "        if k in self.pending:\n"
        "            return self.pending.pop(k)\n"
        "        return None\n"
    )
    assert race_rules(ok) == []


def test_race801_suppression():
    src = (
        "import asyncio\n"
        "class Hub:\n"
        "    def add(self, k, v):\n"
        "        self.pending[k] = v\n"
        "    async def take(self, k):\n"
        "        if k in self.pending:\n"
        "            await asyncio.sleep(0)\n"
        "            # brokerlint: ignore[RACE801] single taker\n"
        "            return self.pending.pop(k)\n"
        "        return None\n"
    )
    assert race_rules(src) == []


def test_race801_suspension_two_calls_deep():
    """The await that opens the window hides behind two helper
    frames — the summary pass must carry `suspends` up the chain."""
    src = (
        "import asyncio\n"
        "class Hub:\n"
        "    def add(self, k, v):\n"
        "        self.pending[k] = v\n"
        "    async def _h2(self):\n"
        "        await asyncio.sleep(0)\n"
        "    async def _h1(self):\n"
        "        await self._h2()\n"
        "    async def take(self, k):\n"
        "        if k in self.pending:\n"
        "            await self._h1()\n"
        "            return self.pending.pop(k)\n"
        "        return None\n"
    )
    assert race_rules(src) == ["RACE801"]


def test_race801_acceptance_helper_two_modules_deep():
    """Acceptance fixture (a): the check-then-act window opens through
    a helper chain spanning two OTHER modules; re-checking after the
    await comes back clean."""
    tree = {
        "svc/io2.py": (
            "import asyncio\n"
            "async def flush2():\n"
            "    await asyncio.sleep(0)\n"
        ),
        "svc/io1.py": (
            "from .io2 import flush2\n"
            "async def flush():\n"
            "    await flush2()\n"
        ),
        "svc/hub.py": (
            "from .io1 import flush\n"
            "class Hub:\n"
            "    def add(self, k, v):\n"
            "        self.pending[k] = v\n"
            "    async def take(self, k):\n"
            "        if k in self.pending:\n"
            "            await flush()\n"
            "            return self.pending.pop(k)\n"
            "        return None\n"
        ),
    }
    assert race_prog(tree) == [("svc/hub.py", "RACE801")]
    fixed = dict(tree)
    fixed["svc/hub.py"] = (
        "from .io1 import flush\n"
        "class Hub:\n"
        "    def add(self, k, v):\n"
        "        self.pending[k] = v\n"
        "    async def take(self, k):\n"
        "        await flush()\n"
        "        if k in self.pending:\n"
        "            return self.pending.pop(k)\n"
        "        return None\n"
    )
    assert race_prog(fixed) == []


# ----------------------------------------------------------- RACE802

def test_race802_suspension_inside_iteration():
    bad = (
        "import asyncio\n"
        "class Hub:\n"
        "    def add(self, k, s):\n"
        "        self.subs[k] = s\n"
        "    def drop(self, k):\n"
        "        self.subs.pop(k, None)\n"
        "    async def notify(self):\n"
        "        for k in self.subs:\n"
        "            await asyncio.sleep(0)\n"
    )
    assert race_rules(bad) == ["RACE802"]
    # snapshot iteration: clean
    ok = bad.replace("in self.subs:", "in list(self.subs):")
    assert race_rules(ok) == []


def test_race802_body_mutates_iterated_container():
    src = (
        "class Hub:\n"
        "    def sweep(self, dead):\n"
        "        for k in self.subs:\n"
        "            if k in dead:\n"
        "                self.subs.pop(k)\n"
    )
    assert race_rules(src) == ["RACE802"]


def test_race802_alias_bound_mutator():
    """The mutation hides behind `self.cb = self._drop`: the resolver
    follows the one-level alias to the bound method's summary."""
    src = (
        "class Hub:\n"
        "    def __init__(self):\n"
        "        self.subs = {}\n"
        "        self.cb = self._drop\n"
        "    def _drop(self, k):\n"
        "        self.subs.pop(k, None)\n"
        "    def sweep(self, dead):\n"
        "        for k in self.subs:\n"
        "            if k in dead:\n"
        "                self.cb(k)\n"
    )
    assert race_rules(src) == ["RACE802"]


def test_race802_suppression():
    src = (
        "class Hub:\n"
        "    def sweep(self, dead):\n"
        "        # brokerlint: ignore[RACE802] returns right after\n"
        "        for k in self.subs:\n"
        "            if k in dead:\n"
        "                self.subs.pop(k)\n"
        "                return\n"
    )
    assert race_rules(src) == []


# ----------------------------------------------------------- RACE803

def test_race803_acceptance_thread_loop_crossing():
    """Acceptance fixture (b): a worker thread mutates a dict the
    event loop reads — flagged; clean once the mutation is handed to
    the loop with call_soon_threadsafe, or once the ownership rule is
    documented with `# loop-ownership:`."""
    bad = (
        "import threading\n"
        "class Hub:\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._worker).start()\n"
        "    def _worker(self):\n"
        "        self.stats['n'] = 1\n"
        "    async def report(self):\n"
        "        return len(self.stats)\n"
    )
    assert race_rules(bad) == ["RACE803"]

    handed_off = (
        "import threading\n"
        "class Hub:\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._worker).start()\n"
        "    def _worker(self):\n"
        "        self.loop.call_soon_threadsafe(self._apply, 1)\n"
        "    def _apply(self, n):\n"
        "        self.stats['n'] = n\n"
        "    async def report(self):\n"
        "        return len(self.stats)\n"
    )
    assert race_rules(handed_off) == []

    annotated = (
        "import threading\n"
        "class Hub:\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._worker).start()\n"
        "    def _worker(self):\n"
        "        # loop-ownership: GIL-atomic store of a gauge the\n"
        "        # loop only reads for display; torn sizes are fine\n"
        "        self.stats['n'] = 1\n"
        "    async def report(self):\n"
        "        return len(self.stats)\n"
    )
    assert race_rules(annotated) == []


def test_race803_locked_sites_are_lock403_territory():
    """A lock around the thread-side mutation silences RACE803 — the
    dual-context lock itself is LOCK403's beat (it wants its own
    `# lock-ownership:` justification)."""
    src = (
        "import threading\n"
        "class Hub:\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._worker).start()\n"
        "    def _worker(self):\n"
        "        with self._lock:\n"
        "            self.stats['n'] = 1\n"
        "    async def report(self):\n"
        "        with self._lock:\n"
        "            return len(self.stats)\n"
    )
    rules = race_rules(src)
    assert "RACE803" not in rules
    assert "LOCK403" in rules


def test_race803_suppression():
    src = (
        "import threading\n"
        "class Hub:\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._worker).start()\n"
        "    def _worker(self):\n"
        "        # brokerlint: ignore[RACE803] fixture reason\n"
        "        self.stats['n'] = 1\n"
        "    async def report(self):\n"
        "        return len(self.stats)\n"
    )
    assert race_rules(src) == []


# ----------------------------------------------------------- RACE804

def test_race804_related_pair_torn_across_await():
    bad = (
        "import asyncio\n"
        "class Hub:\n"
        "    def reset(self):\n"
        "        self.epoch = 0\n"
        "        self.epoch_key = b''\n"
        "    async def rotate(self):\n"
        "        self.epoch = self.epoch + 1\n"
        "        await asyncio.sleep(0)\n"
        "        self.epoch_key = b'x'\n"
    )
    assert race_rules(bad) == ["RACE804"]
    # both halves written before the suspension: clean
    ok = (
        "import asyncio\n"
        "class Hub:\n"
        "    def reset(self):\n"
        "        self.epoch = 0\n"
        "        self.epoch_key = b''\n"
        "    async def rotate(self):\n"
        "        self.epoch = self.epoch + 1\n"
        "        self.epoch_key = b'x'\n"
        "        await asyncio.sleep(0)\n"
    )
    assert race_rules(ok) == []


def test_race804_suppression():
    src = (
        "import asyncio\n"
        "class Hub:\n"
        "    def reset(self):\n"
        "        self.epoch = 0\n"
        "        self.epoch_key = b''\n"
        "    async def rotate(self):\n"
        "        self.epoch = self.epoch + 1\n"
        "        await asyncio.sleep(0)\n"
        "        # brokerlint: ignore[RACE804] stale key tolerated\n"
        "        self.epoch_key = b'x'\n"
    )
    assert race_rules(src) == []


def test_shared_roster_matches_tree():
    """Rot guard: every SHARED_CLASSES entry must still name a class
    that exists in the real tree (a rename silently un-rosters the
    singleton and the RACE rules go blind to it)."""
    repo = Path(__file__).resolve().parents[1]
    for spec in SHARED_CLASSES:
        p = repo / spec.path_suffix
        assert p.exists(), f"rostered module gone: {spec}"
        assert f"class {spec.name}" in p.read_text(), \
            f"rostered class gone: {spec}"


# ------------------------------------------------------------ MET901

def test_met901_unregistered_counter_name():
    tree = {
        "svc/metrics.py": (
            "METRICS = (\n"
            "    'messages.received',\n"
            ")\n"
            "EXTRA_METRIC_PREFIXES = ('gw.',)\n"
        ),
        "svc/app.py": (
            "class App:\n"
            "    def f(self):\n"
            "        self.metrics.inc('messages.recieved')\n"
            "    def g(self):\n"
            "        self.metrics.inc('messages.received')\n"
            "    def h(self):\n"
            "        self.metrics.observe('gw.rtt', 3)\n"
            "    def i(self, name):\n"
            "        self.metrics.inc(name)\n"
        ),
    }
    # only the typo'd literal fires: the registered name, the prefix
    # family, and the dynamic name all pass
    assert race_prog(tree) == [("svc/app.py", "MET901")]


def test_met901_suppression_and_no_registry():
    tree = {
        "svc/metrics.py": "METRICS = ('a.b',)\n",
        "svc/app.py": (
            "class App:\n"
            "    def f(self):\n"
            "        # brokerlint: ignore[MET901] runtime-registered\n"
            "        self.metrics.inc('a.typo')\n"
        ),
    }
    assert race_prog(tree) == []
    # a program with NO registry module skips MET901 entirely
    assert race_prog({"svc/app.py": tree["svc/app.py"]}) == []


def test_race_and_metrics_families_clean_on_repo():
    """The burn-down's end state, asserted family-precisely (the gate
    already covers it via the empty baseline): no RACE8xx or MET901
    debt anywhere on the default surface."""
    findings = [
        f for f in run_lint(list(DEFAULT_PATHS))
        if f.rule.startswith("RACE") or f.rule == "MET901"
    ]
    assert not findings, "\n".join(f.render() for f in findings)


# ------------------------------------------- program-findings cache

def test_program_cache_invalidates_on_callee_edit(tmp_path):
    """THE cache-correctness property: a file's interprocedural
    findings may replay from cache only while its dependency digest
    holds — editing ONLY a callee module must re-lint the caller
    (whose own mtime did not change) and surface the new transitive
    finding there."""
    from tools.brokerlint import engine

    helpers = tmp_path / "helpers.py"
    srv = tmp_path / "srv.py"
    helpers.write_text("import time\ndef slow():\n    pass\n")
    srv.write_text(
        "from helpers import slow\nasync def handle():\n    slow()\n"
    )
    first = run_lint([str(tmp_path)], root=str(tmp_path))
    assert [f.rule for f in first] == []
    # warm run: everything replays from the per-file program cache
    run_lint([str(tmp_path)], root=str(tmp_path))
    prof = engine.LAST_PROFILE
    assert prof["files"]["srv.py"] == {
        "index": "hit", "program": "hit",
    }
    # edit ONLY the callee so it now blocks
    helpers.write_text("import time\ndef slow():\n    time.sleep(1)\n")
    st = helpers.stat()
    os.utime(helpers, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
    third = run_lint([str(tmp_path)], root=str(tmp_path))
    assert [(f.path, f.rule) for f in third] == [
        ("srv.py", "ASYNC101")
    ]
    prof = engine.LAST_PROFILE
    # srv.py's SOURCE cache held (unchanged file) but its program
    # findings were recomputed — the dep digest saw slow()'s new
    # summary through the call edge
    assert prof["files"]["srv.py"] == {
        "index": "hit", "program": "miss",
    }
    assert prof["files"]["helpers.py"]["index"] == "miss"


def test_profile_shape_covers_race_families():
    """--profile's data source: every run_lint rewrites LAST_PROFILE
    with per-family timings (the RACE pass included) and per-file
    cache verdicts."""
    from tools.brokerlint import engine

    run_lint(list(DEFAULT_PATHS))
    prof = engine.LAST_PROFILE
    assert {"program:summaries", "program:digest",
            "program:race-local", "program:race-global"} <= set(
        prof["families"]
    )
    assert all(v >= 0.0 for v in prof["families"].values())
    assert prof["files"], "no per-file cache verdicts recorded"
    assert all(
        rec.get("index") in ("hit", "miss")
        for rec in prof["files"].values()
    )
