"""brokerlint (tools/brokerlint): per-rule fixtures — each rule family
fires on a known-bad snippet, stays silent on the fixed shape, and
honors `# brokerlint: ignore[...]` — plus the tier-1 GATE: the repo
must produce zero findings beyond the checked-in baseline, and the
baseline must match a fresh run exactly (no stale entries: burned-down
debt leaves the file too).

The gate is why this lives in tests/: `python -m pytest tests/` and
`python -m tools.brokerlint` enforce the identical contract (same
run_lint/diff_baseline code path)."""

import subprocess
import sys
from pathlib import Path

from emqx_tpu import failpoints
from tools.brokerlint import (
    DEFAULT_BASELINE, DISPATCH_FUNCS, DispatchFn, SEAM_FUNCS, Seam,
    analyze_source, diff_baseline, load_baseline, run_lint,
)


def rules_of(src, path="fixture.py", seams=(), dispatch=()):
    return [f.rule for f in analyze_source(src, path, seams=seams,
                                           dispatch=dispatch)]


# ----------------------------------------------------------- ASYNC101

def test_async101_blocking_call():
    bad = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)\n"
    )
    assert "ASYNC101" in rules_of(bad)
    # sync function: fine
    ok = "import time\ndef f():\n    time.sleep(1)\n"
    assert "ASYNC101" not in rules_of(ok)
    # the async equivalent: fine
    ok2 = "import asyncio\nasync def f():\n    await asyncio.sleep(1)\n"
    assert rules_of(ok2) == []
    # a sync closure INSIDE an async def is sync code
    ok3 = (
        "import time\n"
        "async def f():\n"
        "    def cb():\n"
        "        time.sleep(1)\n"
        "    return cb\n"
    )
    assert "ASYNC101" not in rules_of(ok3)


def test_async101_suppression_comment():
    bad = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # brokerlint: ignore[ASYNC101]\n"
    )
    assert rules_of(bad) == []
    above = (
        "import time\n"
        "async def f():\n"
        "    # justified because fixture\n"
        "    # brokerlint: ignore[*]\n"
        "    time.sleep(1)\n"
    )
    assert rules_of(above) == []
    # suppressing a DIFFERENT rule does not silence this one
    wrong = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # brokerlint: ignore[ASYNC102]\n"
    )
    assert "ASYNC101" in rules_of(wrong)


# ----------------------------------------------------------- ASYNC102

def test_async102_sync_wait():
    bad = (
        "async def f(fut):\n"
        "    return fut.result()\n"
    )
    assert "ASYNC102" in rules_of(bad)
    bad_join = "async def f(t):\n    t.join()\n"
    assert "ASYNC102" in rules_of(bad_join)
    bad_join_to = "async def f(t):\n    t.join(5)\n"
    assert "ASYNC102" in rules_of(bad_join_to)
    # str.join shapes must NOT fire (their signature differs)
    ok = (
        "async def f(parts):\n"
        "    return ', '.join(parts)\n"
    )
    assert "ASYNC102" not in rules_of(ok)
    # a done-callback (sync def nested in async) legally calls result()
    ok2 = (
        "async def f(task):\n"
        "    def done(t):\n"
        "        return t.result()\n"
        "    task.add_done_callback(done)\n"
    )
    assert "ASYNC102" not in rules_of(ok2)


# ----------------------------------------------------------- ASYNC103

def test_async103_lock_across_io():
    bad = (
        "import asyncio\n"
        "class C:\n"
        "    async def send(self, w):\n"
        "        async with self._lock:\n"
        "            w.write(b'x')\n"
        "            await w.drain()\n"
    )
    assert "ASYNC103" in rules_of(bad)
    # one level of same-module indirection resolves
    indirect = (
        "import asyncio\n"
        "class C:\n"
        "    async def _ensure(self):\n"
        "        await asyncio.open_connection('h', 1)\n"
        "    async def send(self):\n"
        "        async with self._lock:\n"
        "            await self._ensure()\n"
    )
    assert "ASYNC103" in rules_of(indirect)
    # lock around pure computation: fine
    ok = (
        "import asyncio\n"
        "class C:\n"
        "    async def bump(self):\n"
        "        async with self._lock:\n"
        "            self.n += 1\n"
    )
    assert "ASYNC103" not in rules_of(ok)
    # suppression on the async-with line
    suppressed = (
        "import asyncio\n"
        "class C:\n"
        "    async def send(self, w):\n"
        "        # brokerlint: ignore[ASYNC103]\n"
        "        async with self._lock:\n"
        "            await w.drain()\n"
    )
    assert rules_of(suppressed) == []


def test_async103_nested_def_under_lock_not_flagged():
    """An IO-awaiting closure DEFINED (not run) under the lock is not
    a lock-across-IO: the subtree is pruned."""
    ok = (
        "import asyncio\n"
        "class C:\n"
        "    async def send(self, w):\n"
        "        async with self._lock:\n"
        "            async def helper():\n"
        "                await w.drain()\n"
        "            self.h = helper\n"
    )
    assert "ASYNC103" not in rules_of(ok)


# ----------------------------------------------------------- ASYNC104

def test_async104_cancel_then_await_in_stop():
    bad = (
        "import asyncio\n"
        "class C:\n"
        "    async def stop(self):\n"
        "        self._task.cancel()\n"
        "        try:\n"
        "            await self._task\n"
        "        except asyncio.CancelledError:\n"
        "            pass\n"
    )
    assert "ASYNC104" in rules_of(bad)
    bad_wf = (
        "import asyncio\n"
        "class C:\n"
        "    async def close(self):\n"
        "        self._task.cancel()\n"
        "        await asyncio.wait_for(self._task, 2)\n"
    )
    assert "ASYNC104" in rules_of(bad_wf)
    # the fixed shape: aio.cancel_and_wait
    ok = (
        "from emqx_tpu.aio import cancel_and_wait\n"
        "class C:\n"
        "    async def stop(self):\n"
        "        await cancel_and_wait(self._task)\n"
    )
    assert "ASYNC104" not in rules_of(ok)
    # wait_for around a fresh COROUTINE (not a stored task): fine
    ok2 = (
        "import asyncio\n"
        "class C:\n"
        "    async def stop(self):\n"
        "        self._server.close()\n"
        "        await asyncio.wait_for(self._server.wait_closed(), 2)\n"
    )
    assert "ASYNC104" not in rules_of(ok2)
    # same pattern OUTSIDE a stop path: not this rule's business
    ok3 = (
        "import asyncio\n"
        "class C:\n"
        "    async def rotate(self):\n"
        "        self._task.cancel()\n"
        "        await self._task\n"
    )
    assert "ASYNC104" not in rules_of(ok3)


# ----------------------------------------------------------- ASYNC105

def test_async105_dropped_task():
    bad = (
        "import asyncio\n"
        "def kick(loop):\n"
        "    loop.create_task(work())\n"
    )
    assert "ASYNC105" in rules_of(bad)
    ok_kept = (
        "import asyncio\n"
        "def kick(self, loop):\n"
        "    self._t = loop.create_task(work())\n"
    )
    assert "ASYNC105" not in rules_of(ok_kept)
    ok_cb = (
        "import asyncio\n"
        "def kick(loop, tasks):\n"
        "    loop.create_task(work()).add_done_callback(tasks.discard)\n"
    )
    assert "ASYNC105" not in rules_of(ok_cb)


# ---------------------------------------------------------- DEVICE2xx

def test_device201_host_sync_in_jit():
    bad = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.sum().item()\n"
    )
    assert "DEVICE201" in rules_of(bad)
    bad_cast = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(x)\n"
    )
    assert "DEVICE201" in rules_of(bad_cast)
    # float() of a STATIC arg is host math at trace time: fine
    ok = (
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnames=('n',))\n"
        "def f(x, *, n):\n"
        "    return x * float(n)\n"
    )
    assert "DEVICE201" not in rules_of(ok)
    # .item() outside jit is ordinary host code
    ok2 = "def g(x):\n    return x.item()\n"
    assert rules_of(ok2) == []


def test_device202_tracer_branch_in_jit():
    bad = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        return x\n"
        "    return -x\n"
    )
    assert "DEVICE202" in rules_of(bad)
    # branching on shape or a static arg is resolved at trace time
    ok = (
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnames=('n',))\n"
        "def f(x, *, n):\n"
        "    if n > 0 and x.shape[0] > 1:\n"
        "        return x\n"
        "    return -x\n"
    )
    assert "DEVICE202" not in rules_of(ok)


def test_device203_host_numpy_in_jit():
    bad = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return np.asarray(x)\n"
    )
    assert "DEVICE203" in rules_of(bad)
    # np on static/constant values builds trace-time constants: fine
    # (the match kernel's `h0 & np.uint32(nb - 1)` shape)
    ok = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    nb = x.shape[0]\n"
        "    return x & np.uint32(nb - 1)\n"
    )
    assert "DEVICE203" not in rules_of(ok)


def test_device204_unhashable_static():
    bad_default = (
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnames=('caps',))\n"
        "def f(x, caps=[1, 2]):\n"
        "    return x\n"
    )
    assert "DEVICE204" in rules_of(bad_default)
    bad_call = (
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnames=('caps',))\n"
        "def f(x, *, caps=(1, 2)):\n"
        "    return x\n"
        "def g(x):\n"
        "    return f(x, caps=[1, 2])\n"
    )
    assert "DEVICE204" in rules_of(bad_call)
    ok = bad_call.replace("caps=[1, 2]", "caps=(1, 2)")
    assert "DEVICE204" not in rules_of(ok)


def test_device_rules_cover_jit_wrapped_functions():
    """`self._jit = jax.jit(fn)` (rules/predicate.py shape) marks `fn`
    as device code without a decorator."""
    bad = (
        "import jax\n"
        "def fn(x):\n"
        "    return x.item()\n"
        "g = jax.jit(fn)\n"
    )
    assert "DEVICE201" in rules_of(bad)


# -------------------------------------------------------------- FP301

_SEAM = [Seam("pkg/mod.py", "C.send", "test.seam")]


def test_fp301_seam_coverage():
    bad = (
        "class C:\n"
        "    async def send(self):\n"
        "        return 1\n"
    )
    assert "FP301" in rules_of(bad, path="pkg/mod.py", seams=_SEAM)
    ok = (
        "from . import failpoints\n"
        "class C:\n"
        "    async def send(self):\n"
        "        await failpoints.evaluate_async('test.seam')\n"
    )
    assert "FP301" not in rules_of(ok, path="pkg/mod.py", seams=_SEAM)
    # one level of indirection through a helper resolves
    ok2 = (
        "from . import failpoints\n"
        "class C:\n"
        "    async def _seam(self):\n"
        "        return await failpoints.evaluate_async('test.seam')\n"
        "    async def send(self):\n"
        "        await self._seam()\n"
    )
    assert "FP301" not in rules_of(ok2, path="pkg/mod.py", seams=_SEAM)
    # an unrelated module is not checked
    assert "FP301" not in rules_of(bad, path="pkg/other.py",
                                   seams=_SEAM)
    # a renamed/deleted seam function is itself a finding, so the
    # declaration list cannot silently rot
    gone = "class C:\n    async def send2(self):\n        return 1\n"
    assert "FP301" in rules_of(gone, path="pkg/mod.py", seams=_SEAM)


def test_seam_declarations_match_failpoints_tuple():
    """Every declared seam name exists in failpoints.SEAMS (the
    disabled-guard test iterates that tuple), and vice versa for the
    function-level seams."""
    declared = {s.seam for s in SEAM_FUNCS}
    assert declared <= set(failpoints.SEAMS), (
        declared - set(failpoints.SEAMS)
    )
    # ...and the reverse: a name added to failpoints.SEAMS without a
    # SEAM_FUNCS entry would leave FP301 blind to its function — the
    # "coverage grows by construction" guarantee requires both
    assert set(failpoints.SEAMS) <= declared, (
        set(failpoints.SEAMS) - declared
    )


# ------------------------------------------------------------- PERF401

_DISPATCH = [DispatchFn("pkg/disp.py", "B.fan_out")]


def test_perf401_per_subscriber_encode():
    bad = (
        "from codec import serialize\n"
        "class B:\n"
        "    def fan_out(self, subs, pkt):\n"
        "        for s in subs:\n"
        "            s.write(serialize(pkt, s.version))\n"
    )
    assert "PERF401" in rules_of(bad, path="pkg/disp.py",
                                 dispatch=_DISPATCH)
    # encode OUTSIDE the loop (the single-encode shape): fine
    ok = (
        "from codec import serialize\n"
        "class B:\n"
        "    def fan_out(self, subs, pkt):\n"
        "        wire = serialize(pkt, 5)\n"
        "        for s in subs:\n"
        "            s.write(wire)\n"
    )
    assert "PERF401" not in rules_of(ok, path="pkg/disp.py",
                                     dispatch=_DISPATCH)
    # a closure DEFINED in the loop is not a per-subscriber encode
    ok2 = (
        "from codec import serialize\n"
        "class B:\n"
        "    def fan_out(self, subs, pkt):\n"
        "        for s in subs:\n"
        "            def render():\n"
        "                return serialize(pkt, 5)\n"
        "            s.renderer = render\n"
    )
    assert "PERF401" not in rules_of(ok2, path="pkg/disp.py",
                                     dispatch=_DISPATCH)
    # an unrelated module is not checked
    assert "PERF401" not in rules_of(bad, path="pkg/other.py",
                                     dispatch=_DISPATCH)
    # suppression works like every other rule
    sup = bad.replace(
        "s.write(serialize(pkt, s.version))",
        "s.write(serialize(pkt, s.version))"
        "  # brokerlint: ignore[PERF401]",
    )
    assert "PERF401" not in rules_of(sup, path="pkg/disp.py",
                                     dispatch=_DISPATCH)


def test_perf401_declared_function_must_exist():
    """A renamed/deleted dispatch function is itself a finding, so the
    declaration list cannot silently rot."""
    gone = "class B:\n    def other(self):\n        return 1\n"
    assert "PERF401" in rules_of(gone, path="pkg/disp.py",
                                 dispatch=_DISPATCH)


# ------------------------------------------------------------- PERF402

def test_perf402_per_delivery_clock():
    bad = (
        "import time\n"
        "class B:\n"
        "    def fan_out(self, subs):\n"
        "        for s in subs:\n"
        "            s.ts = time.time()\n"
    )
    assert "PERF402" in rules_of(bad, path="pkg/disp.py",
                                 dispatch=_DISPATCH)
    # datetime-shaped per-iteration clocks fire too
    bad2 = bad.replace("time.time()", "datetime.now()")
    assert "PERF402" in rules_of(bad2, path="pkg/disp.py",
                                 dispatch=_DISPATCH)
    # the clock hoisted above the loop (one read per run): fine
    ok = (
        "import time\n"
        "class B:\n"
        "    def fan_out(self, subs):\n"
        "        now = time.time()\n"
        "        for s in subs:\n"
        "            s.ts = now\n"
    )
    assert "PERF402" not in rules_of(ok, path="pkg/disp.py",
                                     dispatch=_DISPATCH)
    # a closure DEFINED in the loop is not a per-delivery clock
    ok2 = (
        "import time\n"
        "class B:\n"
        "    def fan_out(self, subs):\n"
        "        for s in subs:\n"
        "            def stamp():\n"
        "                return time.time()\n"
        "            s.stamp = stamp\n"
    )
    assert "PERF402" not in rules_of(ok2, path="pkg/disp.py",
                                     dispatch=_DISPATCH)
    # an unrelated module is not checked
    assert "PERF402" not in rules_of(bad, path="pkg/other.py",
                                     dispatch=_DISPATCH)


def test_perf402_suppression_comment():
    sup = (
        "import time\n"
        "class B:\n"
        "    def fan_out(self, subs):\n"
        "        for s in subs:\n"
        "            s.ts = time.time()"
        "  # brokerlint: ignore[PERF402]\n"
    )
    assert "PERF402" not in rules_of(sup, path="pkg/disp.py",
                                     dispatch=_DISPATCH)
    # suppressing PERF402 does not silence a PERF401 on the same line
    both = (
        "from codec import serialize\n"
        "import time\n"
        "class B:\n"
        "    def fan_out(self, subs, pkt):\n"
        "        for s in subs:\n"
        "            s.write(serialize(pkt, time.time()))"
        "  # brokerlint: ignore[PERF402]\n"
    )
    assert "PERF401" in rules_of(both, path="pkg/disp.py",
                                 dispatch=_DISPATCH)
    assert "PERF402" not in rules_of(both, path="pkg/disp.py",
                                     dispatch=_DISPATCH)


def test_perf401_declared_functions_exist_in_repo():
    """The shipped DISPATCH_FUNCS point at real functions (the repo
    gate below would fail with `missing` findings otherwise — this
    just localizes the failure)."""
    repo = Path(__file__).resolve().parents[1]
    for d in DISPATCH_FUNCS:
        assert (repo / d.path_suffix).exists(), d


# ------------------------------------------------------------ the gate

def test_repo_has_no_findings_beyond_baseline():
    """The tier-1 gate: zero NEW findings over emqx_tpu/, and zero
    STALE baseline entries (fixed debt must leave the baseline so it
    only ever shrinks)."""
    findings = run_lint(["emqx_tpu"])
    baseline = load_baseline(DEFAULT_BASELINE)
    new, stale = diff_baseline(findings, baseline)
    assert not new, "new brokerlint findings:\n" + "\n".join(
        f.render() for f in new
    )
    assert not stale, (
        "stale baseline entries (fixed? remove them):\n"
        + "\n".join(sorted(stale))
    )


def test_baseline_diff_is_count_aware():
    """Fingerprints are line-number free, so two identical-shape
    violations in one function collide — the diff must compare COUNTS
    or one baseline entry would mask a newly added duplicate."""
    from collections import Counter

    src = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)\n"
        "    time.sleep(2)\n"
    )
    findings = analyze_source(src)
    assert [f.rule for f in findings] == ["ASYNC101", "ASYNC101"]
    fp = findings[0].fingerprint
    assert findings[1].fingerprint == fp
    # one baselined, a second added later: the second is NEW
    new, stale = diff_baseline(findings, Counter({fp: 1}))
    assert len(new) == 1 and not stale
    # two baselined, one fixed: the burned-down copy reads stale
    new, stale = diff_baseline(findings[:1], Counter({fp: 2}))
    assert not new and stale == {fp}


def test_baseline_is_empty():
    """PR 3 burned the baseline to ZERO (the kafka/mongo serialized
    round-trips now pipeline).  It must stay empty: new debt takes a
    justified inline `# brokerlint: ignore[..]` at the site — or gets
    fixed — never a baseline entry."""
    lines = Path(DEFAULT_BASELINE).read_text().splitlines()
    entries = [l for l in lines if l.strip()
               and not l.strip().startswith("#")]
    assert entries == [], (
        "brokerlint baseline must stay empty:\n" + "\n".join(entries)
    )


def test_cli_matches_gate():
    """`python -m tools.brokerlint` (what CI/dev runs) agrees with the
    pytest gate: exit 0, and --json round-trips."""
    repo = Path(__file__).resolve().parents[1]
    proc = subprocess.run(
        [sys.executable, "-m", "tools.brokerlint", "--json"],
        cwd=repo, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    import json

    out = json.loads(proc.stdout)
    assert out["new"] == []
    assert out["stale_baseline"] == []
