"""exhook CLIENT mode: this broker calls out to an external
HookProvider (the reference's own direction,
emqx_exhook_handler.erl:230-236) — round-trip against a stub provider
that mutates publishes, vetoes auth, and observes notifications;
plus the deny/ignore failure policy and the circuit breaker."""

import threading
import time
from concurrent import futures

import grpc
import pytest

from emqx_tpu.broker.broker import Broker
from emqx_tpu.config import BrokerConfig
from emqx_tpu.exhook import pb
from emqx_tpu.exhook.client import SERVICE, ExhookClient
from emqx_tpu.message import Message
from tests_fakes import FakeChannel


def attach(broker, cid, flt):
    ch = FakeChannel()
    broker.cm.open_session(True, cid, ch)
    broker.subscribe(cid, flt, __import__(
        "emqx_tpu.broker.session", fromlist=["SubOpts"]).SubOpts(qos=0))
    return ch


class StubProvider:
    """Minimal HookProvider: wants message.publish + auth + a few
    notifies; rewrites payloads, denies user 'mallory', drops topic
    'secret/x'."""

    def __init__(self, hooks=None):
        self.hooks = hooks or [
            "message.publish", "client.authenticate",
            "client.authorize", "session.created",
        ]
        self.seen = []
        self.lock = threading.Lock()
        self.delay = 0.0  # simulated provider latency (verdict RPCs)

    def _record(self, name, req):
        with self.lock:
            self.seen.append((name, req))

    def handlers(self):
        def unary(fn, req_cls, resp_cls):
            return grpc.unary_unary_rpc_method_handler(
                fn,
                request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )

        def loaded(req, ctx):
            self._record("loaded", req)
            return pb.LoadedResponse(
                hooks=[pb.HookSpec(name=n, topics=["#"])
                       for n in self.hooks]
            )

        def unloaded(req, ctx):
            self._record("unloaded", req)
            return pb.EmptySuccess()

        def on_publish(req, ctx):
            self._record("publish", req)
            if self.delay:
                time.sleep(self.delay)
            m = req.message
            if m.topic == "secret/x":
                out = pb.Message()
                out.CopyFrom(m)
                out.headers["allow_publish"] = "false"
                return pb.ValuedResponse(
                    type=pb.ValuedResponse.STOP_AND_RETURN, message=out
                )
            out = pb.Message()
            out.CopyFrom(m)
            out.payload = m.payload + b"!ext"
            return pb.ValuedResponse(
                type=pb.ValuedResponse.CONTINUE, message=out
            )

        def on_auth(req, ctx):
            self._record("auth", req)
            ok = req.clientinfo.username != "mallory"
            return pb.ValuedResponse(
                type=pb.ValuedResponse.STOP_AND_RETURN, bool_result=ok
            )

        def on_authz(req, ctx):
            self._record("authz", req)
            ok = not req.topic.startswith("forbidden/")
            return pb.ValuedResponse(
                type=pb.ValuedResponse.STOP_AND_RETURN, bool_result=ok
            )

        def notify(name):
            def h(req, ctx):
                self._record(name, req)
                return pb.EmptySuccess()
            return h

        return {
            "OnProviderLoaded": unary(
                loaded, pb.ProviderLoadedRequest, pb.LoadedResponse),
            "OnProviderUnloaded": unary(
                unloaded, pb.ProviderUnloadedRequest, pb.EmptySuccess),
            "OnMessagePublish": unary(
                on_publish, pb.MessagePublishRequest, pb.ValuedResponse),
            "OnClientAuthenticate": unary(
                on_auth, pb.ClientAuthenticateRequest, pb.ValuedResponse),
            "OnClientAuthorize": unary(
                on_authz, pb.ClientAuthorizeRequest, pb.ValuedResponse),
            "OnSessionCreated": unary(
                notify("session.created"), pb.SessionCreatedRequest,
                pb.EmptySuccess),
        }


@pytest.fixture()
def provider():
    stub = StubProvider()
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler(SERVICE, stub.handlers()),
    ))
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    yield stub, port
    server.stop(0)


def make_client(port, **kw):
    broker = Broker(BrokerConfig())
    client = ExhookClient(
        broker, "test", f"127.0.0.1:{port}", timeout=3.0, **kw
    )
    client.start()
    return broker, client


def test_publish_mutation_round_trip(provider):
    stub, port = provider
    broker, client = make_client(port)
    try:
        assert "message.publish" in [n for n, _ in client._registered]

        # subscriber sees the provider-mutated payload
        ch = attach(broker, "c1", "t/#")
        broker.publish(Message(topic="t/1", payload=b"hi", qos=0))
        assert [p.payload for p in ch.sent] == [b"hi!ext"]

        # provider veto: secret topic never delivers
        broker.subscribe("c1", "secret/#", __import__(
            "emqx_tpu.broker.session",
            fromlist=["SubOpts"]).SubOpts(qos=0))
        broker.publish(Message(topic="secret/x", payload=b"s", qos=0))
        assert all(p.topic != "secret/x" for p in ch.sent)

        # $-topics are never sent out (reference skips sys messages)
        n_before = len([s for s in stub.seen if s[0] == "publish"])
        broker.publish(Message(
            topic="$SYS/x", payload=b"s", qos=0, sys=True
        ))
        assert len(
            [s for s in stub.seen if s[0] == "publish"]
        ) == n_before
    finally:
        client.stop()
    assert any(n == "unloaded" for n, _ in stub.seen)


def test_auth_verdicts(provider):
    stub, port = provider
    broker, client = make_client(port)
    try:
        from emqx_tpu.access import ClientInfo

        ok, _ = broker.access.authenticate(
            ClientInfo(clientid="a", username="alice")
        )
        assert ok
        ok, _ = broker.access.authenticate(
            ClientInfo(clientid="m", username="mallory")
        )
        assert not ok

        from emqx_tpu.access import PUBLISH
        assert broker.access.authorize(
            ClientInfo(clientid="a"), PUBLISH, "ok/t"
        )
        assert not broker.access.authorize(
            ClientInfo(clientid="a"), PUBLISH, "forbidden/t"
        )
    finally:
        client.stop()


def test_notify_hooks_fire(provider):
    stub, port = provider
    broker, client = make_client(port)
    try:
        broker.hooks.run("session.created", "some-client")
        deadline = time.time() + 3
        while time.time() < deadline:
            if any(n == "session.created" for n, _ in stub.seen):
                break
            time.sleep(0.05)
        assert any(n == "session.created" for n, _ in stub.seen)
    finally:
        client.stop()


def test_failure_policy_and_breaker(provider):
    stub, port = provider
    # deny: a dead provider drops publishes / denies auth
    broker, client = make_client(port, failure_action="deny",
                                 breaker_threshold=2,
                                 breaker_window=0.3)
    from emqx_tpu.access import ClientInfo

    ch = attach(broker, "c1", "t/#")
    try:
        # kill the transport out from under the client
        client._channel.close()
        client._channel = grpc.insecure_channel("127.0.0.1:1")
        client._methods.clear()

        broker.publish(Message(topic="t/1", payload=b"x", qos=0))
        assert ch.sent == []  # fail-closed: dropped
        ok, _ = broker.access.authenticate(ClientInfo(clientid="a"))
        assert not ok
        # breaker is open after 2 failures: calls fail fast
        before = client.stats["calls"]
        broker.publish(Message(topic="t/2", payload=b"x", qos=0))
        assert client.stats["calls"] == before
        assert client.stats["fast_failed"] >= 1
        assert client.info()["breaker_open"]
    finally:
        client.stop()

    # ignore: a dead provider fails open (local chain continues)
    broker2, client2 = make_client(port, failure_action="ignore")
    ch2 = attach(broker2, "c1", "t/#")
    try:
        client2._channel.close()
        client2._channel = grpc.insecure_channel("127.0.0.1:1")
        client2._methods.clear()
        broker2.publish(Message(topic="t/1", payload=b"y", qos=0))
        assert [p.payload for p in ch2.sent] == [b"y"]
        ok, _ = broker2.access.authenticate(ClientInfo(clientid="a"))
        assert ok  # allow_anonymous default continues to apply
    finally:
        client2.stop()


def test_unreachable_provider_fails_closed_then_recovers(provider):
    """A provider down at dial time with failure_action=deny must fail
    CLOSED (not silently skip), and retry() completes the real
    registration once the server is reachable."""
    stub, port = provider
    broker = Broker(BrokerConfig())
    client = ExhookClient(broker, "t", "127.0.0.1:1",  # nothing there
                          timeout=0.5, failure_action="deny")
    client.start()  # must not raise
    assert not client.loaded
    ch = attach(broker, "c1", "t/#")
    broker.publish(Message(topic="t/1", payload=b"x", qos=0))
    assert ch.sent == []  # fail-closed drop
    from emqx_tpu.access import ClientInfo
    ok, _ = broker.access.authenticate(ClientInfo(clientid="a"))
    assert not ok

    # the provider "comes up": point at the live stub and retry
    client._channel.close()
    client._channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    client._methods.clear()
    client.retry()
    assert client.loaded
    broker.publish(Message(topic="t/2", payload=b"hi", qos=0))
    assert [p.payload for p in ch.sent] == [b"hi!ext"]
    client.stop()

    # ignore policy: down provider fails open at dial time
    broker2 = Broker(BrokerConfig())
    client2 = ExhookClient(broker2, "t2", "127.0.0.1:1",
                           timeout=0.5, failure_action="ignore")
    client2.start()
    ch2 = attach(broker2, "c1", "t/#")
    broker2.publish(Message(topic="t/1", payload=b"y", qos=0))
    assert [p.payload for p in ch2.sent] == [b"y"]
    client2.stop()


def test_async_verdicts_keep_loop_live(provider):
    """Advisor r4 (medium): verdict RPCs must not block the event
    loop.  With a slow provider, the async hook path (used by the
    publish batcher and the channel's deferred authorize) must let
    other loop tasks run during the round-trip, and fold the same
    verdicts as the sync path."""
    import asyncio

    stub, port = provider
    broker, client = make_client(port)
    try:
        # verdict hooks advertise async twins; the access layer and
        # batcher key their off-loop deferral on these
        assert broker.hooks.has_async("message.publish")
        assert broker.access.has_async_authz_hooks
        assert broker.access.has_async_authn

        stub.delay = 0.3

        async def main():
            ticks = 0

            async def ticker():
                nonlocal ticks
                while True:
                    ticks += 1
                    await asyncio.sleep(0.01)

            t = asyncio.create_task(ticker())
            out = await broker.hooks.run_fold_async(
                "message.publish", (),
                Message(topic="t/1", payload=b"x", qos=0),
            )
            from emqx_tpu.access import ClientInfo, PUBLISH
            allowed = await broker.access.authorize_async(
                ClientInfo(clientid="a"), PUBLISH, "ok/t")
            denied = await broker.access.authorize_async(
                ClientInfo(clientid="a"), PUBLISH, "forbidden/t")
            t.cancel()
            return ticks, out, allowed, denied

        ticks, out, allowed, denied = asyncio.run(main())
        assert out.payload == b"x!ext"  # provider mutation folded
        assert allowed and not denied
        # 3 sequential 0.3s RPCs; a BLOCKED loop yields 0-1 ticks while
        # a live one yields dozens — the bound only separates those two
        # regimes (contended CI boxes tick far below the theoretical
        # ~90, so anything tighter flakes)
        assert ticks >= 4
    finally:
        stub.delay = 0.0
        client.stop()


def test_batcher_prepare_uses_async_hook_path(provider):
    """End-to-end through the PublishBatcher: a window folded against
    a slow provider must not starve concurrent loop work."""
    import asyncio

    stub, port = provider
    broker, client = make_client(port)
    ch = attach(broker, "c1", "t/#")
    try:
        stub.delay = 0.2

        async def main():
            from emqx_tpu.broker.broker import PublishBatcher

            batcher = PublishBatcher(broker, window=0.001)
            await batcher.start()
            ticks = 0

            async def ticker():
                nonlocal ticks
                while True:
                    ticks += 1
                    await asyncio.sleep(0.01)

            t = asyncio.create_task(ticker())
            n = await asyncio.wait_for(
                batcher.publish(Message(topic="t/1", payload=b"e",
                                        qos=1)),
                timeout=10,
            )
            t.cancel()
            await batcher.stop()
            return ticks, n

        ticks, n = asyncio.run(main())
        assert n == 1
        assert [p.payload for p in ch.sent] == [b"e!ext"]
        # a blocked loop ticks ~0 during the 0.2s RPC; loose threshold
        # (contended CI boxes tick far below the theoretical ~20)
        assert ticks >= 3
    finally:
        stub.delay = 0.0
        client.stop()
