"""Sharded-index tests on the virtual 8-device CPU mesh: the filter set
partitioned over the 'sub' axis, publish batches over 'pub', matched via
shard_map — results must equal the single-host oracle."""

import random

import jax
import numpy as np
import pytest

from emqx_tpu import topic as T
from emqx_tpu.ops.dictionary import TokenDict
from emqx_tpu.parallel.sharded import (
    ShardedMatchEngine,
    build_sharded_index,
    make_mesh,
)

from test_match_engine import WORDS, random_filter, random_topic


def test_mesh_shape():
    mesh = make_mesh(8, sub=4)
    assert mesh.shape == {"sub": 4, "pub": 2}
    mesh = make_mesh(8)
    assert mesh.shape["sub"] * mesh.shape["pub"] == 8


@pytest.mark.parametrize("seed", range(3))
def test_sharded_equivalence(seed):
    rng = random.Random(seed)
    filters = []
    seen = set()
    for fid in range(400):
        flt = random_filter(rng)
        try:
            T.validate_filter(flt)
        except ValueError:
            continue
        if flt in seen:
            continue
        seen.add(flt)
        filters.append((fid, T.words(flt)))

    mesh = make_mesh(8, sub=4)
    tdict = TokenDict()
    idx = build_sharded_index(filters, tdict, n_shards=4)
    eng = ShardedMatchEngine(mesh, idx, tdict, f_width=8, m_cap=64)

    topics = [random_topic(rng) for _ in range(50)]
    got = eng.match_batch(topics)
    for t, g in zip(topics, got):
        ws = T.words(t)
        want = {fid for fid, fw in filters if T.match_words(ws, fw)}
        assert g == want, (t, g, want)


def test_shard_geometry_uniform():
    rng = random.Random(7)
    filters = [(i, T.words(random_filter(rng))) for i in range(100)]
    filters = [
        (i, ws)
        for i, ws in filters
        if not any("#" == w for w in ws[:-1])
    ]
    idx = build_sharded_index(filters, TokenDict(), n_shards=4)
    ht, node_rows, salts = idx.tables
    # all shards stacked with one shared geometry per table
    assert ht.shape[0] == node_rows.shape[0] == 4
    assert all(a.fp_rows.shape == ht.shape[1:] for a in idx.shards)
    assert all(
        node_rows.shape[1] >= a.node_rows.shape[0] for a in idx.shards
    )


@pytest.mark.parametrize("kind", ["single", "sharded"])
@pytest.mark.parametrize("seed", [3, 11])
def test_unified_engine_churn_equivalence(kind, seed):
    """VERDICT r1 #9: one mutation/match contract, two engines — the
    same randomized churn suite must pass against both."""
    import random

    from emqx_tpu import topic as T
    from emqx_tpu.engine import MatchEngine
    from emqx_tpu.parallel.sharded import ShardedMatchEngine, make_mesh

    rng = random.Random(seed)
    if kind == "single":
        eng = MatchEngine(max_levels=8, rebuild_threshold=200)
    else:
        eng = ShardedMatchEngine(
            make_mesh(4), max_levels=8, rebuild_threshold=200
        )
    live = {}
    words_pool = ["a", "b", "c", "+", "dev", "x1"]
    fid = 0
    for _ in range(4):
        for _ in range(120):
            depth = rng.randint(1, 4)
            ws = [rng.choice(words_pool) for _ in range(depth)]
            if rng.random() < 0.3:
                ws.append("#")
            flt = "/".join(ws)
            try:
                T.validate_filter(flt)
            except ValueError:
                continue
            eng.insert(flt, fid)
            live[fid] = flt
            fid += 1
        for victim in rng.sample(sorted(live), 15):
            eng.delete(victim)
            del live[victim]
        topics = [
            "/".join(
                rng.choice(["a", "b", "c", "dev", "x1", "zz"])
                for _ in range(rng.randint(1, 5))
            )
            for _ in range(25)
        ]
        got = eng.match_batch(topics)
        for t, g in zip(topics, got):
            want = {
                f
                for f, w in live.items()
                if T.match_words(T.words(t), T.words(w))
            }
            assert g == want, (kind, t, g, want)
    eng.rebuild()
    got = eng.match_batch(topics)
    for t, g in zip(topics, got):
        want = {
            f for f, w in live.items() if T.match_words(T.words(t), T.words(w))
        }
        assert g == want, (kind, "post-rebuild", t, g, want)


def test_adopted_exact_filters_deletable():
    """Code-review r2: non-wildcard filters seeded from a pre-built
    index must be deletable (routed through exact, not frozen in the
    base snapshot)."""
    from emqx_tpu.ops.dictionary import TokenDict
    from emqx_tpu.parallel.sharded import (
        ShardedMatchEngine,
        build_sharded_index,
        make_mesh,
    )

    mesh = make_mesh(4)
    tdict = TokenDict()
    idx = build_sharded_index(
        [(0, ("exact", "a", "b")), (1, ("w", "+")), (2, ("w", "q"))],
        tdict,
        n_shards=4,
        max_levels=8,
    )
    eng = ShardedMatchEngine(mesh, idx, tdict)
    assert eng.match("exact/a/b") == {0}
    assert eng.match("w/q") == {1, 2}
    assert eng.delete(0)
    assert eng.match("exact/a/b") == set()
    assert eng.delete(2)
    assert eng.match("w/q") == {1}


def test_sharded_engine_at_scale():
    """VERDICT r2 weak #6: the sharded engine at 100k filters on the
    8-device CPU mesh — correctness against the host oracle on a
    sampled batch, plus a recorded (not asserted) throughput datapoint
    and a sharded-vs-single-chip comparison."""
    import time as _time

    import numpy as np

    from emqx_tpu.engine import MatchEngine

    n = 100_000
    rng = np.random.default_rng(3)
    filters = []
    for i in range(n):
        k = i % 10
        if k < 5:
            filters.append((i, f"vehicles/v{i % 6000}/sensors/#"))
        elif k < 7:
            filters.append((i, f"dev/g{i % 2500}/+/d{i % 7}"))
        elif k < 9:
            filters.append((i, f"site/+/floor/f{i % 2500}/#"))
        else:
            filters.append((i, f"alerts/z{i % 1200}/+/+"))

    mesh = make_mesh()
    sharded = ShardedMatchEngine(mesh=mesh, max_levels=8, rebuild_threshold=10**9)
    single = MatchEngine(max_levels=8, rebuild_threshold=10**9)
    for fid, flt in filters:
        sharded.insert(flt, fid)
        single.insert(flt, fid)
    t0 = _time.perf_counter()
    sharded.rebuild()
    t_build = _time.perf_counter() - t0
    single.rebuild()

    topics = []
    for i in range(512):
        k = i % 4
        if k == 0:
            topics.append(f"vehicles/v{i % 6000}/sensors/temp")
        elif k == 1:
            topics.append(f"dev/g{i % 2500}/x/d{i % 7}")
        elif k == 2:
            topics.append(f"site/s1/floor/f{i % 2500}/a")
        else:
            topics.append(f"nomatch/q{i}")

    got = sharded.match_batch(topics)  # compile + match
    want = single.match_batch(topics)
    for t, g, w in zip(topics, got, want):
        assert g == w, t
    # every topic with matches saw real fan-out (index is populated)
    assert sum(len(g) for g in got) > 1000

    t0 = _time.perf_counter()
    for _ in range(3):
        sharded.match_batch(topics)
    rate = 3 * len(topics) / (_time.perf_counter() - t0)
    print(
        f"\nsharded@100k filters: build {t_build:.2f}s, "
        f"{rate:,.0f} topics/s on the {mesh.shape['sub']}-dev CPU mesh"
    )
