"""OCPP-J gateway: charge point over WebSocket bridged to MQTT
topics (emqx_gateway_ocpp parity)."""

import asyncio
import base64
import json
import os

from emqx_tpu.broker import ws as W
from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


class OcppClient:
    """Raw OCPP-J websocket charge-point client (masked frames)."""

    def __init__(self, port, cpid, proto="ocpp1.6"):
        self.port = port
        self.cpid = cpid
        self.proto = proto

    async def handshake_status(self) -> bytes:
        self.r, self.w = await asyncio.open_connection(
            "127.0.0.1", self.port
        )
        key = base64.b64encode(os.urandom(16)).decode()
        self.w.write((
            f"GET /ocpp/{self.cpid} HTTP/1.1\r\nHost: x\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\n"
            "Sec-WebSocket-Version: 13\r\n"
            f"Sec-WebSocket-Protocol: {self.proto}\r\n\r\n"
        ).encode())
        await self.w.drain()
        return await self.r.readuntil(b"\r\n\r\n")

    async def connect(self):
        status = await self.handshake_status()
        assert b"101" in status.split(b"\r\n")[0], status
        assert b"Sec-WebSocket-Protocol: ocpp1.6" in status
        return self

    def send(self, arr):
        self.w.write(W.frame(
            0x1, json.dumps(arr).encode(), mask=os.urandom(4)
        ))

    async def recv(self, timeout=3.0):
        while True:
            opcode, fin, payload = await asyncio.wait_for(
                W.read_frame(self.r), timeout
            )
            if opcode == 0x1:
                return json.loads(payload)

    def close(self):
        self.w.close()


def test_ocpp_call_result_and_downlink():
    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.gateways = [
            {"type": "ocpp", "bind": "127.0.0.1", "port": 0}
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        gw = srv.broker.gateways.get("ocpp")

        csms = TestClient(srv.listeners[0].port, "csms")
        await csms.connect()
        await csms.subscribe("ocpp/cp/#", qos=1)

        cp = await OcppClient(gw.port, "CP001").connect()

        # -------- upstream CALL -> ocpp/cp/CP001
        cp.send([2, "m1", "BootNotification",
                 {"chargePointModel": "X1", "chargePointVendor": "emq"}])
        pub = await csms.recv_publish()
        assert pub.topic == "ocpp/cp/CP001"
        body = json.loads(pub.payload)
        assert body["type"] == 2 and body["action"] == "BootNotification"
        assert body["payload"]["chargePointModel"] == "X1"

        # -------- downstream CALL: csms -> ocpp/cs/CP001 -> socket
        await csms.publish("ocpp/cs/CP001", json.dumps({
            "type": 2, "id": "srv-1", "action": "RemoteStartTransaction",
            "payload": {"idTag": "ABC"},
        }).encode(), qos=1)
        arr = await cp.recv()
        assert arr == [2, "srv-1", "RemoteStartTransaction",
                       {"idTag": "ABC"}]

        # -------- the charge point's CALLRESULT -> cp/CP001/Reply
        cp.send([3, "srv-1", {"status": "Accepted"}])
        pub = await csms.recv_publish()
        assert pub.topic == "ocpp/cp/CP001/Reply"
        body = json.loads(pub.payload)
        assert body["type"] == 3 and body["payload"]["status"] == \
            "Accepted"

        # -------- CALLERROR goes to the Reply topic too
        cp.send([4, "srv-2", "NotSupported", "nope", {}])
        pub = await csms.recv_publish()
        assert pub.topic == "ocpp/cp/CP001/Reply"
        body = json.loads(pub.payload)
        assert body["type"] == 4 and body["error_code"] == "NotSupported"

        # -------- malformed frame answers a ProtocolError on-socket
        cp.send({"not": "an array"})
        arr = await cp.recv()
        assert arr[0] == 4 and arr[2] == "ProtocolError"

        cp.close()
        await csms.disconnect()
        await srv.stop()

    run(t())


def test_ocpp_rejects_bad_cpid_and_subprotocol():
    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.gateways = [
            {"type": "ocpp", "bind": "127.0.0.1", "port": 0}
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        gw = srv.broker.gateways.get("ocpp")

        # wildcard-smuggling cpids must not become subscriptions
        for cpid in ("%23", "%2B", "a%2Fb", "+"):
            c = OcppClient(gw.port, cpid)
            status = await c.handshake_status()
            assert b"101" in status.split(b"\r\n")[0]
            # server closes without attaching a session
            op, _, _ = await asyncio.wait_for(
                W.read_frame(c.r), 3.0
            )
            assert op == 0x8  # close frame
            c.close()
        assert srv.broker.cm.lookup("#") is None
        assert srv.broker.cm.lookup("+") is None

        # unsupported subprotocol: upgrade rejected outright
        c = OcppClient(gw.port, "CP009", proto="ocpp2.0.1")
        status = await c.handshake_status()
        assert b"400" in status.split(b"\r\n")[0]
        c.close()
        await srv.stop()

    run(t())


def test_ocpp_downlink_flood_beyond_inflight_window():
    """Deliveries settle on socket handoff: far more than the 32-slot
    inflight window must arrive (a silent stall at 32 was the bug)."""

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.gateways = [
            {"type": "ocpp", "bind": "127.0.0.1", "port": 0}
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        gw = srv.broker.gateways.get("ocpp")

        csms = TestClient(srv.listeners[0].port, "csms-f")
        await csms.connect()
        await csms.subscribe("ocpp/cp/#", qos=1)
        cp = await OcppClient(gw.port, "CP077").connect()
        cp.send([2, "m1", "Heartbeat", {}])
        await csms.recv_publish()  # the heartbeat (cp is attached)

        for i in range(100):
            await csms.publish("ocpp/cs/CP077", json.dumps({
                "type": 2, "id": f"c{i}", "action": "GetConfiguration",
                "payload": {},
            }).encode(), qos=1)
        got = set()
        for _ in range(100):
            arr = await cp.recv()
            got.add(arr[1])
        assert got == {f"c{i}" for i in range(100)}

        cp.close()
        await csms.disconnect()
        await srv.stop()

    run(t())


def test_ocpp_session_registered_and_cleanup():
    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.gateways = [
            {"type": "ocpp", "bind": "127.0.0.1", "port": 0}
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        gw = srv.broker.gateways.get("ocpp")

        cp = await OcppClient(gw.port, "CP002").connect()
        cp.send([2, "m1", "Heartbeat", {}])
        for _ in range(50):
            if srv.broker.cm.connected("CP002"):
                break
            await asyncio.sleep(0.02)
        assert srv.broker.cm.connected("CP002")
        cp.close()
        for _ in range(100):
            if not srv.broker.cm.connected("CP002"):
                break
            await asyncio.sleep(0.02)
        assert not srv.broker.cm.connected("CP002")
        await srv.stop()

    run(t())


def test_ocpp_schema_validation():
    """OCPP 1.6 core-profile CALL payloads validate against the
    per-action schemas: violations answer CALLERROR
    TypeConstraintViolation on-socket and never reach the broker;
    valid frames and unknown actions pass."""

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.gateways = [
            {"type": "ocpp", "bind": "127.0.0.1", "port": 0}
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        gw = srv.broker.gateways.get("ocpp")

        csms = TestClient(srv.listeners[0].port, "csms")
        await csms.connect()
        await csms.subscribe("ocpp/cp/#", qos=1)
        cp = await OcppClient(gw.port, "CP9").connect()

        # missing required field
        cp.send([2, "b1", "BootNotification",
                 {"chargePointModel": "X1"}])
        arr = await cp.recv()
        assert arr[0] == 4 and arr[1] == "b1"
        assert arr[2] == "TypeConstraintViolation"

        # wrong type
        cp.send([2, "s1", "StatusNotification",
                 {"connectorId": "one", "errorCode": "NoError",
                  "status": "Available"}])
        arr = await cp.recv()
        assert arr[2] == "TypeConstraintViolation"

        # enum violation
        cp.send([2, "s2", "StatusNotification",
                 {"connectorId": 1, "errorCode": "NoError",
                  "status": "Snoozing"}])
        arr = await cp.recv()
        assert arr[2] == "TypeConstraintViolation"

        # valid frames reach the broker
        cp.send([2, "s3", "StatusNotification",
                 {"connectorId": 1, "errorCode": "NoError",
                  "status": "Charging"}])
        pub = await csms.recv_publish()
        assert json.loads(pub.payload)["payload"]["status"] == \
            "Charging"

        # unknown actions pass through unvalidated (strict=false)
        cp.send([2, "d1", "DataTransfer", {"vendorId": "x",
                                           "weird": [1, 2]}])
        pub = await csms.recv_publish()
        assert json.loads(pub.payload)["action"] == "DataTransfer"

        cp.close()
        await csms.disconnect()
        await srv.stop()

    run(t())
