"""Native batch filter encoder vs the Python TokenDict loop: ids,
bodies, hash flags, and new-word mirroring must agree bit-for-bit."""

import numpy as np
import pytest

from emqx_tpu.ops.dictionary import (PAD_TOK, PLUS_TOK, TokenDict,
                                     encode_filter)
from emqx_tpu.ops.tokdict_native import load


FILTERS = [
    ("a", "b", "c"),
    ("a", "+", "c"),
    ("#",),
    ("a", "#"),
    ("a", "", "#"),
    ("", "#"),
    ("+",),
    ("+", "+"),
    ("$SYS", "broker", "#"),
    ("x" * 100, "y"),
    ("a", "b"),        # repeats reuse ids
    ("utf8", "日本語", "résumé"),
    ("",),
]


@pytest.mark.skipif(load() is None, reason="native tokdict unavailable")
def test_native_matches_python_encoder():
    max_levels = 8
    # python reference
    td_py = TokenDict()
    ref = [encode_filter(td_py, ws) for ws in FILTERS]

    td = TokenDict()
    n = len(FILTERS)
    mat = np.full((n, max_levels), PAD_TOK, np.int32)
    blen = np.zeros(n, np.int32)
    ish = np.zeros(n, bool)
    items = [(i, ws) for i, ws in enumerate(FILTERS)]
    assert td.encode_filters_into(items, max_levels, mat, blen, ish)

    for i, (body, hsh) in enumerate(ref):
        assert bool(ish[i]) == hsh, FILTERS[i]
        assert int(blen[i]) == len(body), FILTERS[i]
        assert mat[i, : len(body)].tolist() == body, FILTERS[i]
        assert (mat[i, len(body):] == PAD_TOK).all()
    # the python mirror ends up identical to the pure-python dict
    assert td._ids == td_py._ids
    # and subsequent python-side adds stay aligned with the mirror
    wid = td.add("brand-new-word")
    assert wid == len(td._ids) - 1
    assert td.native().add("brand-new-word") == wid


@pytest.mark.skipif(load() is None, reason="native tokdict unavailable")
def test_native_rejects_too_deep():
    td = TokenDict()
    deep = tuple(f"l{i}" for i in range(10))
    mat = np.zeros((1, 4), np.int32)
    blen = np.zeros(1, np.int32)
    ish = np.zeros(1, bool)
    with pytest.raises(ValueError):
        td.encode_filters_into([(0, deep)], 4, mat, blen, ish)


@pytest.mark.skipif(load() is None, reason="native tokdict unavailable")
def test_randomized_equivalence_native_vs_python():
    import random

    rng = random.Random(7)
    words = ["a", "b", "cc", "+", "", "dev", "$x", "zz9"]
    filters = []
    for _ in range(500):
        n = rng.randint(1, 6)
        ws = [rng.choice(words) for _ in range(n)]
        if rng.random() < 0.4:
            ws.append("#")
        filters.append(tuple(ws))
    td_py = TokenDict()
    ref = [encode_filter(td_py, ws) for ws in filters]
    td = TokenDict()
    mat = np.full((len(filters), 8), PAD_TOK, np.int32)
    blen = np.zeros(len(filters), np.int32)
    ish = np.zeros(len(filters), bool)
    assert td.encode_filters_into(
        [(i, ws) for i, ws in enumerate(filters)], 8, mat, blen, ish
    )
    for i, (body, hsh) in enumerate(ref):
        assert bool(ish[i]) == hsh
        assert mat[i, : len(body)].tolist() == body
        assert int(blen[i]) == len(body)
    assert td._ids == td_py._ids


@pytest.mark.skipif(load() is None, reason="native tokdict unavailable")
def test_encode_topics_into_matches_python():
    from emqx_tpu.ops.dictionary import encode_topics, UNKNOWN_TOK
    from emqx_tpu import topic as T

    td = TokenDict()
    # register some filter words so ids exist
    mat0 = np.zeros((3, 6), np.int32); b0 = np.zeros(3, np.int32)
    h0 = np.zeros(3, bool)
    td.encode_filters_into(
        [(0, ("a", "b")), (1, ("$SYS", "x")), (2, ("deep", "", "w"))],
        6, mat0, b0, h0,
    )
    topics = ["a/b", "a/zz", "$SYS/x", "", "/", "deep//w",
              "a/b/c/d/e/f/g/h/i"]  # last: truncation at levels
    levels = 6
    want = encode_topics(td, [T.words(t) for t in topics], levels)
    n = len(topics)
    mat = np.zeros((n, levels), np.int32)
    lens = np.zeros(n, np.int32)
    dol = np.zeros(n, bool)
    td.native().encode_topics_into(topics, levels, mat, lens, dol)
    assert (mat == want[0]).all()
    assert (lens == want[1]).all()
    assert (dol == want[2]).all()
