"""Schema registry (emqx_schema_registry parity): avro binary codec
round-trips and decodes reference-style payloads, protobuf schemas
compile via protoc and round-trip, rule-engine schema_decode/encode/
check resolve names, and the REST surface registers/serves/removes
entries."""

import asyncio
import struct

import pytest

from emqx_tpu.schema_registry import (AvroSchema, ProtobufSchema,
                                      SchemaRegistry, global_registry)


AVRO_SCHEMA = {
    "type": "record",
    "name": "Telemetry",
    "fields": [
        {"name": "device", "type": "string"},
        {"name": "temp", "type": "double"},
        {"name": "seq", "type": "long"},
        {"name": "ok", "type": "boolean"},
        {"name": "tags", "type": {"type": "array", "items": "string"}},
        {"name": "attrs", "type": {"type": "map", "values": "long"}},
        {"name": "note", "type": ["null", "string"]},
        {"name": "mode", "type": {
            "type": "enum", "name": "Mode",
            "symbols": ["AUTO", "MANUAL"],
        }},
    ],
}

PROTO_SRC = """
syntax = "proto3";
message SensorReading {
  string device = 1;
  double temp = 2;
  int64 seq = 3;
  repeated string tags = 4;
}
"""


def test_avro_round_trip_all_shapes():
    s = AvroSchema(AVRO_SCHEMA)
    value = {
        "device": "v-17",
        "temp": 21.5,
        "seq": 12345678901,
        "ok": True,
        "tags": ["a", "b"],
        "attrs": {"x": 1, "y": -2},
        "note": None,
        "mode": "MANUAL",
    }
    data = s.encode(value)
    assert s.decode(data) == value
    # union non-null branch
    value["note"] = "hello"
    assert s.decode(s.encode(value)) == value
    # negative/zigzag edges
    v2 = dict(value, seq=-1, attrs={"z": -(2**40)})
    assert s.decode(s.encode(v2)) == v2


def test_avro_known_bytes():
    """Spec anchors (Avro 1.11 §binary encoding): zig-zag longs and
    length-prefixed strings — guards against a self-consistent but
    wrong codec."""
    s = AvroSchema({"type": "record", "name": "R", "fields": [
        {"name": "a", "type": "long"},
        {"name": "b", "type": "string"},
    ]})
    # long 1 -> 0x02; long -1 -> 0x01; "foo" -> 0x06 'f' 'o' 'o'
    assert s.encode({"a": 1, "b": "foo"}) == b"\x02\x06foo"
    assert s.encode({"a": -1, "b": ""}) == b"\x01\x00"
    assert s.decode(b"\x02\x06foo") == {"a": 1, "b": "foo"}


def test_avro_truncated_rejected():
    s = AvroSchema(AVRO_SCHEMA)
    with pytest.raises(ValueError):
        s.decode(b"\x02")  # truncated record


def test_protobuf_compile_and_round_trip():
    s = ProtobufSchema(PROTO_SRC)
    assert s.message_types() == ["SensorReading"]
    value = {"device": "d1", "temp": 3.5, "seq": "42",
             "tags": ["x", "y"]}
    data = s.encode(value, "SensorReading")
    out = s.decode(data, "SensorReading")
    assert out["device"] == "d1"
    assert out["tags"] == ["x", "y"]
    # cross-check against a hand-built wire payload: field 1
    # (string "d1") = 0x0A 0x02 'd' '1'
    assert data.startswith(b"\x0a\x02d1")

    with pytest.raises(ValueError):
        ProtobufSchema("syntax = nonsense;")


def test_registry_and_rule_functions():
    reg = global_registry()
    reg.add("tele", "avro", AVRO_SCHEMA)
    reg.add("sensor", "protobuf", PROTO_SRC)
    reg.add("cfg", "json", {
        "type": "object",
        "required": ["mode"],
        "properties": {"mode": {"type": "string"}},
    })
    try:
        from emqx_tpu.rules.funcs import FUNCS

        s = AvroSchema(AVRO_SCHEMA)
        payload = s.encode({
            "device": "v1", "temp": 1.0, "seq": 1, "ok": False,
            "tags": [], "attrs": {}, "note": None, "mode": "AUTO",
        })
        out = FUNCS["schema_decode"]("tele", payload)
        assert out["device"] == "v1" and out["mode"] == "AUTO"
        assert FUNCS["schema_check"]("tele", payload)
        assert not FUNCS["schema_check"]("tele", b"garbage")

        enc = FUNCS["schema_encode"]("sensor", {"device": "d9"})
        assert FUNCS["schema_decode"]("sensor", enc)["device"] == "d9"

        assert FUNCS["schema_check"]("cfg", b'{"mode": "on"}')
        assert not FUNCS["schema_check"]("cfg", b'{"other": 1}')
    finally:
        for n in ("tele", "sensor", "cfg"):
            reg.remove(n)


def test_rest_schema_crud():
    import tempfile

    from emqx_tpu.broker.listener import BrokerServer
    from emqx_tpu.config import BrokerConfig, ListenerConfig
    from api_helper import auth_session

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.api.enable = True
        cfg.api.port = 0
        cfg.api.data_dir = tempfile.mkdtemp(prefix="emqx-mgmt-")
        srv = BrokerServer(cfg)
        await srv.start()
        http, api = await auth_session(srv)
        async with http:
            async with http.post(api + "/api/v5/schema_registry", json={
                "name": "t1", "type": "avro",
                "source": {"type": "record", "name": "R", "fields": [
                    {"name": "x", "type": "long"}]},
            }) as r:
                assert r.status == 201
            async with http.post(api + "/api/v5/schema_registry", json={
                "name": "bad", "type": "protobuf",
                "source": "not a proto",
            }) as r:
                assert r.status == 400
            async with http.get(api + "/api/v5/schema_registry") as r:
                data = (await r.json())["data"]
            assert {"name": "t1", "type": "avro"} in data
            async with http.delete(
                api + "/api/v5/schema_registry/t1"
            ) as r:
                assert r.status == 204
            async with http.delete(
                api + "/api/v5/schema_registry/t1"
            ) as r:
                assert r.status == 404
        await srv.stop()

    asyncio.run(t())


def test_schema_persistence_and_backup(tmp_path):
    reg_path = str(tmp_path / "schemas.json")
    reg = SchemaRegistry(persist_path=reg_path)
    reg.add("p1", "avro", {"type": "record", "name": "R", "fields": [
        {"name": "x", "type": "long"}]})
    # a fresh registry reloads the persisted entry
    reg2 = SchemaRegistry()
    reg2.load(reg_path)
    assert reg2.decode("p1", b"\x04") == {"x": 2}
    # invalid schemas are rejected at registration
    with pytest.raises(ValueError):
        reg2.add("bad", "avro", {"type": "record", "name": "B"})
    with pytest.raises(ValueError):
        reg2.add("bad2", "avro", {"type": "wat"})
    # truncated payloads raise ValueError (never struct.error / short
    # reads)
    reg2.add("fx", "avro", {"type": "record", "name": "F", "fields": [
        {"name": "d", "type": "double"},
        {"name": "k", "type": {"type": "fixed", "name": "K",
                               "size": 4}}]})
    with pytest.raises(ValueError):
        reg2.decode("fx", b"\x00\x01")
    import struct as _struct
    with pytest.raises(ValueError):
        reg2.decode("fx", _struct.pack("<d", 1.0) + b"\x01\x02")
