"""Group-commit fsync durability: the SyncGate, the atomic metadata
helper, the three new chaos seams (``ds.store.append`` /
``ds.store.sync`` / ``ds.meta.write``), and the broker-level "acked
means durable" contract — a sync fault mid-window keeps PUBACKs parked
and retried, concurrent windows coalesce onto one flush, and detected
corruption surfaces as alarms + counters on every ops plane."""

import asyncio
import os
import time

import pytest

from emqx_tpu import failpoints as fp
from emqx_tpu.config import BrokerConfig, ListenerConfig, check_config
from emqx_tpu.ds import atomicio
from emqx_tpu.ds.durability import SyncGate
from emqx_tpu.ds.persist import DurableSessions
from emqx_tpu.message import Message


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.clear()
    yield
    fp.clear()


# ------------------------------------------------------------ SyncGate


def test_gate_watermarks_and_sync_now():
    flushed = []
    gate = SyncGate(lambda: flushed.append(1))
    assert not gate.dirty
    gate.sync_now()
    assert flushed == []  # nothing unsynced: no disk touch
    gate.mark_appended(3)
    assert gate.dirty and gate.unsynced == 3
    gate.sync_now()
    assert flushed == [1]
    assert not gate.dirty and gate.sync_count == 1
    gate.sync_now()
    assert flushed == [1]  # idempotent


def test_gate_wait_durable_coalesces_windows():
    """N concurrent windows parked on the gate are released by at most
    two flushes (one in flight + one covering the stragglers) — the
    group-commit amortization claim."""
    calls = []

    def slow_sync():
        calls.append(1)
        time.sleep(0.02)

    gate = SyncGate(slow_sync)

    async def main():
        async def window(i):
            gate.mark_appended(1)
            await gate.wait_durable()

        await asyncio.gather(*(window(i) for i in range(16)))

    run(main())
    assert len(calls) <= 3
    assert gate.parked == 0 and not gate.dirty


def test_gate_fault_keeps_waiters_parked_and_retries():
    boom = [3]

    def flaky_sync():
        if boom[0] > 0:
            boom[0] -= 1
            raise OSError("disk on fire")

    gate = SyncGate(flaky_sync)
    errors = []
    gate.on_error = errors.append

    async def main():
        gate.mark_appended(1)
        t0 = time.monotonic()
        await asyncio.wait_for(gate.wait_durable(), timeout=5)
        return time.monotonic() - t0

    elapsed = run(main())
    # three failed rounds back off 0.05 + 0.1 + 0.2 before the flush
    assert elapsed > 0.3
    assert gate.sync_errors == 3 and len(errors) == 3
    assert gate.sync_count == 1 and not gate.dirty


def test_gate_wait_returns_immediately_when_clean():
    gate = SyncGate(lambda: (_ for _ in ()).throw(AssertionError))

    async def main():
        await asyncio.wait_for(gate.wait_durable(), timeout=1)

    run(main())  # no append: never touches the disk


def test_gate_stop_cancels_parked_windows():
    gate = SyncGate(lambda: time.sleep(10))

    async def main():
        gate.mark_appended(1)
        loop = asyncio.get_running_loop()
        with gate._lock:
            fut = loop.create_future()
            gate._waiters.append((gate._appended, fut))
        gate.stop()
        assert fut.cancelled()

    run(main())


# ------------------------------------------------------------ atomicio


def test_atomic_write_round_trip(tmp_path):
    p = str(tmp_path / "meta.json")
    atomicio.atomic_write_json(p, {"a": [1, 2], "b": "x"})
    assert atomicio.load_json(p) == {"a": [1, 2], "b": "x"}
    # no staging leftovers
    assert not os.path.exists(p + ".tmp")


def test_legacy_raw_json_still_loads(tmp_path):
    p = str(tmp_path / "legacy.json")
    with open(p, "w") as f:
        f.write('{"k": 1}')
    assert atomicio.load_json(p) == {"k": 1}


def test_missing_vs_unreadable_are_distinct(tmp_path):
    p = str(tmp_path / "gone.json")
    with pytest.raises(FileNotFoundError):
        atomicio.load_json(p)
    atomicio.atomic_write_json(p, {"k": 1})
    doc = open(p).read()
    # torn write: any strict prefix must be detected, never parsed
    # into an empty default
    for cut in (1, len(doc) // 2, len(doc) - 1):
        with open(p, "w") as f:
            f.write(doc[:cut])
        with pytest.raises(atomicio.MetaCorruption):
            atomicio.load_json(p)


def test_crc_detects_bit_rot(tmp_path):
    p = str(tmp_path / "meta.json")
    atomicio.atomic_write_json(p, {"progress": [123, 456]})
    doc = open(p).read()
    flipped = doc.replace("123", "124")
    assert flipped != doc
    with open(p, "w") as f:
        f.write(flipped)
    with pytest.raises(atomicio.MetaCorruption):
        atomicio.load_json(p)


def test_meta_write_failpoint_actions(tmp_path):
    p = str(tmp_path / "meta.json")
    atomicio.atomic_write_json(p, {"v": 1})
    # error: raises BEFORE touching anything — old content survives
    fp.configure("ds.meta.write", "error")
    with pytest.raises(fp.FailpointError):
        atomicio.atomic_write_json(p, {"v": 2})
    assert atomicio.load_json(p) == {"v": 1}
    # drop: the write is silently lost (rename never persisted)
    fp.configure("ds.meta.write", "drop")
    atomicio.atomic_write_json(p, {"v": 3})
    assert atomicio.load_json(p) == {"v": 1}
    # duplicate: idempotent
    fp.configure("ds.meta.write", "duplicate")
    atomicio.atomic_write_json(p, {"v": 4})
    fp.clear()
    assert atomicio.load_json(p) == {"v": 4}


# ------------------------------------------------- store chaos seams


def _mk_ds(tmp_path, mode="always", layout="hash"):
    ds = DurableSessions(
        str(tmp_path / "ds"), layout=layout, fsync=mode
    )
    ds.add_filter("t/#")
    return ds


def _msg(i, t=None):
    return Message(
        topic=f"t/{i}", payload=b"p%d" % i, qos=1,
        timestamp=t if t is not None else time.time(),
    )


def test_append_error_fails_persist_not_silently(tmp_path):
    ds = _mk_ds(tmp_path)
    fp.configure("ds.store.append", "error")
    with pytest.raises(OSError):
        ds.persist([_msg(0)])
    fp.clear()
    ds.persist([_msg(1)])
    assert ds.storage.stats()["messages"] == 1
    ds.close()


def test_append_drop_models_lying_disk(tmp_path):
    """`drop` silently loses the record — exactly the failure class
    the crash-point suite (and the always-mode sync barrier) exists
    to bound; at the storage surface the loss is at least visible in
    the record count."""
    ds = _mk_ds(tmp_path)
    fp.configure("ds.store.append", "drop")
    ds.persist([_msg(0)])
    fp.clear()
    assert ds.storage.stats()["messages"] == 0
    ds.close()


def test_append_duplicate_deduped_by_replay(tmp_path):
    ds = _mk_ds(tmp_path)
    t0 = time.time()
    ds.save("c1", {"t/#": {"qos": 1}}, expiry=3600.0, now=t0)
    fp.configure("ds.store.append", "duplicate")
    ds.persist([_msg(0, t=t0 + 1)])
    fp.clear()
    # two records on disk (at-least-once)...
    assert ds.storage.stats()["messages"] == 2
    ds.close()
    # ...ONE delivery after the replay mid-dedup (reboot restores the
    # checkpoint as a boot state)
    ds2 = DurableSessions(str(tmp_path / "ds"), layout="hash",
                          fsync="always")
    state = ds2.load("c1")
    assert state is not None
    got = ds2.replay(state)
    assert len(got) == 1
    ds2.close()


def test_sync_error_propagates_and_gate_counts(tmp_path):
    ds = _mk_ds(tmp_path)
    ds.persist([_msg(0)])
    fp.configure("ds.store.sync", "error")
    with pytest.raises(OSError):
        ds.gate.sync_now()
    assert ds.gate.sync_errors == 1 and ds.gate.dirty
    fp.clear()
    ds.gate.sync_now()
    assert not ds.gate.dirty
    ds.close()


def test_meta_write_fault_keeps_old_checkpoint(tmp_path):
    ds = _mk_ds(tmp_path)
    t0 = time.time()
    ds.save("c1", {"t/#": {"qos": 1}}, expiry=3600.0, now=t0)
    fp.configure("ds.meta.write", "error", match="sessions")
    with pytest.raises(fp.FailpointError):
        ds.save("c1", {"t/#": {"qos": 1}, "u/#": {"qos": 1}},
                expiry=3600.0, now=t0 + 5)
    fp.clear()
    # the old checkpoint survived the failed replace
    obj = atomicio.load_json(ds._state_path("c1"))
    assert obj["disconnected_at"] == t0
    assert set(obj["subs"]) == {"t/#"}
    ds.close()


# ------------------------------------------- corruption surfacing


def test_share_progress_corruption_alarms_not_silent(tmp_path):
    d = str(tmp_path / "ds")
    ds = DurableSessions(d, layout="hash", fsync="interval")
    ds._share_progress = {"$share/g/t/#": {"0": [5, 5]}}
    ds._share_prog_dirty = True
    ds._flush_share_progress()
    ds.close()
    # tear the file (power fail without fsync)
    p = os.path.join(d, "share_progress.json")
    doc = open(p).read()
    with open(p, "w") as f:
        f.write(doc[: len(doc) // 2])
    ds2 = DurableSessions(d, layout="hash", fsync="interval")
    # conservative fallback: EMPTY progress (replay from the
    # checkpoint: at-least-once), with the corruption counted —
    # the pre-PR code reset silently
    assert ds2._share_progress == {}
    assert ds2.corruption_counts["meta"] >= 1
    assert any(
        e["path"].endswith("share_progress.json")
        for e in ds2.corruption_events
    )
    ds2.close()


def test_share_members_corruption_falls_back_to_checkpoints(tmp_path):
    d = str(tmp_path / "ds")
    ds = DurableSessions(d, layout="hash", fsync="interval")
    flt = "$share/g/t/#"
    ds.save("m1", {flt: {"qos": 1}}, expiry=3600.0)
    ds.shared_join(flt, "m1")
    ds.shared_join(flt, "m2")
    ds.close()
    p = os.path.join(d, "share_members.json")
    with open(p, "w") as f:
        f.write("{torn")
    ds2 = DurableSessions(d, layout="hash", fsync="interval")
    assert ds2.corruption_counts["meta"] >= 1
    # the checkpointed member is still derivable (conservative union)
    assert "m1" in ds2.shared_group_members(flt)
    ds2.close()


def test_storage_quarantine_reports_through_sessions(tmp_path):
    d = str(tmp_path / "ds")
    ds = DurableSessions(d, layout="hash", fsync="interval")
    t0 = time.time()
    ds.add_filter("t/#")
    for i in range(6):
        ds.persist([_msg(i, t=t0 + i)])
    ds.sync()
    ds.close()
    # interior flip in the one stream's segment
    msgdir = os.path.join(d, "messages")
    seg = next(
        os.path.join(msgdir, n) for n in sorted(os.listdir(msgdir))
        if n.startswith("seg-")
    )
    with open(seg, "r+b") as f:
        f.seek(28 + 2)
        b = f.read(1)
        f.seek(28 + 2)
        f.write(bytes((b[0] ^ 0xFF,)))
    ds2 = DurableSessions(d, layout="hash", fsync="interval")
    stats = ds2.sync_stats()
    assert stats["corrupt_records"] >= 1
    assert stats["quarantined_segments"] == 1
    assert ds2.corruption_counts["storage"] >= 1
    assert any(
        e["kind"] == "storage" for e in ds2.corruption_events
    )
    ds2.close()


# --------------------------------------------------- config bounds


def test_check_config_bounds_for_fsync_keys():
    cfg = BrokerConfig()
    cfg.durable.fsync = "sometimes"
    assert any("durable.fsync" in p for p in check_config(cfg))
    cfg.durable.fsync = "always"
    cfg.durable.fsync_interval = 0.0
    assert any("fsync_interval" in p for p in check_config(cfg))
    cfg.durable.fsync_interval = 5.0
    assert not check_config(cfg)


# ------------------------------------------- broker group commit


def _srv_cfg(tmp_path, mode):
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    cfg.listeners = [ListenerConfig(port=0)]
    cfg.durable.enable = True
    cfg.durable.data_dir = str(tmp_path / "ds")
    cfg.durable.fsync = mode
    return cfg


async def _persistent_sub(port, cid="psub"):
    from mqtt_client import TestClient

    sub = TestClient(port, cid)
    await sub.connect(
        clean_start=True,
        properties={"session_expiry_interval": 3600},
    )
    await sub.subscribe("dur/+/q", qos=1)
    return sub


def test_broker_always_mode_parks_acks_until_flush(tmp_path):
    """The tentpole contract end to end: QoS1 publishes whose
    messages the persistence gate captures PUBACK only after the
    covering dslog_sync; concurrent publishes coalesce onto a handful
    of flushes; everything acked is on disk."""
    from emqx_tpu.broker.listener import BrokerServer
    from mqtt_client import TestClient

    async def main():
        srv = BrokerServer(_srv_cfg(tmp_path, "always"))
        await srv.start()
        try:
            port = srv.listeners[0].port
            broker = srv.broker
            sub = await _persistent_sub(port)
            pub = TestClient(port, "pub")
            await pub.connect()
            base = broker.durable.gate.sync_count
            acks = await asyncio.gather(*(
                pub.publish(f"dur/{i}/q", b"x", qos=1, timeout=10)
                for i in range(16)
            ))
            assert all(a is not None for a in acks)
            synced = broker.durable.gate.sync_count - base
            # at least one flush happened; the 16 acks did NOT cost 16
            assert 1 <= synced < 16
            assert not broker.durable.gate.dirty  # acked => flushed
            assert broker.metrics.val("ds.sync.count") >= 1
            # the captured copies are all on disk
            assert broker.durable.storage.stats()["messages"] == 16
            for i in range(16):
                await sub.recv_publish(timeout=5)
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await srv.stop()

    run(main())


def test_broker_sync_fault_parks_puback_and_retries(tmp_path):
    """`ds.store.sync=error` mid-window: the PUBACK stays parked
    while the gate retries with backoff, and releases (without
    publisher disconnect) once the disk recovers."""
    from emqx_tpu.broker.listener import BrokerServer
    from mqtt_client import TestClient

    async def main():
        srv = BrokerServer(_srv_cfg(tmp_path, "always"))
        await srv.start()
        try:
            port = srv.listeners[0].port
            broker = srv.broker
            sub = await _persistent_sub(port)
            pub = TestClient(port, "pub")
            await pub.connect()
            # fail the next 3 fsyncs, then recover
            fp.configure("ds.store.sync", "error", times=3)
            t0 = time.monotonic()
            ack = await pub.publish("dur/0/q", b"x", qos=1, timeout=10)
            elapsed = time.monotonic() - t0
            assert ack is not None
            # three failed rounds backed off before the ack released
            assert elapsed > 0.3, elapsed
            assert broker.durable.gate.sync_errors >= 3
            assert broker.metrics.val("ds.sync.errors") >= 3
            assert not broker.durable.gate.dirty
            await sub.recv_publish(timeout=5)
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await srv.stop()
            fp.clear()

    run(main())


def test_broker_interval_mode_acks_before_flush(tmp_path):
    """`interval` keeps today's latency: the PUBACK does not wait on
    the disk (the tick flushes on its own cadence)."""
    from emqx_tpu.broker.listener import BrokerServer
    from mqtt_client import TestClient

    async def main():
        srv = BrokerServer(_srv_cfg(tmp_path, "interval"))
        await srv.start()
        try:
            port = srv.listeners[0].port
            broker = srv.broker
            sub = await _persistent_sub(port)
            pub = TestClient(port, "pub")
            await pub.connect()
            # a sync fault cannot delay interval-mode acks
            fp.configure("ds.store.sync", "error")
            ack = await pub.publish("dur/0/q", b"x", qos=1, timeout=5)
            assert ack is not None
            assert broker.durable.gate.dirty  # flush owed, ack free
            fp.clear()
            broker.durable.sync_soon()
            await asyncio.sleep(0.05)
            assert not broker.durable.gate.dirty
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await srv.stop()
            fp.clear()

    run(main())


def test_broker_nodes_api_and_ctl_surface_durability(tmp_path):
    from api_helper import auth_session
    from emqx_tpu.broker.listener import BrokerServer
    from emqx_tpu.config import ApiConfig

    async def main():
        cfg = _srv_cfg(tmp_path, "always")
        cfg.api = ApiConfig(enable=True, port=0)
        srv = BrokerServer(cfg)
        await srv.start()
        try:
            http, api = await auth_session(srv)
            async with http:
                async with http.get(api + "/api/v5/nodes") as r:
                    node = (await r.json())["data"][0]
                assert node["durability"]["fsync"] == "always"
                assert "unsynced" in node["durability"]
                assert "corrupt_records" in node["durability"]
                async with http.get(api + "/metrics") as r:
                    text = await r.text()
                assert "emqx_ds_unsynced" in text
                assert "emqx_ds_sync_count" in text
                assert "emqx_profiler_ds_sync_us" in text
        finally:
            await srv.stop()

    run(main())
