"""Stage-level profile of the bench full path on the real chip:
where do the ~105ms/batch of non-device cost go?  Candidates: Python
tokenize loop, np.unique, device dispatch, device->host code transfer
(tunnel bandwidth), CSR expand, fid gather."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax

from bench import make_filters, make_topics
from emqx_tpu import topic as T
from emqx_tpu.ops.automaton import build_automaton, expand_codes_flat
from emqx_tpu.engine import _pad_batch
from emqx_tpu.ops.dictionary import PAD_TOK, TokenDict
from emqx_tpu.ops.match_kernel import match_batch, match_batch_compact

n_subs = int(os.environ.get("P_SUBS", 1_000_000))
batch = int(os.environ.get("P_BATCH", 32768))
iters = int(os.environ.get("P_ITERS", 12))
f_width, m_cap = 4, 16

print(f"platform={jax.devices()[0].platform}", flush=True)

# tunnel bandwidth probe: time device->host of known sizes
x = jax.device_put(np.zeros((1 << 20,), np.int32))  # 4 MB
np.asarray(x)
t0 = time.perf_counter(); np.asarray(x); bw4 = 4 / (time.perf_counter() - t0)
y = jax.device_put(np.zeros((1 << 18,), np.int32))  # 1 MB
np.asarray(y)
t0 = time.perf_counter(); np.asarray(y); bw1 = 1 / (time.perf_counter() - t0)
tiny = jax.jit(lambda a: a + 1); ta = jax.device_put(np.zeros(8, np.int32))
np.asarray(tiny(ta))
t0 = time.perf_counter()
for _ in range(5): np.asarray(tiny(ta))
rtt = (time.perf_counter() - t0) / 5 * 1e3
print(f"d2h bandwidth: 4MB={bw4:.1f} MB/s 1MB={bw1:.1f} MB/s rtt={rtt:.0f} ms", flush=True)

rng = np.random.default_rng(0)
filters, pops = make_filters(n_subs, 8)
tdict = TokenDict()
t0 = time.perf_counter()
aut = build_automaton(filters, tdict, max_levels=16)
print(f"build {time.perf_counter()-t0:.1f}s nodes={aut.n_nodes}", flush=True)
dev = tuple(jax.device_put(a) for a in aut.device_arrays())
fid_arr = np.arange(n_subs, dtype=np.int64)
streams = [make_topics(rng, batch, pops) for _ in range(iters)]
levels = aut.kernel_levels

enc_index = {}; enc_mat = np.full((65536, levels), PAD_TOK, np.int32)
enc_len = np.zeros(65536, np.int32); enc_dol = np.zeros(65536, bool)
used = 0
S = dict(tok=0.0, uniq=0.0, dispatch=0.0, xfer=0.0, expand=0.0, gather=0.0)

def submit(ts):
    global used, enc_mat, enc_len, enc_dol
    t0 = time.perf_counter()
    idx = np.empty(len(ts), np.int64)
    get = tdict.get
    for i, t in enumerate(ts):
        j = enc_index.get(t)
        if j is None:
            ws = T.words(t)
            n = min(len(ws), levels)
            row = enc_mat[used]; row[:] = PAD_TOK
            for k in range(n): row[k] = get(ws[k])
            enc_len[used] = n; enc_dol[used] = ws[0].startswith("$")
            j = enc_index[t] = used; used += 1
        idx[i] = j
    S["tok"] += time.perf_counter() - t0
    t0 = time.perf_counter()
    uniq, inv = np.unique(idx, return_inverse=True)
    tokens, lengths, dollar = _pad_batch(enc_mat[uniq], enc_len[uniq], enc_dol[uniq])
    S["uniq"] += time.perf_counter() - t0
    t0 = time.perf_counter()
    out = match_batch_compact(*dev, tokens, lengths, dollar, f_width=f_width, m_cap=m_cap, c_cap=tokens.shape[0])
    out[0].copy_to_host_async(); out[1].copy_to_host_async(); out[2].copy_to_host_async()
    S["dispatch"] += time.perf_counter() - t0
    return out, len(uniq), inv, tokens.shape

def drain(p):
    out, n_uniq, inv, shp = p
    t0 = time.perf_counter()
    flat = np.asarray(out[0]); counts = np.asarray(out[1]).astype(np.int64)
    assert int(np.asarray(out[2])[0]) <= len(flat), "compact clip"
    S["xfer"] += time.perf_counter() - t0
    t0 = time.perf_counter()
    ovf_u = counts < 0
    rows, pos = expand_codes_flat(aut.code_off, aut.code_idx, flat,
                                  np.where(ovf_u, -counts-1, counts), inv)
    codes = flat
    S["expand"] += time.perf_counter() - t0
    t0 = time.perf_counter()
    fids = fid_arr[pos]
    S["gather"] += time.perf_counter() - t0
    return rows, fids, codes.shape, int((codes >= 0).sum())

# warm
drain(submit(streams[0]))
for k in S: S[k] = 0.0

from collections import deque
depth = 8
inflight = deque(); t_start = time.perf_counter(); nvalid = 0; shp = None
for s in streams:
    inflight.append(submit(s))
    if len(inflight) >= depth:
        _, _, shp, nv = drain(inflight.popleft()); nvalid += nv
while inflight:
    _, _, shp, nv = drain(inflight.popleft()); nvalid += nv
el = time.perf_counter() - t_start
print(f"full path: {batch*iters/el:,.0f} topics/s ({el/iters*1e3:.1f} ms/batch)", flush=True)
print(f"codes shape/batch={shp} valid codes/batch={nvalid/iters:,.0f}", flush=True)
for k, v in S.items():
    print(f"  {k:9s} {v/iters*1e3:7.2f} ms/batch", flush=True)

# device-only for comparison
enc = []
for s in streams:
    idx = np.array([enc_index[t] for t in s]); u, _ = np.unique(idx, return_inverse=True)
    enc.append(_pad_batch(enc_mat[u], enc_len[u], enc_dol[u]))
match_batch(*dev, *enc[0], f_width=f_width, m_cap=m_cap)[1].block_until_ready()
t0 = time.perf_counter()
outs = [match_batch(*dev, *e, f_width=f_width, m_cap=m_cap) for e in enc]
outs[-1][1].block_until_ready()
el = time.perf_counter() - t0
print(f"device-only(dedup): {batch*iters/el:,.0f} topics/s ({el/iters*1e3:.1f} ms/batch)", flush=True)
