"""Reproduce measure_insert_rps and attribute stalls: log every insert
>2ms with the engine state flags, plus a background-thread activity
sample, to find what steals the insert thread's time."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
from bench import make_filters
from emqx_tpu.engine import MatchEngine, enable_compile_cache
enable_compile_cache()

n_base = 1_000_000
n_insert = 100_000
filters, pops = make_filters(n_base, 8)
eng = MatchEngine(max_levels=16, rebuild_threshold=65536,
                  background_rebuild=True, use_device=True)
for fid, ws in filters:
    eng._wild.insert("/".join(ws), fid)
    eng._by_fid[fid] = "/".join(ws)
t0 = time.perf_counter(); eng.rebuild()
print(f"rebuild base: {time.perf_counter()-t0:.1f}s", flush=True)
probe = [f"vehicles/v{i}/sensors/temp" for i in range(16)]
t0 = time.perf_counter(); eng.match_batch(probe)
print(f"first match: {time.perf_counter()-t0:.1f}s", flush=True)

stalls = []
t_start = time.perf_counter()
match_time = 0.0
mlat = []
W = 512
for w0 in range(0, n_insert, W):
    t0 = time.perf_counter()
    eng.insert_many([(f"ins/{i % 4099}/+/x{i}", n_base + i)
                     for i in range(w0, min(w0 + W, n_insert))])
    dt = time.perf_counter() - t0
    if dt > 0.004:
        stalls.append((w0, dt, dict(eng.index_stats())))
    if (w0 // W) % 4 == 3:
        m0 = time.perf_counter()
        eng.match_batch(probe)
        md = time.perf_counter() - m0
        match_time += md
        mlat.append((w0, md))
el = time.perf_counter() - t_start - match_time
print(f"insert rate: {n_insert/el:,.0f}/s (el={el:.2f}s match_time={match_time:.2f}s)", flush=True)
print(f"stalls>2ms: {len(stalls)} total {sum(s[1] for s in stalls):.2f}s", flush=True)
for i, dt, st in stalls[:15]:
    print(f"  insert#{i} {dt*1e3:8.1f} ms building={st['building']} folding={st['folding']} delta={st['delta']} residual={st['residual']}", flush=True)
mlat.sort(key=lambda x: -x[1])
print("slowest matches:", [(i, round(d*1e3)) for i, d in mlat[:6]], flush=True)

# second pass: timeline of builder phases vs probe spikes
import threading
from emqx_tpu.engine import MatchEngine as _ME
ev = []
_orig_dp = _ME._device_put
_orig_warm = _ME._warm_built
def dp(self, aut, chunk_bytes=1 << 19):
    t0 = time.perf_counter(); out = _orig_dp(self, aut, chunk_bytes)
    ev.append(("device_put", t0, time.perf_counter(), threading.current_thread().name))
    return out
def warm(self, aut, dev):
    t0 = time.perf_counter(); out = _orig_warm(self, aut, dev)
    ev.append(("warm", t0, time.perf_counter(), threading.current_thread().name))
    return out
_ME._device_put = dp; _ME._warm_built = warm

eng2 = _ME(max_levels=16, rebuild_threshold=65536,
           background_rebuild=True, use_device=True)
for fid, ws in filters:
    eng2._wild.insert("/".join(ws), fid)
    eng2._by_fid[fid] = "/".join(ws)
eng2.rebuild(); eng2.match_batch(probe)
base_t = time.perf_counter()
probes = []
W = 512
for w0 in range(0, n_insert, W):
    eng2.insert_many([(f"i2/{i % 4099}/+/y{i}", 3*n_base + i)
                      for i in range(w0, min(w0 + W, n_insert))])
    if (w0 // W) % 4 == 3:
        m0 = time.perf_counter()
        eng2.match_batch(probe)
        probes.append((m0 - base_t, time.perf_counter() - m0))
print("--- timeline (builder events, relative s) ---", flush=True)
for name, t0, t1, thr in ev:
    print(f"  {name:10s} {t0-base_t:7.2f} -> {t1-base_t:7.2f} ({t1-t0:6.2f}s) [{thr}]", flush=True)
slow = sorted(probes, key=lambda x: -x[1])[:8]
print("slow probes at:", [(round(t,2), round(d*1e3)) for t, d in slow], flush=True)
