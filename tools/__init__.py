"""Developer tooling (benches, profilers, and the brokerlint static
analyzer).  A package so `python -m tools.brokerlint` works from the
repo root — the same invocation CI's tier-1 gate uses."""
