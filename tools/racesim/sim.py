"""Schedule-space search over the forced-interleaving sanitizer.

Where crashsim (tools/crashsim) enumerates crash POINTS in a durable
write sequence, racesim enumerates task SCHEDULES of an async
workload:

  * ``run_schedule``   — one workload run under one policy (its own
    fresh event loop; the policy's trace is the schedule evidence).
  * ``run_seeds``      — the seeded sweep: same workload, N seeds,
    collect every failure with the trace that produced it.  The
    property-suite workhorse (tier-1 budget: small N).
  * ``run_exhaustive`` — every 0/1 preemption script up to a bounded
    number of decision points (2^k schedules): the small-schedule
    exhaustive mode, marked slow in CI.

A workload is a zero-argument callable returning a fresh coroutine
(it runs once per schedule).  A run FAILS when the coroutine raises;
assertion-style invariants live inside the workload itself.
"""

from __future__ import annotations

import asyncio
from itertools import product
from typing import Callable, Iterable, List, NamedTuple, Optional, Tuple

from emqx_tpu.testing.interleave import SchedulePolicy, drive


class Outcome(NamedTuple):
    label: str                      # "seed=7" / "script=(1,0,1)"
    error: Optional[BaseException]  # None on a clean run
    trace: Tuple[Tuple[str, int], ...]  # the schedule that ran

    @property
    def failed(self) -> bool:
        return self.error is not None


def run_schedule(workload: Callable[[], "asyncio.Future"],
                 policy: SchedulePolicy,
                 label: str = "",
                 timeout: float = 30.0) -> Outcome:
    """One run on a fresh event loop; the workload (and every task it
    spawns) steps through the policy's yieldpoints."""
    async def _main():
        await asyncio.wait_for(drive(workload(), policy), timeout)

    err: Optional[BaseException] = None
    try:
        asyncio.run(_main())
    except BaseException as e:  # noqa: BLE001 — the outcome IS the data
        err = e
    return Outcome(label, err, tuple(policy.trace))


def run_seeds(workload: Callable[[], "asyncio.Future"],
              seeds: Iterable[int] = range(20),
              prob: float = 1.0,
              max_preempts: int = 64,
              timeout: float = 30.0) -> List[Outcome]:
    """Seeded sweep: same workload under N random schedules.  Returns
    every outcome; callers assert ``not any(o.failed ...)`` (burned-
    down sites) or ``any(o.failed ...)`` (reproducing a still-racy
    fixture)."""
    out: List[Outcome] = []
    for seed in seeds:
        policy = SchedulePolicy(mode="random", seed=seed, prob=prob,
                                max_preempts=max_preempts)
        out.append(run_schedule(workload, policy,
                                label=f"seed={seed}", timeout=timeout))
    return out


def exhaustive_scripts(points: int) -> Iterable[Tuple[int, ...]]:
    """Every 0/1 preemption decision vector over `points` yieldpoints
    (2^points scripts, all-zeros first: the undisturbed schedule)."""
    return product((0, 1), repeat=points)


def run_exhaustive(workload: Callable[[], "asyncio.Future"],
                   points: int = 8,
                   timeout: float = 30.0) -> List[Outcome]:
    """The exhaustive small-schedule mode: run the workload under
    EVERY preemption script of `points` decisions.  Exponential —
    keep `points` small (<= ~12); the CI variant behind the ``slow``
    marker uses larger budgets than the tier-1 smoke run."""
    out: List[Outcome] = []
    for script in exhaustive_scripts(points):
        policy = SchedulePolicy(mode="script", script=script)
        out.append(run_schedule(
            workload, policy, label=f"script={script}",
            timeout=timeout,
        ))
    return out
