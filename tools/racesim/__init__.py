"""racesim: schedule-space search harness over the forced-
interleaving sanitizer (emqx_tpu.testing.interleave) — crashsim's
enumeration idea applied to task schedules instead of crash points."""

from .sim import (  # noqa: F401
    Outcome, exhaustive_scripts, run_exhaustive, run_schedule,
    run_seeds,
)
