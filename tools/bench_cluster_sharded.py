"""Cluster-sharded match engine benchmark: N OS-process nodes, the
cluster's wildcard set PARTITIONED by rendezvous hash (each node owns
~1/N — cluster/sharded_routes.py) instead of the reference's full
per-node replica (emqx_router.erl:133-162).  Prints ONE JSON line:

  { nodes, cluster_filters, shard_sizes, scatter_topics_per_s,
    scatter_p50_ms, scatter_p99_ms, oracle_ok }

Each node registers its slice of the filter set as local
subscriptions; shard ops flow over the cluster wire to the owners.
One node then scatter-matches publish windows against the whole
cluster and the result is checked against a single-process oracle.
"""

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def node_main():
    """Child: one broker + sharded cluster node; registers its slice
    of the filter set, reports shard stats over stdout, serves until
    killed."""
    import bench

    from emqx_tpu.broker.listener import BrokerServer
    from emqx_tpu.broker.session import SubOpts
    from emqx_tpu.cluster import ClusterNode
    from emqx_tpu.config import BrokerConfig

    name = os.environ["SHARD_NODE"]
    idx = int(os.environ["SHARD_IDX"])
    n_nodes = int(os.environ["SHARD_N"])
    n_filters = int(os.environ["SHARD_FILTERS"])
    seed_port = int(os.environ.get("SHARD_SEED_PORT", "0"))

    async def main():
        cfg = BrokerConfig()
        cfg.listeners[0].port = 0
        srv = BrokerServer(cfg)
        await srv.start()
        node = ClusterNode(
            name, srv.broker, sharded_routes=True,
            heartbeat_interval=0.2, down_after=2.0,
            flush_interval=0.005,
        )
        seeds = []
        if seed_port:
            seeds = [("node0", "127.0.0.1", seed_port)]
        await node.start(seeds=seeds)
        print(json.dumps({"ev": "up", "cluster_port": node.port}),
              flush=True)

        # this node's slice: filters i with i % n_nodes == idx
        filters, _pops = bench.make_filters(n_filters, 8)
        t0 = time.perf_counter()
        opts = SubOpts(qos=0)
        router = srv.broker.router
        for fid, ws in filters:
            if fid % n_nodes != idx:
                continue
            router.subscribe(f"bg{fid}", "/".join(ws), opts)
        reg_s = time.perf_counter() - t0
        print(json.dumps({"ev": "registered", "secs": reg_s}),
              flush=True)

        # report shard stats on demand via stdin lines
        loop = asyncio.get_running_loop()
        reader = asyncio.StreamReader()
        await loop.connect_read_pipe(
            lambda: asyncio.StreamReaderProtocol(reader), sys.stdin
        )
        while True:
            line = await reader.readline()
            if not line:
                break
            cmd = line.decode().strip()
            if cmd == "stats":
                print(json.dumps({
                    "ev": "stats", **node.shard.info(),
                    "engine": node.shard.table.engine.index_stats(),
                }), flush=True)
            elif cmd.startswith("match"):
                # match a window of topics fed as json on the same line
                topics = json.loads(cmd[5:])
                t0 = time.perf_counter()
                out = await node.shard.match_scatter(topics)
                dt = time.perf_counter() - t0
                print(json.dumps({
                    "ev": "match", "secs": dt,
                    "nodes": [sorted(s) for s in out],
                }), flush=True)
            elif cmd == "quit":
                break
        await node.stop()
        await srv.stop()

    asyncio.run(main())


def main():
    import subprocess

    import numpy as np

    import bench
    from emqx_tpu import topic as T

    n_nodes = int(os.environ.get("BENCH_SHARD_NODES", "2"))
    n_filters = int(os.environ.get("BENCH_SHARD_FILTERS", "200000"))
    n_windows = int(os.environ.get("BENCH_SHARD_WINDOWS", "30"))
    win = int(os.environ.get("BENCH_SHARD_WINDOW", "1024"))

    env_base = dict(os.environ, JAX_PLATFORMS="cpu",
                    SHARD_N=str(n_nodes), SHARD_FILTERS=str(n_filters))
    procs = []
    seed_port = 0
    try:
        for i in range(n_nodes):
            env = dict(env_base, SHARD_NODE=f"node{i}",
                       SHARD_IDX=str(i),
                       SHARD_SEED_PORT=str(seed_port))
            p = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "node"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                text=True, env=env,
            )
            procs.append(p)
            up = json.loads(p.stdout.readline())
            assert up["ev"] == "up"
            if i == 0:
                seed_port = up["cluster_port"]
        # wait for registration + shard-op drain
        for p in procs:
            json.loads(p.stdout.readline())  # "registered"
        deadline = time.time() + 120
        sizes = []
        while time.time() < deadline:
            sizes = []
            for p in procs:
                p.stdin.write("stats\n")
                p.stdin.flush()
                sizes.append(json.loads(p.stdout.readline()))
            total = sum(s["owned_filters"] for s in sizes)
            # distinct filters (patterns repeat across fids but router
            # dedups per filter string): ask once, compare stable
            if total > 0 and all(
                s["owned_filters"] > 0 for s in sizes
            ):
                time.sleep(1.0)
                stable = []
                for p in procs:
                    p.stdin.write("stats\n")
                    p.stdin.flush()
                    stable.append(json.loads(p.stdout.readline()))
                if [s["owned_filters"] for s in stable] == [
                    s["owned_filters"] for s in sizes
                ]:
                    sizes = stable
                    break
            time.sleep(0.5)

        filters, pops = bench.make_filters(n_filters, 8)
        rng = np.random.default_rng(0)
        lat = []
        n_topics = 0
        driver = procs[0]
        last_nodes = None
        last_topics = None
        for w in range(n_windows):
            topics = bench.make_topics(rng, win, pops)
            driver.stdin.write("match" + json.dumps(topics) + "\n")
            driver.stdin.flush()
            rep = json.loads(driver.stdout.readline())
            assert rep["ev"] == "match"
            lat.append(rep["secs"])
            n_topics += len(topics)
            last_nodes, last_topics = rep["nodes"], topics

        # oracle check on the last window: node sets must equal the
        # full-knowledge computation (minus the driver node itself)
        oracle_ok = True
        by_node = {}
        for fid, ws in filters:
            by_node.setdefault(f"node{fid % n_nodes}", []).append(ws)
        for t, got in zip(last_topics, last_nodes):
            tws = T.words(t)
            want = {
                n for n, fws in by_node.items()
                if any(T.match_words(tws, ws) for ws in fws)
            }
            want.discard("node0")
            if set(got) != want:
                oracle_ok = False
                break

        lat_ms = np.array(lat) * 1e3
        out = {
            "sharded_cluster_nodes": n_nodes,
            "sharded_cluster_filters": n_filters,
            "sharded_cluster_shard_sizes": [
                s["owned_filters"] for s in sizes
            ],
            "sharded_cluster_scatter_topics_per_s":
                n_topics / float(np.sum(lat)),
            "sharded_cluster_scatter_p50_ms":
                float(np.percentile(lat_ms, 50)),
            "sharded_cluster_scatter_p99_ms":
                float(np.percentile(lat_ms, 99)),
            "sharded_cluster_oracle_ok": oracle_ok,
        }
        print(json.dumps(out), flush=True)
    finally:
        for p in procs:
            try:
                p.stdin.write("quit\n")
                p.stdin.flush()
            except Exception:
                pass
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "node":
        node_main()
    else:
        main()
