"""Kernel profiling harness (dev tool, not part of the framework).

Builds (once, cached to .profile_cache2.npz) the bench.py 10M-sub
automaton + encoded topic streams, then times the production
match_batch on the real device across f_width/m_cap settings.

Timing notes for the axon tunnel platform: `block_last` (dispatch all
batches, block on the final output) is the trusted device-compute
proxy; `fetch_all` adds one serialized tunnel round-trip per batch and
overstates steady-state cost (production overlaps transfers).

Usage: python tools/profile_kernel.py [f_width ...]
"""

import os
import sys
import time
from functools import partial

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from bench import make_filters, make_topics
from emqx_tpu import topic as T
from emqx_tpu.ops.automaton import build_automaton
from emqx_tpu.ops.dictionary import TokenDict, encode_topics
from emqx_tpu.ops.match_kernel import match_batch

CACHE = os.path.join(os.path.dirname(__file__), ".profile_cache2.npz")
N_SUBS = int(os.environ.get("PROF_SUBS", 10_000_000))
BATCH = int(os.environ.get("PROF_BATCH", 32768))
ITERS = int(os.environ.get("PROF_ITERS", 30))
M_CAP = int(os.environ.get("PROF_M", 16))


def log(m):
    print(m, file=sys.stderr, flush=True)


def load_or_build():
    if os.path.exists(CACHE):
        return dict(np.load(CACHE, allow_pickle=False))
    t0 = time.perf_counter()
    filters, pops = make_filters(N_SUBS, 8)
    tdict = TokenDict()
    aut = build_automaton(filters, tdict, max_levels=16)
    log(f"built: nodes={aut.n_nodes} buckets={len(aut.fp_rows)} "
        f"salt={aut.salt} levels={aut.kernel_levels} "
        f"in {time.perf_counter()-t0:.1f}s")
    rng = np.random.default_rng(0)
    toks, lens, dols = [], [], []
    for _ in range(ITERS):
        s = make_topics(rng, BATCH, pops)
        tk, ln, dl = encode_topics(tdict, [T.words(t) for t in s],
                                   aut.kernel_levels)
        toks.append(tk); lens.append(ln); dols.append(dl)
    data = dict(
        fp_rows=aut.fp_rows, node_rows=aut.node_rows,
        salt=np.uint32(aut.salt),
        toks=np.stack(toks), lens=np.stack(lens), dols=np.stack(dols),
    )
    np.savez_compressed(CACHE, **data)
    return data


def main():
    d = load_or_build()
    log(f"buckets={len(d['fp_rows'])} nodes={len(d['node_rows'])} "
        f"salt={int(d['salt'])} platform={jax.devices()[0].platform}")
    dev = (jax.device_put(d["fp_rows"]), jax.device_put(d["node_rows"]),
           jax.device_put(d["salt"].reshape(())))
    streams = [(d["toks"][i], d["lens"][i], d["dols"][i])
               for i in range(len(d["toks"]))]

    widths = [int(w) for w in (sys.argv[1:] or ["4", "8"])]
    for fw in widths:
        fn = partial(match_batch, f_width=fw, m_cap=M_CAP)
        o = fn(*dev, *streams[0])
        np.asarray(o[1])  # compile + settle queue
        for _rep in range(2):  # second rep = steady state
            t0 = time.perf_counter()
            outs = [fn(*dev, tk, ln, dl) for tk, ln, dl in streams]
            jax.block_until_ready(outs[-1])
            t_blocklast = time.perf_counter() - t0
            total = sum(int(np.asarray(x[1]).sum()) for x in outs)
            dt = time.perf_counter() - t0
        ovf = sum(int(np.asarray(o[2]).sum()) for o in outs)
        n = BATCH * len(streams)
        log(f"f_width={fw:2d}  block_last {t_blocklast:.3f}s "
            f"({n / t_blocklast:12,.0f} topics/s)  fetch_all {dt:.3f}s  "
            f"matches={total} ovf={ovf}")


if __name__ == "__main__":
    main()
