"""DS layout benchmark: LTS (learned-topic-structure) vs flat hash —
the property that justifies the layout (emqx_ds_lts role): wildcard
replay over a many-topic log must scan only the overlapping
structures, and a concrete-topic replay ~one sub-stream.  Prints ONE
JSON line with ds_* keys."""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from emqx_tpu.ds.builtin_local import LocalStorage
    from emqx_tpu.ds.lts import LtsStorage
    from emqx_tpu.message import Message

    n_per_family = int(os.environ.get("BENCH_DS_PER_FAMILY", "40000"))
    fams = ["veh/%d/t", "grid/%d/load", "app/%d/evt"]
    t0 = 1_700_000_000.0

    def fill(store):
        t_fill = time.perf_counter()
        for f_i, fam in enumerate(fams):
            batch = [
                Message(topic=fam % i, payload=b"x" * 32,
                        timestamp=t0 + f_i * n_per_family + i)
                for i in range(n_per_family)
            ]
            store.store_batch(batch)
        return time.perf_counter() - t_fill

    def replay(store, flt, page=512):
        n = 0
        t_r = time.perf_counter()
        for stream in store.get_streams(flt):
            it = store.make_iterator(stream, flt, 0)
            while True:
                it, msgs = store.next(it, page)
                if not msgs:
                    break
                n += len(msgs)
        return n, time.perf_counter() - t_r

    out = {}
    total = n_per_family * len(fams)
    point_topic = f"veh/{n_per_family // 2}/t"  # always exists
    for name, cls in (("lts", LtsStorage), ("hash", LocalStorage)):
        d = tempfile.mkdtemp(prefix=f"benchds-{name}-")
        try:
            store = cls(d)
            out[f"ds_{name}_fill_s"] = round(fill(store), 3)
            # one structure's wildcard: must NOT pay for the other two
            n, dt = replay(store, "veh/+/t")
            assert n == n_per_family, (name, n)
            out[f"ds_{name}_wildcard_replay_s"] = round(dt, 3)
            out[f"ds_{name}_wildcard_msgs_per_s"] = round(
                n / max(dt, 1e-6), 1
            )
            # concrete topic: point replay
            n, dt = replay(store, point_topic)
            assert n == 1, (name, n)
            out[f"ds_{name}_point_replay_ms"] = round(dt * 1e3, 2)
            store.close()
        finally:
            shutil.rmtree(d, ignore_errors=True)
    out["ds_records"] = total
    out["ds_lts_vs_hash_wildcard_speedup"] = round(
        out["ds_hash_wildcard_replay_s"]
        / max(out["ds_lts_wildcard_replay_s"], 1e-3), 2
    )
    out["ds_lts_vs_hash_point_speedup"] = round(
        out["ds_hash_point_replay_ms"]
        / max(out["ds_lts_point_replay_ms"], 1e-3), 2
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
