"""Multi-core broker benchmark: N worker processes (SO_REUSEPORT +
loopback clustering + the shared match service) driven by K
load-generator processes, so neither side is single-core-bound.
Prints ONE JSON line.

Workload = the emqtt_bench shape run_broker_bench uses: S wildcard
subscribers (bench/{i}/#), P QoS1 publishers round-robining over
them; with workers sharing the accept socket, most deliveries cross
worker processes over the binary cluster wire.

``--smoke`` is the tier-1 fast path: 2 workers + the match service,
one tiny cross-worker pubsub round, liveness + clean-shutdown checks,
and a zero-findings brokerlint pass over the multicore modules —
small enough to run un-``slow``-marked in CI."""

import asyncio
import json
import os
import struct
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def _loadgen(port, gen_id, n_pubs, n_subs, sub_base, n_msgs,
                   inflight):
    from emqx_tpu.codec import mqtt as C

    loop = asyncio.get_running_loop()
    total = n_pubs * n_msgs
    received = 0
    lat = []
    all_done = loop.create_future()
    sub_ready = [asyncio.Event() for _ in range(n_subs)]

    async def open_conn(cid):
        r, w = await asyncio.open_connection("127.0.0.1", port)
        w.write(C.serialize(
            C.Connect(client_id=cid, proto_ver=C.MQTT_V5), C.MQTT_V5
        ))
        await w.drain()
        p = C.StreamParser(version=C.MQTT_V5)
        while True:
            data = await r.read(1 << 16)
            assert data, "closed during CONNECT"
            pkts = list(p.feed(data))
            if pkts:
                assert pkts[0].type == C.CONNACK
                break
        return r, w, p

    async def subscriber(i):
        nonlocal received
        r, w, p = await open_conn(f"g{gen_id}s{i}")
        w.write(C.serialize(C.Subscribe(
            packet_id=1,
            subscriptions=[C.Subscription(
                topic_filter=f"bench/{sub_base + i}/#", qos=0
            )],
        ), C.MQTT_V5))
        await w.drain()
        while True:
            data = await r.read(1 << 16)
            if not data:
                return
            for pkt in p.feed(data):
                if pkt.type == C.SUBACK:
                    sub_ready[i].set()
                elif pkt.type == C.PUBLISH:
                    lat.append(
                        loop.time()
                        - struct.unpack_from("d", pkt.payload)[0]
                    )
                    received += 1
                    if received >= total and not all_done.done():
                        all_done.set_result(None)

    async def publisher(j):
        r, w, p = await open_conn(f"g{gen_id}p{j}")
        acked = 0
        dead = False
        ev = asyncio.Event()

        async def acks():
            nonlocal acked, dead
            while acked < n_msgs:
                data = await r.read(1 << 16)
                if not data:
                    # connection lost: wake the flow-control wait or
                    # the publisher parks forever
                    dead = True
                    ev.set()
                    return
                for pkt in p.feed(data):
                    if pkt.type == C.PUBACK:
                        acked += 1
                        ev.set()

        t = loop.create_task(acks())
        pid = 0
        for k in range(n_msgs):
            i = (j + k * 7) % n_subs
            pid = (pid % 65535) + 1
            w.write(C.serialize(C.Publish(
                topic=f"bench/{sub_base + i}/v",
                payload=struct.pack("d", loop.time()),
                qos=1, packet_id=pid,
            ), C.MQTT_V5))
            if (k & 31) == 0:
                await w.drain()
            while k - acked >= inflight and not dead:
                ev.clear()
                await ev.wait()
            if dead:
                raise ConnectionError(f"publisher g{gen_id}p{j} lost")
        await w.drain()
        await t
        w.close()

    subs = [asyncio.ensure_future(subscriber(i)) for i in range(n_subs)]
    await asyncio.gather(*(e.wait() for e in sub_ready))
    await asyncio.sleep(1.0)  # cross-worker route replication settles
    t0 = time.perf_counter()
    await asyncio.gather(*(publisher(j) for j in range(n_pubs)))
    await asyncio.wait_for(all_done, 180)
    elapsed = time.perf_counter() - t0
    for t in subs:
        t.cancel()
    import numpy as np

    lat_ms = np.array(lat) * 1e3
    return {
        "msgs": total,
        "elapsed": elapsed,
        "p50_ms": float(np.percentile(lat_ms, 50)),
        "p99_ms": float(np.percentile(lat_ms, 99)),
    }


def smoke():
    """Tier-1 liveness smoke: boot the REAL multicore topology (2
    workers sharing the port + the match service over shm rings), push
    one small cross-worker pubsub round, then prove clean shutdown and
    a clean brokerlint over the multicore modules.  Prints ONE JSON
    line; exits non-zero on any failed check."""
    from emqx_tpu.broker.multicore import free_ports, spawn_workers
    from tools.brokerlint.engine import run_lint

    ncpu = os.cpu_count() or 1
    port = free_ports(1)[0]
    pool = spawn_workers(2, port, bind="127.0.0.1")
    try:
        pool.wait_ready(port, timeout=120)
        time.sleep(1.5)  # cluster mesh + service attach settle
        res = asyncio.run(_loadgen(
            port, 0, n_pubs=2, n_subs=4, sub_base=0, n_msgs=5,
            inflight=16,
        ))
        alive = pool.alive()
        service_alive = pool.service_alive()
    finally:
        pool.stop()
    # clean shutdown: SIGINT drains the workers, SIGTERM the service
    stopped_clean = (pool.procs == [] and pool.service_proc is None
                     and not os.path.exists(pool.service_socket))
    findings = run_lint([
        "emqx_tpu/broker/shmring.py",
        "emqx_tpu/broker/matchclient.py",
        "emqx_tpu/broker/multicore.py",
        "emqx_tpu/ops/matchsvc.py",
    ])
    out = {
        "mc_smoke": "ok",
        "mc_host_cpus": ncpu,
        "mc_workers": 2,
        "mc_alive": alive,
        "mc_service_alive": service_alive,
        "mc_stopped_clean": stopped_clean,
        "mc_msgs": res["msgs"],
        "mc_delivery_p50_ms": round(res["p50_ms"], 2),
        "lint_findings": len(findings),
    }
    failed = (alive != 2 or not service_alive or not stopped_clean
              or res["msgs"] != 2 * 5 or findings)
    if failed:
        out["mc_smoke"] = "FAILED"
        if findings:
            out["lint"] = [f.render() for f in findings]
    print(json.dumps(out))
    sys.exit(1 if failed else 0)


def main():
    import signal

    from emqx_tpu.broker.multicore import spawn_workers

    # a SIGTERM (e.g. the parent bench's timeout kill) must still run
    # the finally that stops the worker pool, or orphans keep the
    # port and skew the next bench phase
    signal.signal(signal.SIGTERM,
                  lambda *_: (_ for _ in ()).throw(KeyboardInterrupt()))

    ncpu = os.cpu_count() or 1
    # scaling beyond the core count only adds scheduling overhead; the
    # result records the cpu count so the number is interpretable
    n_workers = int(os.environ.get(
        "BENCH_MC_WORKERS", max(2, min(8, ncpu))
    ))
    n_gens = int(os.environ.get(
        "BENCH_MC_GENS", max(2, min(4, ncpu // 2 or 1))
    ))
    pubs_per_gen = int(os.environ.get("BENCH_MC_PUBS", 25))
    subs_per_gen = int(os.environ.get("BENCH_MC_SUBS", 25))
    msgs = int(os.environ.get("BENCH_MC_MSGS", 400))
    from emqx_tpu.broker.multicore import free_ports

    port = free_ports(1)[0]
    env = dict(os.environ)
    pool = spawn_workers(n_workers, port, bind="127.0.0.1")
    try:
        pool.wait_ready(port, timeout=120)
        time.sleep(2.0)  # cluster mesh settles
        gens = []
        for g in range(n_gens):
            gens.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--loadgen", str(port), str(g), str(pubs_per_gen),
                 str(subs_per_gen), str(g * subs_per_gen), str(msgs)],
                stdout=subprocess.PIPE, text=True, env=env,
            ))
        results = []
        for p in gens:
            out, _ = p.communicate(timeout=240)
            results.append(json.loads(out.strip().splitlines()[-1]))
        total = sum(r["msgs"] for r in results)
        elapsed = max(r["elapsed"] for r in results)
        print(json.dumps({
            "mc_host_cpus": ncpu,
            "mc_workers": n_workers,
            "mc_alive": pool.alive(),
            "mc_service_alive": pool.service_alive(),
            "mc_loadgens": n_gens,
            "mc_msgs": total,
            "mc_msgs_per_s": round(total / elapsed, 1),
            # worst GEN's percentiles (per-gen distributions are not
            # merged), named so nobody reads them as a combined p50
            "mc_delivery_p50_worst_gen_ms": round(max(
                r["p50_ms"] for r in results), 2),
            "mc_delivery_p99_worst_gen_ms": round(max(
                r["p99_ms"] for r in results), 2),
        }))
    finally:
        pool.stop()


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--loadgen":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        _, _, port, gid, pubs, subs, base, msgs = sys.argv
        print(json.dumps(asyncio.run(_loadgen(
            int(port), int(gid), int(pubs), int(subs), int(base),
            int(msgs), inflight=256,
        ))))
    elif len(sys.argv) > 1 and sys.argv[1] == "--smoke":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        smoke()
    else:
        main()
