"""Sharded-engine benchmark on the virtual 8-device CPU mesh (the
driver's dryrun environment): sharded insert + match throughput under
churn, with incremental per-shard rebuilds.  Spawned by bench.py as a
subprocess (the main bench must keep seeing the real TPU) — prints
ONE JSON line on stdout."""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")

    from bench import make_filters, make_topics
    from emqx_tpu.ops.dictionary import TokenDict
    from emqx_tpu.parallel.sharded import ShardedMatchEngine, make_mesh

    n_subs = int(os.environ.get("BENCH_SHARDED_SUBS", 200_000))
    n_insert = int(os.environ.get("BENCH_SHARDED_INSERTS", 50_000))
    batch = int(os.environ.get("BENCH_SHARDED_BATCH", 4096))
    mesh = make_mesh(8)

    rng = np.random.default_rng(0)
    filters, pops = make_filters(n_subs, 8)
    # construct EMPTY and seed through the real mutation path: timing
    # the engine's own insert_many + sharded rebuild measures the index
    # that actually serves the matches below (a pre-built seed index
    # would be discarded by adoption)
    eng = ShardedMatchEngine(
        mesh, f_width=4, m_cap=16,
        rebuild_threshold=10**9, background_rebuild=True,
    )
    t0 = time.perf_counter()
    W = 4096
    pairs = [("/".join(ws), fid) for fid, ws in filters]
    for w0 in range(0, len(pairs), W):
        eng.insert_many(pairs[w0:w0 + W])
    eng.rebuild()
    build_s = time.perf_counter() - t0
    eng.rebuild_threshold = 65536

    streams = [make_topics(rng, batch, pops) for _ in range(10)]
    eng.match_batch(streams[0])  # compile

    # match throughput on the mesh
    t0 = time.perf_counter()
    total = 0
    for s in streams:
        out = eng.match_batch(s)
        total += sum(len(x) for x in out)
    match_rate = batch * len(streams) / (time.perf_counter() - t0)

    # churn: windowed inserts while the match stream stays hot (the
    # final explicit rebuild below is the incremental one — the churn
    # volume stays under the background threshold)
    probe = streams[0][:256]
    t0 = time.perf_counter()
    match_time = 0.0
    lat = []
    W = 512
    for w0 in range(0, n_insert, W):
        eng.insert_many([
            (f"ins/{i % 4099}/+/x{i}", n_subs + i)
            for i in range(w0, min(w0 + W, n_insert))
        ])
        if (w0 // W) % 8 == 7:
            m0 = time.perf_counter()
            eng.match_batch(probe)
            dt = time.perf_counter() - m0
            match_time += dt
            lat.append(dt)
    el = time.perf_counter() - t0 - match_time
    insert_rps = n_insert / el

    # one explicit incremental rebuild: only the delta re-encodes
    t0 = time.perf_counter()
    eng.rebuild()
    incr_rebuild_s = time.perf_counter() - t0
    eng.match_batch(probe)

    lat_ms = np.array(lat or [0.0]) * 1e3
    print(json.dumps({
        "sharded_mesh": dict(mesh.shape),
        "sharded_subs": n_subs,
        "sharded_build_s": round(build_s, 2),
        "sharded_match_topics_per_s": round(match_rate, 1),
        "sharded_mean_fanout": round(total / (batch * len(streams)), 2),
        "sharded_insert_rps": round(insert_rps, 1),
        "sharded_churn_match_p50_ms": round(
            float(np.percentile(lat_ms, 50)), 1),
        "sharded_churn_match_p99_ms": round(
            float(np.percentile(lat_ms, 99)), 1),
        "sharded_incremental_rebuild_s": round(incr_rebuild_s, 2),
    }))


if __name__ == "__main__":
    main()
