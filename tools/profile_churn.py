"""Where do insert cycles go under sustained churn?  Compares bare
host-only inserts, device-path inserts (folds+rebuilds live), and the
encode cost the background threads pay (GIL steal suspect)."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
from emqx_tpu.engine import MatchEngine

N = int(os.environ.get("P_N", 100_000))

# 1. bare inserts, host only, huge thresholds (no folds/builds)
eng = MatchEngine(use_device=False, rebuild_threshold=10**9,
                  delta_aut_threshold=10**9)
t0 = time.perf_counter()
for i in range(N):
    eng.insert(f"ins/{i % 4099}/+/x{i}", i)
el = time.perf_counter() - t0
print(f"bare insert (no device, no folds): {N/el:,.0f}/s ({el/N*1e6:.1f} us)", flush=True)

# 2. encode cost of those same filters (what fold/rebuild threads pay)
from emqx_tpu.ops.automaton import encode_filters
items = list(eng._delta.items())
t0 = time.perf_counter()
inputs = encode_filters(items, eng._tdict, 16)
el = time.perf_counter() - t0
print(f"encode_filters of {len(items)}: {el*1e3:.0f} ms ({el/len(items)*1e6:.1f} us/filter)", flush=True)

# 3. assemble cost (numpy, releases GIL in C)
from emqx_tpu.ops.automaton import assemble_automaton
t0 = time.perf_counter()
aut = assemble_automaton(*inputs, max_levels=16)
el = time.perf_counter() - t0
print(f"assemble: {el*1e3:.0f} ms", flush=True)

# 4. device-path churn (folds + background rebuild live), no matches
eng2 = MatchEngine(rebuild_threshold=65536, background_rebuild=True,
                   use_device=True)
for i in range(1000):
    eng2.insert(f"seed/{i}/+/s{i}", -i - 1)
eng2.rebuild()
t0 = time.perf_counter()
for i in range(N):
    eng2.insert(f"ins/{i % 4099}/+/x{i}", 10**6 + i)
el = time.perf_counter() - t0
print(f"device-path insert (folds+rebuilds): {N/el:,.0f}/s ({el/N*1e6:.1f} us) stats={eng2.index_stats()}", flush=True)
