"""CLI: ``python -m tools.brokerlint [paths...] [--baseline F]
[--json] [--write-baseline]``.

Exit codes: 0 clean (baselined findings and stale entries are
reported but don't fail), 1 on any NEW finding — identical behavior
to the tier-1 pytest gate (tests/test_lint.py), which calls the same
`run_lint`/`diff_baseline`.
"""

from __future__ import annotations

import argparse
import json
import sys

from .engine import (
    DEFAULT_BASELINE, DEFAULT_PATHS, diff_baseline, load_baseline,
    run_lint,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.brokerlint",
        description="repo-aware AST lint: async-race, device-purity, "
                    "failpoint-coverage",
    )
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                    help="files/dirs to lint (default: emqx_tpu/)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file of accepted fingerprints")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline file from this run "
                         "(each entry still deserves a justification "
                         "comment — add them before committing)")
    args = ap.parse_args(argv)

    findings = run_lint(args.paths or list(DEFAULT_PATHS))
    baseline = set() if args.no_baseline else load_baseline(
        args.baseline
    )
    new, stale = diff_baseline(findings, baseline)

    if args.write_baseline:
        with open(args.baseline, "w") as f:
            f.write("# brokerlint baseline — accepted pre-existing "
                    "findings (burn these down).\n"
                    "# One fingerprint per line; '#' comments hold "
                    "the justification.\n")
            for fi in sorted(findings, key=lambda x: x.fingerprint):
                f.write(fi.fingerprint + "\n")
        print(f"wrote {len(findings)} entries to {args.baseline}")
        return 0

    if args.as_json:
        print(json.dumps({
            "findings": [f.as_dict() for f in findings],
            "new": [f.as_dict() for f in new],
            "stale_baseline": sorted(stale),
        }, indent=1))
    else:
        for f in findings:
            mark = "" if f.fingerprint in baseline else " [NEW]"
            print(f.render() + mark)
        for s in sorted(stale):
            print(f"stale baseline entry (no longer found): {s}")
        print(f"brokerlint: {len(findings)} finding(s), "
              f"{len(new)} new, {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'}")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
