"""CLI: ``python -m tools.brokerlint [paths...] [--baseline F]
[--json | --sarif] [--changed [REF]] [--write-baseline]``.

Exit codes: 0 clean (baselined findings and stale entries are
reported but don't fail), 1 on any NEW finding — identical behavior
to the tier-1 pytest gate (tests/test_lint.py), which calls the same
`run_lint`/`diff_baseline` code path.

``--changed [REF]`` lints the whole default surface (the
interprocedural pass needs the full program for correct summaries)
but only REPORTS findings in files changed vs the git ref (default
HEAD) — the editor/pre-push fast path.  ``--sarif`` emits SARIF
2.1.0 for editor and CI annotation consumers.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path

from .engine import (
    DEFAULT_BASELINE, DEFAULT_PATHS, diff_baseline, load_baseline,
    run_lint,
)

_REPO = Path(__file__).resolve().parents[2]


def _changed_files(ref: str) -> set:
    """Repo-relative posix paths of .py files changed vs `ref`
    (committed + staged + worktree), plus untracked ones."""
    out = set()
    for args in (
        ["git", "diff", "--name-only", ref, "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        proc = subprocess.run(
            args, cwd=_REPO, capture_output=True, text=True,
            timeout=30,
        )
        if proc.returncode != 0:
            raise SystemExit(
                f"brokerlint: git failed: {proc.stderr.strip()}"
            )
        out.update(
            line.strip() for line in proc.stdout.splitlines()
            if line.strip().endswith(".py")
        )
    return out


def _sarif(findings, new_fps) -> dict:
    rules = sorted({f.rule for f in findings})
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0"
                    ".json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "brokerlint",
                "informationUri":
                    "tools/brokerlint (repo-local analyzer)",
                "rules": [{"id": r} for r in rules],
            }},
            "results": [{
                "ruleId": f.rule,
                "level": ("error" if f.fingerprint in new_fps
                          else "note"),
                "message": {"text": f"[{f.qualname}] {f.message}"},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {"startLine": f.line},
                    },
                }],
                "fingerprints": {"brokerlint/v1": f.fingerprint},
            } for f in findings],
        }],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.brokerlint",
        description="repo-aware AST lint: async-race, device-purity, "
                    "failpoint-coverage, dispatch-perf, native "
                    "buffer-lifetime, lock discipline "
                    "(interprocedural)",
    )
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                    help="files/dirs to lint (default: emqx_tpu/ "
                         "tools/ bench.py)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file of accepted fingerprints")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--sarif", action="store_true",
                    help="SARIF 2.1.0 output (editor/CI annotations)")
    ap.add_argument("--changed", nargs="?", const="HEAD",
                    default=None, metavar="REF",
                    help="only report findings in files changed vs "
                         "REF (default HEAD); the whole program is "
                         "still indexed for interprocedural "
                         "summaries")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline file from this run "
                         "(each entry still deserves a justification "
                         "comment — add them before committing)")
    ap.add_argument("--profile", action="store_true",
                    help="print per-rule-family wall time and "
                         "per-file cache hit/miss stats after the "
                         "run")
    args = ap.parse_args(argv)

    findings = run_lint(args.paths or list(DEFAULT_PATHS))
    all_findings = findings
    if args.changed is not None:
        changed = _changed_files(args.changed)
        findings = [f for f in findings if f.path in changed]
    baseline = set() if args.no_baseline else load_baseline(
        args.baseline
    )
    # staleness is a whole-run property: diff against the UNFILTERED
    # findings so --changed never misreports unchanged files' baseline
    # entries as stale; only the NEW list is scoped to the filter
    new, stale = diff_baseline(all_findings, baseline)
    if args.changed is not None:
        new = [f for f in new if f.path in changed]

    if args.write_baseline:
        # ALWAYS write the unfiltered run: a --changed filter must
        # never truncate the baseline's entries for unchanged files
        with open(args.baseline, "w") as f:
            f.write("# brokerlint baseline — accepted pre-existing "
                    "findings (burn these down).\n"
                    "# One fingerprint per line; '#' comments hold "
                    "the justification.\n")
            for fi in sorted(all_findings,
                             key=lambda x: x.fingerprint):
                f.write(fi.fingerprint + "\n")
        print(f"wrote {len(all_findings)} entries to {args.baseline}")
        return 0

    if args.sarif:
        print(json.dumps(
            _sarif(findings, {f.fingerprint for f in new}), indent=1
        ))
    elif args.as_json:
        print(json.dumps({
            "findings": [f.as_dict() for f in findings],
            "new": [f.as_dict() for f in new],
            "stale_baseline": sorted(stale),
        }, indent=1))
    else:
        for f in findings:
            mark = "" if f.fingerprint in baseline else " [NEW]"
            print(f.render() + mark)
        for s in sorted(stale):
            print(f"stale baseline entry (no longer found): {s}")
        print(f"brokerlint: {len(findings)} finding(s), "
              f"{len(new)} new, {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'}")
    if args.profile:
        _print_profile()
    return 1 if new else 0


def _print_profile() -> None:
    from .engine import LAST_PROFILE

    fams = LAST_PROFILE.get("families", {})
    files = LAST_PROFILE.get("files", {})
    total = sum(fams.values())
    print("\n-- profile: rule-family wall time "
          f"(total {total * 1000:.1f} ms) --")
    for name, secs in sorted(fams.items(), key=lambda kv: -kv[1]):
        print(f"  {name:24s} {secs * 1000:9.1f} ms")
    counts = {"index": {"hit": 0, "miss": 0},
              "program": {"hit": 0, "miss": 0}}
    for stats in files.values():
        for kind, val in stats.items():
            if kind in counts and val in counts[kind]:
                counts[kind][val] += 1
    print("-- caches: "
          f"index {counts['index']['hit']} hit / "
          f"{counts['index']['miss']} miss; "
          f"program-findings {counts['program']['hit']} hit / "
          f"{counts['program']['miss']} miss --")
    cold = sorted(
        path for path, stats in files.items()
        if "miss" in (stats.get("index"), stats.get("program"))
    )
    for path in cold:
        stats = files[path]
        print(f"  {path}: index={stats.get('index', '-')} "
              f"program={stats.get('program', '-')}")


if __name__ == "__main__":
    sys.exit(main())
