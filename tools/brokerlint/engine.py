"""brokerlint core: findings, suppressions, baselines, the runner.

Repo-aware AST analysis for the broker (the role clippy lints +
erlang's dialyzer checks play for the reference).  Three rule
families (see the sibling modules):

  * async-concurrency  (``ASYNC1xx``, asyncrules.py)   — blocking
    calls / sync waits inside ``async def``, asyncio locks held
    across IO awaits, cancel-then-await shutdown hangs (bpo-37658),
    dropped ``create_task`` results;
  * device-purity      (``DEVICE2xx``, devicerules.py) — host syncs,
    host-numpy calls, and tracer-valued python branches inside
    ``@jax.jit`` code, unhashable static args;
  * failpoint-coverage (``FP301``, failpointrules.py)  — declared IO
    seams must carry a ``failpoints.evaluate`` call;
  * dispatch-perf     (``PERF4xx``, perfrules.py)      — no
    per-subscriber encode calls (PERF401) or per-delivery clock
    reads (PERF402) inside dispatch-marked hot loops (the
    single-encode / one-clock-per-run fan-out invariants).

Suppression: a ``# brokerlint: ignore[RULE]`` comment on the finding's
line (or on a comment-only line directly above it) silences that rule
there; ``ignore[*]`` silences every rule on the line.  Suppressions
are for *intentional* designs (e.g. a lock that IS the per-peer
ordering/backpressure bound) and should carry a justification comment.

Baseline: a checked-in file of finding fingerprints (line-number free,
so unrelated edits don't churn it).  The gate fails on any finding NOT
in the baseline; baselined findings are debt to burn down, and stale
entries (baselined but no longer found) are reported so the file
shrinks with the debt.
"""

from __future__ import annotations

import ast
import re
from collections import Counter
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*brokerlint:\s*ignore\[([A-Za-z0-9_*,\s]+)\]"
)
_COMMENT_ONLY_RE = re.compile(r"^\s*#")

# call names whose *await* performs (or unboundedly waits on) IO —
# used by the lock-across-IO rule and by the one-level "does this
# method do IO" resolution below
IO_AWAIT_NAMES: Set[str] = {
    "open_connection", "open_unix_connection", "start_server",
    "create_connection", "create_datagram_endpoint", "connect",
    "drain", "read", "readline", "readexactly", "readuntil",
    "recv", "recv_into", "recvfrom", "send", "sendall", "sendto",
    "request", "get", "post", "put", "delete", "fetch",
    "wait_closed", "wait_for", "wait", "getaddrinfo",
}


@dataclass
class Finding:
    path: str       # repo-relative posix path
    line: int
    rule: str
    qualname: str   # dotted function/class context ("<module>" at top)
    message: str
    detail: str = ""  # stable token for the fingerprint (no line nos)

    @property
    def fingerprint(self) -> str:
        parts = [self.path, self.qualname, self.rule]
        if self.detail:
            parts.append(self.detail)
        return "::".join(parts)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} "
                f"[{self.qualname}] {self.message}")

    def as_dict(self) -> Dict:
        return {
            "path": self.path,
            "line": self.line,
            "rule": self.rule,
            "qualname": self.qualname,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


class ModuleContext:
    """Everything the rule visitors need about one source file."""

    def __init__(self, path: str, source: str, tree: ast.Module) -> None:
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.findings: List[Finding] = []
        # one-level indirection maps, filled by _index():
        #   method qualname -> its FunctionDef node
        self.functions: Dict[str, ast.AST] = {}
        #   bare method name -> does its body await IO / evaluate a
        #   failpoint (class-blind on purpose: one level, best effort)
        self.io_methods: Set[str] = set()
        self.failpoint_methods: Set[str] = set()
        self._index()

    # ------------------------------------------------------- indexing

    def _index(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = node.name
                self.functions.setdefault(name, node)
                if _body_awaits_io(node):
                    self.io_methods.add(name)
                if _body_calls_failpoint(node):
                    self.failpoint_methods.add(name)

    # ----------------------------------------------------- reporting

    def report(self, node: ast.AST, rule: str, qualname: str,
               message: str, detail: str = "") -> None:
        line = getattr(node, "lineno", 1)
        if self._suppressed(line, rule):
            return
        self.findings.append(Finding(
            path=self.path, line=line, rule=rule,
            qualname=qualname, message=message, detail=detail,
        ))

    def _suppressed(self, line: int, rule: str) -> bool:
        for cand in (line, line - 1):
            if not (1 <= cand <= len(self.lines)):
                continue
            text = self.lines[cand - 1]
            if cand != line and not _COMMENT_ONLY_RE.match(text):
                continue  # the line above only counts if comment-only
            m = _SUPPRESS_RE.search(text)
            if m is None:
                continue
            rules = {r.strip() for r in m.group(1).split(",")}
            if "*" in rules or rule in rules:
                return True
        return False


# ---------------------------------------------------------- AST helpers

def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, '' when dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        # e.g. get_running_loop().create_task -> keep the tail only
        inner = dotted_name(node.func)
        if inner:
            parts.append(inner + "()")
    else:
        return ""
    return ".".join(reversed(parts))


def call_tail(call: ast.Call) -> str:
    """The final attribute/name of a call's callee (``drain`` for
    ``self._writer.drain()``)."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def awaits_io(expr: ast.AST, io_methods: Set[str] = frozenset()) -> Optional[str]:
    """If `expr` (an awaited value) contains an IO-performing call,
    return that call's name.  `io_methods` extends the builtin set with
    same-module methods known to await IO (one-level resolution of
    ``await self._ensure()``-style indirection)."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call):
            tail = call_tail(sub)
            if tail in IO_AWAIT_NAMES or tail in io_methods:
                return tail
    return None


def _body_awaits_io(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Await):
            if awaits_io(node.value) is not None:
                return True
    return False


def is_failpoint_call(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    return name.endswith("failpoints.evaluate") or \
        name.endswith("failpoints.evaluate_async") or \
        name in ("evaluate", "evaluate_async")


def _body_calls_failpoint(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and is_failpoint_call(node):
            return True
    return False


# -------------------------------------------------------------- runner

def analyze_source(source: str, path: str = "<string>",
                   seams: Optional[Sequence] = None,
                   dispatch: Optional[Sequence] = None) -> List[Finding]:
    """Run every rule family over one source string (fixture tests use
    this directly; `run_lint` maps it over the tree)."""
    from . import asyncrules, devicerules, failpointrules, perfrules

    tree = ast.parse(source, filename=path)
    ctx = ModuleContext(path, source, tree)
    asyncrules.check(ctx)
    devicerules.check(ctx)
    failpointrules.check(
        ctx, failpointrules.SEAM_FUNCS if seams is None else seams
    )
    perfrules.check(
        ctx, perfrules.DISPATCH_FUNCS if dispatch is None else dispatch
    )
    ctx.findings.sort(key=lambda f: (f.line, f.rule))
    return ctx.findings


def iter_py_files(paths: Sequence[str], root: Path) -> Iterable[Path]:
    for p in paths:
        path = (root / p) if not Path(p).is_absolute() else Path(p)
        if path.is_dir():
            yield from sorted(path.rglob("*.py"))
        elif path.suffix == ".py":
            yield path


def run_lint(paths: Sequence[str], root: Optional[str] = None,
             seams: Optional[Sequence] = None) -> List[Finding]:
    """Lint every .py under `paths` (files or directories), returning
    findings with repo-relative posix paths."""
    root_path = Path(root) if root else Path(__file__).resolve().parents[2]
    out: List[Finding] = []
    for f in iter_py_files(paths, root_path):
        try:
            rel = f.resolve().relative_to(root_path.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        try:
            src = f.read_text()
        except (OSError, UnicodeDecodeError):
            continue
        try:
            out.extend(analyze_source(src, rel, seams=seams))
        except SyntaxError as exc:
            out.append(Finding(
                path=rel, line=exc.lineno or 1, rule="PARSE000",
                qualname="<module>",
                message=f"syntax error: {exc.msg}",
            ))
    return out


# ------------------------------------------------------------ baseline

def load_baseline(path: str) -> Counter:
    """Fingerprint MULTISET from a baseline file ('#' comments and
    blank lines ignored; each entry should carry a justification
    comment).  A multiset because fingerprints are line-number free:
    two identical-shape violations in the same function share one
    fingerprint and need two baseline lines."""
    fps: Counter = Counter()
    p = Path(path)
    if not p.exists():
        return fps
    for line in p.read_text().splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        fps[line] += 1
    return fps


def diff_baseline(
    findings: Sequence[Finding], baseline
) -> Tuple[List[Finding], Set[str]]:
    """(new findings beyond the baselined COUNT per fingerprint, stale
    baseline entries no longer matched).  Count-aware: one baseline
    entry must not mask a SECOND identical-shape violation added later
    to the same function."""
    base = baseline if isinstance(baseline, Counter) else Counter(
        baseline
    )
    seen: Counter = Counter()
    new: List[Finding] = []
    for f in findings:
        seen[f.fingerprint] += 1
        if seen[f.fingerprint] > base.get(f.fingerprint, 0):
            new.append(f)
    stale = {
        fp for fp, n in base.items() if seen.get(fp, 0) < n
    }
    return new, stale


DEFAULT_BASELINE = str(Path(__file__).parent / "baseline.txt")
DEFAULT_PATHS = ("emqx_tpu",)
