"""brokerlint core: findings, suppressions, baselines, the runner.

Repo-aware AST analysis for the broker (the role clippy lints +
erlang's dialyzer checks play for the reference).  Six rule
families (see the sibling modules):

  * async-concurrency  (``ASYNC1xx``, asyncrules.py)   — blocking
    calls / sync waits inside ``async def``, asyncio locks held
    across IO awaits, cancel-then-await shutdown hangs (bpo-37658),
    dropped ``create_task`` results;
  * device-purity      (``DEVICE2xx``, devicerules.py) — host syncs,
    host-numpy calls, and tracer-valued python branches inside
    ``@jax.jit`` code, unhashable static args;
  * failpoint-coverage (``FP301``, failpointrules.py)  — declared IO
    seams must carry a ``failpoints.evaluate`` call;
  * dispatch-perf     (``PERF4xx``, perfrules.py)      — no
    per-subscriber encode calls (PERF401) or per-delivery clock
    reads (PERF402) inside dispatch-marked hot loops (the
    single-encode / one-clock-per-run fan-out invariants);
  * native buffer-lifetime (``NATIVE5xx``, nativerules.py) — cached
    ctypes views must not survive arena growth, no temporary buffers
    at GIL-released boundaries (interprocedural);
  * lock discipline   (``LOCK4xx``, lockrules.py)      — program-wide
    lock-order inversions, locks held across await/native
    boundaries, async+thread dual-context locks (interprocedural);
  * async atomicity   (``RACE8xx``, racerules.py)      — check-then-
    act windows across suspensions, unsafe shared-container
    iteration, thread<->loop crossings, torn multi-field updates
    over the shared-singleton roster (interprocedural), plus the
    ``MET901`` metrics-registry contract.

The interprocedural substrate (callgraph.py: whole-program index +
resolved call graph, mtime-cached; dataflow.py: bottom-up SCC
summaries) also upgrades ASYNC101 and DEVICE201/203 to see through
resolved helper calls.

Suppression: a ``# brokerlint: ignore[RULE]`` comment on the finding's
line (or on a comment-only line directly above it) silences that rule
there; ``ignore[*]`` silences every rule on the line.  Suppressions
are for *intentional* designs (e.g. a lock that IS the per-peer
ordering/backpressure bound) and should carry a justification comment.

Baseline: a checked-in file of finding fingerprints (line-number free,
so unrelated edits don't churn it).  The gate fails on any finding NOT
in the baseline; baselined findings are debt to burn down, and stale
entries (baselined but no longer found) are reported so the file
shrinks with the debt.
"""

from __future__ import annotations

import ast
import hashlib
import re
import time
from collections import Counter
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*brokerlint:\s*ignore\[([A-Za-z0-9_*,\s]+)\]"
)
_COMMENT_ONLY_RE = re.compile(r"^\s*#")


def ignore_matches(text: str, rule: str) -> bool:
    """Does this source line carry `# brokerlint: ignore[...]` for
    `rule` (or `*`)?"""
    m = _SUPPRESS_RE.search(text)
    if m is None:
        return False
    rules = {r.strip() for r in m.group(1).split(",")}
    return "*" in rules or rule in rules


def site_suppressed(lines: Sequence[str], line: int,
                    rule: str) -> bool:
    """THE suppression contract for one site: an ignore on the line
    itself, or on a comment-only line directly above.  Shared by
    finding reporting (ModuleContext) and summary base facts
    (callgraph.ModuleIndex) so the two can never drift."""
    for cand in (line, line - 1):
        if not (1 <= cand <= len(lines)):
            continue
        text = lines[cand - 1]
        if cand != line and not _COMMENT_ONLY_RE.match(text):
            continue  # the line above only counts if comment-only
        if ignore_matches(text, rule):
            return True
    return False

# call names whose *await* performs (or unboundedly waits on) IO —
# used by the lock-across-IO rule and by the one-level "does this
# method do IO" resolution below
IO_AWAIT_NAMES: Set[str] = {
    "open_connection", "open_unix_connection", "start_server",
    "create_connection", "create_datagram_endpoint", "connect",
    "drain", "read", "readline", "readexactly", "readuntil",
    "recv", "recv_into", "recvfrom", "send", "sendall", "sendto",
    "request", "get", "post", "put", "delete", "fetch",
    "wait_closed", "wait_for", "wait", "getaddrinfo",
}


@dataclass
class Finding:
    path: str       # repo-relative posix path
    line: int
    rule: str
    qualname: str   # dotted function/class context ("<module>" at top)
    message: str
    detail: str = ""  # stable token for the fingerprint (no line nos)

    @property
    def fingerprint(self) -> str:
        parts = [self.path, self.qualname, self.rule]
        if self.detail:
            parts.append(self.detail)
        return "::".join(parts)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} "
                f"[{self.qualname}] {self.message}")

    def as_dict(self) -> Dict:
        return {
            "path": self.path,
            "line": self.line,
            "rule": self.rule,
            "qualname": self.qualname,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


class ModuleContext:
    """Everything the rule visitors need about one source file."""

    def __init__(self, path: str, source: str, tree: ast.Module,
                 methods: Optional[Tuple[Set[str], Set[str]]] = None
                 ) -> None:
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.findings: List[Finding] = []
        # one-level indirection maps, filled by _index():
        #   method qualname -> its FunctionDef node
        self.functions: Dict[str, ast.AST] = {}
        #   bare method name -> does its body await IO / evaluate a
        #   failpoint (class-blind on purpose: one level, best effort)
        self.io_methods: Set[str] = set()
        self.failpoint_methods: Set[str] = set()
        if methods is not None:
            # cached from a previous run over the same (mtime, size)
            self.io_methods, self.failpoint_methods = methods
        else:
            self._index()

    # ------------------------------------------------------- indexing

    def _index(self) -> None:
        # ONE walk: collect function nodes + the lines of IO awaits
        # and failpoint calls, then attribute them to functions by
        # line interval (equivalent to the old per-function re-walks
        # — a nested def's hit marked its enclosing method there too —
        # at O(tree + f log n) instead of O(tree × depth))
        import bisect

        fns: List[ast.AST] = []
        io_lines: List[int] = []
        fp_lines: List[int] = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, node)
                fns.append(node)
            elif isinstance(node, ast.Await):
                if awaits_io(node.value) is not None:
                    io_lines.append(node.lineno)
            elif isinstance(node, ast.Call) and is_failpoint_call(node):
                fp_lines.append(node.lineno)
        io_lines.sort()
        fp_lines.sort()
        for node in fns:
            lo, hi = node.lineno, getattr(node, "end_lineno",
                                          node.lineno)
            i = bisect.bisect_left(io_lines, lo)
            if i < len(io_lines) and io_lines[i] <= hi:
                self.io_methods.add(node.name)
            i = bisect.bisect_left(fp_lines, lo)
            if i < len(fp_lines) and fp_lines[i] <= hi:
                self.failpoint_methods.add(node.name)

    # ----------------------------------------------------- reporting

    def report(self, node: ast.AST, rule: str, qualname: str,
               message: str, detail: str = "") -> None:
        line = getattr(node, "lineno", 1)
        if self._suppressed(line, rule, node):
            return
        self.findings.append(Finding(
            path=self.path, line=line, rule=rule,
            qualname=qualname, message=message, detail=detail,
        ))

    def report_at(self, line: int, rule: str, qualname: str,
                  message: str, detail: str = "") -> None:
        """Report by line number (program-level rules that carry a
        site rather than a node)."""
        if self._suppressed(line, rule):
            return
        self.findings.append(Finding(
            path=self.path, line=line, rule=rule,
            qualname=qualname, message=message, detail=detail,
        ))

    def _suppressed(self, line: int, rule: str,
                    node: Optional[ast.AST] = None) -> bool:
        if site_suppressed(self.lines, line, rule):
            return True
        # function-level findings additionally honor ignores on every
        # decorator line, the comment line above the first decorator,
        # and the whole (possibly multi-line) def header — so the
        # closing-paren line of a long signature works too
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            decs = getattr(node, "decorator_list", [])
            first = min((d.lineno for d in decs), default=node.lineno)
            body_start = (node.body[0].lineno if node.body
                          else node.lineno + 1)
            extra = {d.lineno for d in decs} | set(
                range(node.lineno, body_start)
            )
            for cand in sorted(extra):
                if 1 <= cand <= len(self.lines) and ignore_matches(
                    self.lines[cand - 1], rule
                ):
                    return True
            if site_suppressed(self.lines, first, rule):
                return True
        return False


# ---------------------------------------------------------- AST helpers

def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, '' when dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        # e.g. get_running_loop().create_task -> keep the tail only
        inner = dotted_name(node.func)
        if inner:
            parts.append(inner + "()")
    else:
        return ""
    return ".".join(reversed(parts))


def call_tail(call: ast.Call) -> str:
    """The final attribute/name of a call's callee (``drain`` for
    ``self._writer.drain()``)."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def awaits_io(expr: ast.AST, io_methods: Set[str] = frozenset()) -> Optional[str]:
    """If `expr` (an awaited value) contains an IO-performing call,
    return that call's name.  `io_methods` extends the builtin set with
    same-module methods known to await IO (one-level resolution of
    ``await self._ensure()``-style indirection)."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call):
            tail = call_tail(sub)
            if tail in IO_AWAIT_NAMES or tail in io_methods:
                return tail
    return None


def is_failpoint_call(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    return name.endswith("failpoints.evaluate") or \
        name.endswith("failpoints.evaluate_async") or \
        name in ("evaluate", "evaluate_async")


# -------------------------------------------------------------- runner

# last run's profile, rewritten by every run_lint call:
#   {"families": {name: seconds}, "files": {path: {"index": "hit"|
#    "miss", "program": "hit"|"miss"}}}
# __main__ prints it under --profile; tests assert its shape.
LAST_PROFILE: Dict = {}


def _tick(prof: Optional[Dict], family: str, t0: float) -> None:
    if prof is not None:
        fam = prof["families"]
        fam[family] = fam.get(family, 0.0) + (time.perf_counter() - t0)


def _mark(prof: Optional[Dict], path: str, kind: str,
          value: str) -> None:
    if prof is not None:
        prof["files"].setdefault(path, {})[kind] = value


def _run_file_checks(ctx: ModuleContext,
                     seams: Optional[Sequence],
                     dispatch: Optional[Sequence],
                     prof: Optional[Dict] = None) -> None:
    from . import (
        asyncrules, devicerules, durrules, failpointrules, obsrules,
        perfrules,
    )

    for family, run in (
        ("file:async", lambda: asyncrules.check(ctx)),
        ("file:device", lambda: devicerules.check(ctx)),
        ("file:dur", lambda: durrules.check(ctx)),
        ("file:failpoint", lambda: failpointrules.check(
            ctx, failpointrules.SEAM_FUNCS if seams is None else seams
        )),
        ("file:perf", lambda: perfrules.check(
            ctx,
            perfrules.DISPATCH_FUNCS if dispatch is None else dispatch
        )),
        ("file:obs", lambda: obsrules.check(
            ctx,
            perfrules.DISPATCH_FUNCS if dispatch is None else dispatch
        )),
    ):
        t0 = time.perf_counter()
        run()
        _tick(prof, family, t0)


def _dep_digest(path: str, program, summaries, extra: str) -> str:
    """Cache key for one file's LOCAL program findings: every own
    function's summary signature, every resolved direct callee's
    (key, signature) — transitive facts are already folded into the
    direct summaries by the SCC pass — plus the race/metrics context
    slice (`extra`).  The file's own source is implicit: the cache
    lives on its mtime-keyed ModuleIndex.  Editing ONLY a callee
    changes that callee's signature and therefore this digest — the
    invalidation the naive own-mtime key misses."""
    from . import dataflow

    h = hashlib.sha256()
    h.update(extra.encode())
    mod = program.modules[path]
    for qual in sorted(mod.funcs):
        fn = mod.funcs[qual]
        s = summaries.get(fn.key)
        h.update(qual.encode())
        h.update(b"\x00")
        h.update(dataflow.summary_sig(s).encode() if s else b"-")
        for _call, callee in program.callees(fn):
            cs = summaries.get(callee.key)
            h.update(repr(callee.key).encode())
            h.update(dataflow.summary_sig(cs).encode() if cs else b"-")
        h.update(b"\x01")
    return h.hexdigest()


def _run_program_checks(modules: Dict, ctxs: Dict[str, ModuleContext],
                        shared: Optional[Sequence] = None,
                        prof: Optional[Dict] = None,
                        use_cache: bool = False) -> None:
    """The interprocedural pass: call-graph + summaries once, then
    every whole-program rule family (transitive ASYNC101,
    transitive DEVICE201/203, NATIVE5xx, LOCK4xx, RACE8xx, MET901)
    reports through the per-file contexts so suppression and
    fingerprints behave identically to the intra-function rules.

    Families split two ways:
      * LOCAL — findings land in the same file whose functions they
        analyze and depend only on that file + its direct callee
        summaries (async/device/native/race-local/metrics).  With
        `use_cache`, each file's local findings replay from its
        ModuleIndex when the dependency digest matches.
      * GLOBAL — findings mix state from many files (lock cycles,
        dual-context locks, thread<->loop crossings); always
        recomputed, they're cheap (restricted walks).
    """
    from . import (
        asyncrules, callgraph, dataflow, devicerules, lockrules,
        nativerules, racerules,
    )

    t0 = time.perf_counter()
    program = callgraph.build_program(modules)
    summaries = dataflow.summarize(program)
    rc = racerules.prepare(program, summaries, shared)
    _tick(prof, "program:summaries", t0)

    local_ctxs: Dict[str, ModuleContext] = ctxs
    misses: List[Tuple[str, ModuleContext, int, str]] = []
    if use_cache:
        local_ctxs = {}
        t0 = time.perf_counter()
        for path, ctx in ctxs.items():
            idx = modules[path]
            digest = _dep_digest(path, program, summaries,
                                 rc.file_extra(path))
            cached = getattr(idx, "program_cache", None)
            if cached is not None and cached[0] == digest:
                ctx.findings.extend(cached[1])
                _mark(prof, path, "program", "hit")
            else:
                local_ctxs[path] = ctx
                misses.append((path, ctx, len(ctx.findings), digest))
                _mark(prof, path, "program", "miss")
        _tick(prof, "program:digest", t0)

    for family, run in (
        ("program:async", lambda: asyncrules.check_program(
            program, summaries, local_ctxs)),
        ("program:device", lambda: devicerules.check_program(
            program, summaries, local_ctxs)),
        ("program:native", lambda: nativerules.check_program(
            program, summaries, local_ctxs)),
        ("program:race-local", lambda: racerules.check_local(
            rc, local_ctxs)),
    ):
        t0 = time.perf_counter()
        run()
        _tick(prof, family, t0)

    if use_cache:
        for path, ctx, start, digest in misses:
            modules[path].program_cache = (
                digest, tuple(ctx.findings[start:])
            )

    # global families AFTER the cache capture: their findings must
    # never be frozen into a single file's cache entry
    for family, run in (
        ("program:lock", lambda: lockrules.check_program(
            program, summaries, ctxs)),
        ("program:race-global", lambda: racerules.check_global(
            rc, ctxs)),
    ):
        t0 = time.perf_counter()
        run()
        _tick(prof, family, t0)


def analyze_source(source: str, path: str = "<string>",
                   seams: Optional[Sequence] = None,
                   dispatch: Optional[Sequence] = None,
                   shared: Optional[Sequence] = None) -> List[Finding]:
    """Run every rule family — intra-function AND the interprocedural
    pass, over this one module — on a source string (fixture tests
    use this directly; `run_lint` maps the same checks over the
    tree).  `shared` overrides the RACE8xx roster (racerules
    .SHARED_CLASSES) for fixture classes."""
    from . import callgraph

    idx = callgraph.ModuleIndex(path, source)  # ONE parse, shared
    ctx = ModuleContext(path, source, idx.tree)
    _run_file_checks(ctx, seams, dispatch)
    _run_program_checks({path: idx}, {path: ctx}, shared=shared)
    ctx.findings.sort(key=lambda f: (f.line, f.rule))
    return ctx.findings


def analyze_program(sources: Dict[str, str],
                    seams: Optional[Sequence] = None,
                    dispatch: Optional[Sequence] = None,
                    shared: Optional[Sequence] = None
                    ) -> List[Finding]:
    """Run every rule family over a MULTI-module fixture tree
    ({path: source}): the cross-module test surface for the
    interprocedural rules (a jit helper two modules deep, opposite
    lock orders in two files)."""
    from . import callgraph

    ctxs: Dict[str, ModuleContext] = {}
    modules: Dict[str, callgraph.ModuleIndex] = {}
    for path, source in sources.items():
        idx = callgraph.ModuleIndex(path, source)
        ctx = ModuleContext(path, source, idx.tree)
        _run_file_checks(ctx, seams, dispatch)
        ctxs[path] = ctx
        modules[path] = idx
    _run_program_checks(modules, ctxs, shared=shared)
    out: List[Finding] = []
    for ctx in ctxs.values():
        out.extend(ctx.findings)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def iter_py_files(paths: Sequence[str], root: Path) -> Iterable[Path]:
    for p in paths:
        path = (root / p) if not Path(p).is_absolute() else Path(p)
        if path.is_dir():
            yield from sorted(path.rglob("*.py"))
        elif path.suffix == ".py":
            yield path


def run_lint(paths: Sequence[str], root: Optional[str] = None,
             seams: Optional[Sequence] = None) -> List[Finding]:
    """Lint every .py under `paths` (files or directories), returning
    findings with repo-relative posix paths.  Parsing + indexing is
    cached per (file, mtime, size) — see callgraph._INDEX_CACHE — so
    repeated whole-tree runs only re-parse what changed; the
    interprocedural pass runs over the files of THIS invocation."""
    from . import callgraph

    global LAST_PROFILE
    prof: Dict = {"families": {}, "files": {}}
    root_path = Path(root) if root else Path(__file__).resolve().parents[2]
    out: List[Finding] = []
    ctxs: Dict[str, ModuleContext] = {}
    modules: Dict[str, callgraph.ModuleIndex] = {}
    for f in iter_py_files(paths, root_path):
        try:
            rel = f.resolve().relative_to(root_path.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        try:
            idx = callgraph.index_file(str(f), rel)
        except (OSError, UnicodeDecodeError):
            continue
        except SyntaxError as exc:
            out.append(Finding(
                path=rel, line=exc.lineno or 1, rule="PARSE000",
                qualname="<module>",
                message=f"syntax error: {exc.msg}",
            ))
            continue
        _mark(prof, rel, "index",
              "hit" if idx.from_cache else "miss")
        cache = getattr(idx, "file_cache", None) if seams is None \
            else None
        if cache is not None:
            # per-file findings are deterministic in the source, so a
            # mtime-cached index replays them without re-running the
            # intra-function families
            base, io_m, fp_m = cache
            ctx = ModuleContext(rel, idx.source, idx.tree,
                                methods=(io_m, fp_m))
            ctx.findings = list(base)
        else:
            ctx = ModuleContext(rel, idx.source, idx.tree)
            _run_file_checks(ctx, seams, None, prof=prof)
            if seams is None:
                idx.file_cache = (
                    tuple(ctx.findings), ctx.io_methods,
                    ctx.failpoint_methods,
                )
        ctxs[rel] = ctx
        modules[rel] = idx
    _run_program_checks(modules, ctxs, prof=prof,
                        use_cache=seams is None)
    for ctx in ctxs.values():
        out.extend(ctx.findings)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    LAST_PROFILE = prof
    return out


# ------------------------------------------------------------ baseline

def load_baseline(path: str) -> Counter:
    """Fingerprint MULTISET from a baseline file ('#' comments and
    blank lines ignored; each entry should carry a justification
    comment).  A multiset because fingerprints are line-number free:
    two identical-shape violations in the same function share one
    fingerprint and need two baseline lines."""
    fps: Counter = Counter()
    p = Path(path)
    if not p.exists():
        return fps
    for line in p.read_text().splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        fps[line] += 1
    return fps


def diff_baseline(
    findings: Sequence[Finding], baseline
) -> Tuple[List[Finding], Set[str]]:
    """(new findings beyond the baselined COUNT per fingerprint, stale
    baseline entries no longer matched).  Count-aware: one baseline
    entry must not mask a SECOND identical-shape violation added later
    to the same function."""
    base = baseline if isinstance(baseline, Counter) else Counter(
        baseline
    )
    seen: Counter = Counter()
    new: List[Finding] = []
    for f in findings:
        seen[f.fingerprint] += 1
        if seen[f.fingerprint] > base.get(f.fingerprint, 0):
            new.append(f)
    stale = {
        fp for fp, n in base.items() if seen.get(fp, 0) < n
    }
    return new, stale


DEFAULT_BASELINE = str(Path(__file__).parent / "baseline.txt")
# the analyzer eats its own dog food: tools/ (brokerlint itself) and
# bench.py are part of the default gate surface alongside the broker
DEFAULT_PATHS = ("emqx_tpu", "tools", "bench.py")
