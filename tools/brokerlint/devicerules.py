"""Device-purity rules (DEVICE2xx).

The paper's premise is that matching lives in batched XLA kernels: a
host sync or a tracer-branching ``if`` silently falling into a
``@jax.jit`` function destroys the perf story (forced device->host
round-trip per step, or a recompile per distinct value).  These rules
walk every function the module jit-compiles — decorated with
``@jax.jit`` / ``@partial(jax.jit, ...)`` or wrapped via
``jax.jit(fn)`` — and flag host escapes:

  DEVICE201  host sync inside jit: ``.item()`` / ``.tolist()``, or
             ``float()``/``int()``/``bool()`` on a traced value —
             each forces a blocking device->host transfer (and
             tracer-boolean conversion raises at trace time).
  DEVICE202  python ``if``/``while`` on a tracer-valued expression
             inside jit: branches on data must be ``jnp.where`` /
             ``lax.cond`` (shape/dtype/static-arg branches are fine).
  DEVICE203  host-numpy call (``np.*``) on a traced value inside jit:
             silently pulls the array off-device (constants built
             from static values are fine).
  DEVICE204  unhashable static arg: a ``static_argnums``/
             ``static_argnames`` parameter defaulted to (or called
             with) a list/dict/set — every call re-hashes, fails, and
             forces a retrace.

Staticness is decided structurally: constants, shape/dtype/size/ndim
attributes, ``len()``/``isinstance()`` results, and declared static
parameters are static; anything referencing a non-static parameter is
traced.  Names the analysis cannot see (locals, globals) are assumed
static — the rules under-approximate rather than spam.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from .engine import ModuleContext, call_tail, dotted_name

_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "at"}
_STATIC_FNS = {"len", "isinstance", "hasattr", "range", "type"}
_CASTS = {"float", "int", "bool", "complex"}


def _jit_decorated(fn) -> Optional[ast.expr]:
    """The jit decorator node when `fn` is jit-compiled directly."""
    for dec in fn.decorator_list:
        name = dotted_name(dec if not isinstance(dec, ast.Call)
                           else dec.func)
        if name.endswith("jit"):
            return dec
        if isinstance(dec, ast.Call) and name.endswith("partial"):
            if dec.args and dotted_name(dec.args[0]).endswith("jit"):
                return dec
    return None


def _wrapped_names(tree: ast.Module) -> Set[str]:
    """Functions compiled indirectly: any ``jax.jit(fn)`` call whose
    argument is a bare name (``self._jit = jax.jit(fn)`` and friends)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and dotted_name(
            node.func
        ).endswith("jit"):
            for arg in node.args[:1]:
                if isinstance(arg, ast.Name):
                    out.add(arg.id)
    return out


def _static_params(fn, dec: Optional[ast.expr]) -> Set[str]:
    """Parameter names declared static on the jit decorator."""
    params = [a.arg for a in (fn.args.posonlyargs + fn.args.args)]
    static: Set[str] = set()
    if dec is None or not isinstance(dec, ast.Call):
        return static
    for kw in dec.keywords:
        val = kw.value
        if kw.arg == "static_argnames":
            for sub in ast.walk(val):
                if isinstance(sub, ast.Constant) and isinstance(
                    sub.value, str
                ):
                    static.add(sub.value)
        elif kw.arg == "static_argnums":
            for sub in ast.walk(val):
                if isinstance(sub, ast.Constant) and isinstance(
                    sub.value, int
                ) and 0 <= sub.value < len(params):
                    static.add(params[sub.value])
    # keyword-only args are static by construction in jax only when
    # named; treat declared names as the whole static set
    return static


class _Staticness:
    """Structural static/traced classifier for one jit function."""

    def __init__(self, traced: Set[str]) -> None:
        self.traced = traced  # parameter names that carry tracers

    def is_static(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, ast.Name):
            return node.id not in self.traced
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return True
            return self.is_static(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_static(node.value)
        if isinstance(node, ast.Call):
            fname = call_tail(node)
            if fname in _STATIC_FNS:
                return True
            return all(self.is_static(a) for a in node.args) and all(
                self.is_static(k.value) for k in node.keywords
            )
        if isinstance(node, (ast.BoolOp, ast.BinOp, ast.UnaryOp,
                             ast.Compare, ast.IfExp, ast.Tuple,
                             ast.List)):
            return all(
                self.is_static(c) for c in ast.iter_child_nodes(node)
                if isinstance(c, ast.expr)
            )
        if isinstance(node, (ast.boolop, ast.operator, ast.unaryop,
                             ast.cmpop)):
            return True
        return False


def _check_jit_body(ctx: ModuleContext, fn, qualname: str,
                    static: Set[str]) -> None:
    params = {
        a.arg
        for a in (fn.args.posonlyargs + fn.args.args
                  + fn.args.kwonlyargs)
    } - static - {"self", "cls"}
    cls = _Staticness(params)
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            tail = call_tail(node)
            name = dotted_name(node.func)
            if tail in ("item", "tolist") and not node.args:
                ctx.report(
                    node, "DEVICE201", qualname,
                    f"`.{tail}()` inside jit forces a blocking "
                    f"device->host sync",
                    detail=tail,
                )
            elif (isinstance(node.func, ast.Name)
                    and node.func.id in _CASTS and node.args
                    and not cls.is_static(node.args[0])):
                ctx.report(
                    node, "DEVICE201", qualname,
                    f"`{node.func.id}()` on a traced value inside jit "
                    f"forces a host sync (tracer bool/int conversion "
                    f"raises at trace time)",
                    detail=node.func.id,
                )
            elif (name.startswith(("np.", "numpy."))
                    and node.args
                    and any(not cls.is_static(a) for a in node.args)):
                ctx.report(
                    node, "DEVICE203", qualname,
                    f"host-numpy call `{name}` on a traced value "
                    f"inside jit pulls the array off-device — use "
                    f"jnp/lax",
                    detail=name,
                )
        elif isinstance(node, (ast.If, ast.While)):
            if not cls.is_static(node.test):
                kind = "if" if isinstance(node, ast.If) else "while"
                ctx.report(
                    node, "DEVICE202", qualname,
                    f"python `{kind}` on a tracer-valued expression "
                    f"inside jit (use jnp.where / lax.cond; branch "
                    f"on shapes or static args instead)",
                    detail=kind,
                )


def _check_static_hashability(ctx: ModuleContext, fn, qualname: str,
                              static: Set[str]) -> None:
    """DEVICE204: a static param defaulted to a mutable literal."""
    args = fn.args
    pos = args.posonlyargs + args.args
    for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
        if a.arg in static and isinstance(
            d, (ast.List, ast.Dict, ast.Set)
        ):
            ctx.report(
                d, "DEVICE204", qualname,
                f"static arg `{a.arg}` defaults to an unhashable "
                f"{type(d).__name__.lower()} — jit re-hashes statics "
                f"per call; use a tuple/frozen value",
                detail=a.arg,
            )
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if d is not None and a.arg in static and isinstance(
            d, (ast.List, ast.Dict, ast.Set)
        ):
            ctx.report(
                d, "DEVICE204", qualname,
                f"static arg `{a.arg}` defaults to an unhashable "
                f"{type(d).__name__.lower()} — use a tuple/frozen "
                f"value",
                detail=a.arg,
            )


def _check_call_sites(ctx: ModuleContext, tree: ast.Module,
                      static_by_fn: Dict[str, Set[str]]) -> None:
    """DEVICE204 at call sites: passing a list/dict/set literal for a
    known static kwarg of a module-local jit function."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        static = static_by_fn.get(call_tail(node))
        if not static:
            continue
        for kw in node.keywords:
            if kw.arg in static and isinstance(
                kw.value, (ast.List, ast.Dict, ast.Set)
            ):
                ctx.report(
                    kw.value, "DEVICE204", "<module>",
                    f"unhashable {type(kw.value).__name__.lower()} "
                    f"passed for static arg `{kw.arg}` — every call "
                    f"fails the static hash and retraces",
                    detail=f"call:{kw.arg}",
                )


def check(ctx: ModuleContext) -> None:
    wrapped = _wrapped_names(ctx.tree)
    static_by_fn: Dict[str, Set[str]] = {}
    stack: List[str] = []

    def walk(node) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                stack.append(child.name)
                walk(child)
                stack.pop()
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                stack.append(child.name)
                qual = ".".join(stack)
                dec = _jit_decorated(child)
                if dec is not None or child.name in wrapped:
                    static = _static_params(child, dec)
                    static_by_fn[child.name] = static
                    _check_jit_body(ctx, child, qual, static)
                    _check_static_hashability(ctx, child, qual, static)
                    # nested defs inside a jit body are traced too and
                    # already covered by the ast.walk over the parent —
                    # don't descend and double-report
                else:
                    walk(child)
                stack.pop()
            else:
                walk(child)

    walk(ctx.tree)
    _check_call_sites(ctx, ctx.tree, static_by_fn)


def check_program(program, summaries, ctxs) -> None:
    """Transitive DEVICE201/203: a helper call chain inside a
    ``@jax.jit`` body that ends in a host sync.  The intra rule sees
    ``x.item()`` in the jit body; this one sees
    ``helper(x)`` → ``helper2(x)`` → ``x.item()`` across modules.
    ``sync_always`` summaries (``.item()``/``.tolist()``) propagate
    unconditionally; ``sync_traced`` ones (``float(p)``/``np.f(p)``
    on a parameter-derived value) only fire when the jit call site
    actually passes a traced argument — constants stay host math at
    trace time, exactly like the intra staticness contract."""
    from .dataflow import flow_params

    # every jit-compiled function in the program: its own intra pass
    # covers its body, so edges INTO another jit fn are not re-flagged
    jit = {}
    for mod in program.modules.values():
        wrapped = mod.wrapped_cache
        if wrapped is None:
            wrapped = mod.wrapped_cache = _wrapped_names(mod.tree)
        for fn in mod.funcs.values():
            dec = _jit_decorated(fn.node)
            if dec is not None or fn.name in wrapped:
                jit[fn.key] = _static_params(fn.node, dec)
    for fn in program.functions():
        static = jit.get(fn.key)
        if static is None:
            continue
        ctx = ctxs.get(fn.module.path)
        if ctx is None:
            continue
        args = fn.node.args
        traced = {
            a.arg for a in (args.posonlyargs + args.args
                            + args.kwonlyargs)
        } - static - {"self", "cls"}
        cls = _Staticness(traced)
        for call, callee in program.callees(fn):
            if callee.key in jit:
                continue
            s = summaries.get(callee.key)
            if s is None:
                continue
            hit = None
            if s.sync_always is not None:
                hit = s.sync_always
            elif s.sync_traced is not None and flow_params(
                call, callee, s.sync_traced_params, cls
            ) is not None:
                hit = s.sync_traced
            if hit is None:
                continue
            rule, name, via = hit
            chain = f"{callee.name} -> {via}" if via else callee.name
            what = ("host sync" if rule == "DEVICE201"
                    else "host-numpy call")
            ctx.report(
                call, rule, fn.qualname,
                f"`{callee.name}()` transitively performs a {what} "
                f"(`{name}`, via `{chain}`) inside jit — a blocking "
                f"device->host round-trip per step; keep the helper "
                f"chain on-device (jnp/lax) or hoist the sync out of "
                f"the jit region",
                detail=f"via:{callee.name}:{name}",
            )


__all__ = ["check", "check_program"]
