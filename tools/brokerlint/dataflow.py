"""Forward dataflow over the resolved call graph: per-function
summaries computed bottom-up over SCCs.

Each function gets one ``FnSummary`` describing the facts the
interprocedural rules consume:

  * ``blocks``       — calling this SYNC function (transitively)
                       executes an event-loop-blocking call
                       (``time.sleep``, sync subprocess/socket/HTTP);
                       feeds the transitive ASYNC101 upgrade.
  * ``awaits_io``    — awaiting this ASYNC function (transitively)
                       performs IO; feeds LOCK402 and the
                       interprocedural ASYNC103 generalization.
  * ``sync_always``  — this function (transitively) host-syncs
                       unconditionally (``.item()``/``.tolist()``);
                       feeds transitive DEVICE201.
  * ``sync_traced``  — this function host-syncs IF a traced value
                       flows into it (``float(x)``/``np.f(x)`` on a
                       parameter-derived value); feeds transitive
                       DEVICE201/203 — the caller side checks that the
                       jit call site actually passes a traced arg.
  * ``invalidates``  — this function (transitively) grows or clears
                       an encoder ``arena`` buffer, which dangles any
                       cached ``native_views``/``span_arrays`` ctypes
                       pointer (NATIVE501).
  * ``native``       — this function (transitively) enters a
                       GIL-released native entry point
                       (``da_``/``ht_``/``td_``/``su_``/``dslog_``
                       C-ABI symbols); feeds LOCK402.
  * ``acquires``     — normalized lock tokens this function
                       (transitively) acquires; feeds the LOCK401
                       lock-order graph.
  * ``suspends``     — awaiting this ASYNC function can GENUINELY
                       yield the event loop to another task (it
                       transitively awaits IO, a sleep/gather/queue/
                       lock primitive, a bare future, or enters an
                       ``async for``/``async with``).  Strictly wider
                       than ``awaits_io`` — ``await asyncio.sleep(0)``
                       suspends without IO — and the atomicity-window
                       fact RACE801/802/804 hang on: an await of a
                       pure async helper that never suspends does NOT
                       open a task-switch window.
  * ``mutates``      — ``module.Class.attr`` tokens for the
                       self-attributes this function (transitively,
                       through resolved calls — including
                       ``self.cb = self._m`` aliases) mutates; feeds
                       the RACE802 iterate-while-mutating check and
                       the RACE801 act-through-helper resolution.

Facts are monotone (None -> value, sets grow), so mutual recursion
converges: Tarjan emits SCCs callee-first and each SCC iterates to a
fixpoint before its callers are summarized.  Base facts respect inline
``# brokerlint: ignore[RULE]`` suppressions at their site — a
justified blocking call in a loader does not poison every transitive
caller.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from . import callgraph
from .asyncrules import _is_lockish, is_blocking_call
from .devicerules import _CASTS, _Staticness
from .engine import IO_AWAIT_NAMES, awaits_io, call_tail, dotted_name

Key = Tuple[str, str]  # (path, qualname)

# awaited call tails that suspend WITHOUT being IO: scheduling
# primitives, queue/lock waits, executor hand-offs.  Together with
# IO_AWAIT_NAMES these are the base "this await can yield the loop"
# facts; `sleep` covers asyncio.sleep(0), the canonical pure yield.
SUSPEND_AWAIT_NAMES: Set[str] = IO_AWAIT_NAMES | {
    "sleep", "gather", "acquire", "join", "to_thread",
    "run_in_executor", "shield", "wait_durable",
}


@dataclass
class FnSummary:
    blocks: Optional[Tuple[str, str]] = None       # (name, via)
    awaits_io: Optional[Tuple[str, str]] = None    # (name, via)
    sync_always: Optional[Tuple[str, str, str]] = None  # (rule, name, via)
    sync_traced: Optional[Tuple[str, str, str]] = None  # (rule, name, via)
    # the function's OWN param names that feed the sync_traced site
    # (parameter-aware taint: a constant fed to them does not sync)
    sync_traced_params: Tuple[str, ...] = ()
    invalidates: Optional[str] = None              # site token
    native: Optional[str] = None                   # entry name
    acquires: Set[str] = field(default_factory=set)
    # does the BODY contain a token-resolved lock acquisition?  (the
    # lock rules skip their held-walk for lock-free functions)
    has_lock_ctx: bool = False
    suspends: Optional[Tuple[str, str]] = None     # (name, via)
    mutates: Set[str] = field(default_factory=set)  # mod.Cls.attr


# ----------------------------------------------------------- helpers

def walk_pruned(fn_node: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body, skipping nested def/lambda subtrees
    (they are their own functions and must not leak facts)."""
    stack: List[ast.AST] = [fn_node]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            yield child
            stack.append(child)


def awaited_calls(fn_node: ast.AST) -> Set[int]:
    """id()s of Call nodes that execute under an ``await`` (directly
    or nested in the awaited expression, e.g.
    ``await wait_for(self._io(), 2)``)."""
    out: Set[int] = set()
    for node in walk_pruned(fn_node):
        if isinstance(node, ast.Await):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Call):
                    out.add(id(sub))
    return out


def traced_params(fn_node: ast.AST) -> Set[str]:
    args = fn_node.args
    return {
        a.arg for a in (args.posonlyargs + args.args + args.kwonlyargs)
    } - {"self", "cls"}


def flow_params(call: ast.Call, callee: callgraph.FuncInfo,
                target_params: Tuple[str, ...],
                static_cls: _Staticness) -> Optional[Set[str]]:
    """Parameter-aware taint step: does this call feed a NON-STATIC
    (caller-traced) value into any of the callee's `target_params`?
    Returns the caller-side names appearing in the feeding
    expressions (for the caller's own summary), or None when only
    static values flow — ``helper(self.where, cols)`` does not
    propagate a sync that only touches ``where``.  Falls back to
    every argument when the call uses *args/**kwargs or the targets
    are unknown."""
    args = callee.node.args
    pos = [a.arg for a in (args.posonlyargs + args.args)]
    # bound-method calls (`obj.m(x)`) don't carry the receiver in
    # call.args; class-qualified calls (`Cls.m(obj, x)`) DO — detect
    # the latter by the receiver naming the callee's own class
    bound = isinstance(call.func, ast.Attribute) and not (
        isinstance(call.func.value, ast.Name)
        and callee.cls is not None
        and call.func.value.id == callee.cls
    )
    offset = 1 if pos and pos[0] in ("self", "cls") and bound else 0
    exprs: List[ast.expr] = []
    unmappable = (
        not target_params
        or any(isinstance(a, ast.Starred) for a in call.args)
        or any(kw.arg is None for kw in call.keywords)
    )
    if unmappable:
        exprs = list(call.args) + [kw.value for kw in call.keywords]
    else:
        for p in target_params:
            e: Optional[ast.expr] = None
            for kw in call.keywords:
                if kw.arg == p:
                    e = kw.value
                    break
            if e is None and p in pos:
                i = pos.index(p) - offset
                if 0 <= i < len(call.args):
                    e = call.args[i]
            if e is not None:
                exprs.append(e)
    traced = [e for e in exprs if not static_cls.is_static(e)]
    if not traced:
        return None
    names: Set[str] = set()
    for e in traced:
        for sub in ast.walk(e):
            if isinstance(sub, ast.Name):
                names.add(sub.id)
    return names


def _is_arena_buf(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Attribute):
        return expr.attr == "arena"
    if isinstance(expr, ast.Name):
        return expr.id == "arena"
    return False


def stmt_invalidates_arena(node: ast.AST) -> bool:
    """Does this single node grow/clear/reassign an ``arena`` buffer
    (the base NATIVE501 invalidation fact)?"""
    if isinstance(node, ast.AugAssign) and _is_arena_buf(node.target):
        return True
    if isinstance(node, ast.Assign) and any(
        _is_arena_buf(t) for t in node.targets
    ):
        return True
    if isinstance(node, ast.Call) and isinstance(
        node.func, ast.Attribute
    ) and node.func.attr in ("clear", "extend", "append") and \
            _is_arena_buf(node.func.value):
        return True
    return False


# container-mutating method tails: receiver `self.X.<tail>(...)`
# counts as a mutation of attribute X
MUTATOR_TAILS: Set[str] = {
    "append", "appendleft", "add", "remove", "discard", "pop",
    "popleft", "popitem", "clear", "update", "extend", "insert",
    "setdefault", "rotate", "sort",
}


def self_attr_of(expr: ast.AST) -> Optional[str]:
    """``self.X``/``cls.X`` -> ``X`` (None for anything else)."""
    if isinstance(expr, ast.Attribute) and isinstance(
        expr.value, ast.Name
    ) and expr.value.id in ("self", "cls"):
        return expr.attr
    return None


def _mut_target_attr(target: ast.AST) -> Optional[str]:
    """The self-attr a store/delete TARGET mutates: ``self.X``
    (rebind), ``self.X[k]`` (item store/delete)."""
    attr = self_attr_of(target)
    if attr is not None:
        return attr
    if isinstance(target, ast.Subscript):
        return self_attr_of(target.value)
    return None


def attr_mutations(node: ast.AST) -> List[str]:
    """Self-attributes this single node mutates (base RACE fact):
    assignment/augassign/del targets and container-mutator calls."""
    out: List[str] = []
    if isinstance(node, ast.Assign):
        targets: List[ast.AST] = []
        for t in node.targets:
            targets.extend(t.elts if isinstance(
                t, (ast.Tuple, ast.List)) else [t])
        for t in targets:
            attr = _mut_target_attr(t)
            if attr is not None:
                out.append(attr)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        if getattr(node, "value", True) is not None:
            attr = _mut_target_attr(node.target)
            if attr is not None:
                out.append(attr)
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            attr = _mut_target_attr(t)
            if attr is not None:
                out.append(attr)
    elif isinstance(node, ast.Call) and isinstance(
        node.func, ast.Attribute
    ) and node.func.attr in MUTATOR_TAILS:
        attr = self_attr_of(node.func.value)
        if attr is not None:
            out.append(attr)
    return out


def await_suspends(node: ast.Await) -> Optional[str]:
    """Base fact: can THIS await yield the loop?  A bare future/event
    value always can; a call only when its tail is a known suspending
    primitive (IO names + sleep/gather/queue/lock waits).  Awaits of
    unresolved helper calls return None here — the propagation step
    adds them when the resolved callee's summary suspends
    (under-approximate, never guess)."""
    v = node.value
    if not any(isinstance(s, ast.Call) for s in ast.walk(v)):
        return dotted_name(v) or "<future>"
    for sub in ast.walk(v):
        if isinstance(sub, ast.Call):
            tail = call_tail(sub)
            if tail in SUSPEND_AWAIT_NAMES:
                return tail
    return None


_LOCK_CTORS = {"Lock", "RLock", "Semaphore", "BoundedSemaphore",
               "Condition"}


def _ctor_is_lock(ctor: Optional[ast.expr]) -> bool:
    if ctor is None:
        return False
    name = dotted_name(ctor)
    return name.rpartition(".")[2] in _LOCK_CTORS


def _lock_typed(expr: ast.expr, fn: callgraph.FuncInfo,
                program: Optional[callgraph.Program]) -> bool:
    """Is this expression's KNOWN assignment a Lock-family
    constructor?  Complements the name heuristic so a lock called
    ``self._mu`` or ``gate`` still gets a graph identity."""
    mod = fn.module
    if isinstance(expr, ast.Name):
        if _ctor_is_lock(mod.mod_types.get(expr.id)):
            return True
        if program is not None and expr.id in mod.from_imports:
            b, orig = mod.from_imports[expr.id]
            origin = program.by_dotted.get(b)
            if origin is not None and _ctor_is_lock(
                origin.mod_types.get(orig)
            ):
                return True
        return False
    if isinstance(expr, ast.Attribute):
        base = expr.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls") \
                and fn.cls is not None:
            ci = mod.classes.get(fn.cls)
            if ci is not None and _ctor_is_lock(
                ci.attr_types.get(expr.attr)
            ):
                return True
        elif isinstance(base, ast.Name) and program is not None:
            if base.id in mod.import_mods:
                origin = program.by_dotted.get(
                    mod.import_mods[base.id]
                )
                if origin is not None and _ctor_is_lock(
                    origin.mod_types.get(expr.attr)
                ):
                    return True
    return False


def lock_token(expr: ast.expr, fn: callgraph.FuncInfo,
               program: Optional[callgraph.Program] = None
               ) -> Optional[str]:
    """Normalize a lock-acquisition expression to a program-wide
    identity, so the SAME lock acquired in two modules maps to one
    graph node:

      * ``self._lock`` in class K of module M  -> ``M.K._lock``
      * module-level ``with state_lock:``      -> ``M.state_lock``
      * a from-imported lock                   -> ``origin.name``

    A context expression counts as a lock when its NAME looks lockish
    (lock/sem/cond/mutex) or its known assignment is a Lock-family
    constructor.  Unknown receivers (a parameter, a dynamic
    attribute) yield None: no token, no edge — under-approximate,
    never guess."""
    if not (_is_lockish(expr) or _lock_typed(expr, fn, program)):
        return None
    mod = fn.module
    if isinstance(expr, ast.Attribute):
        base = expr.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls"):
            if fn.cls is None:
                return None
            return f"{mod.dotted}.{fn.cls}.{expr.attr}"
        if isinstance(base, ast.Name):
            # imported module's lock: mod_alias.LOCK
            if base.id in mod.import_mods:
                return f"{mod.import_mods[base.id]}.{expr.attr}"
            if base.id in mod.from_imports:
                b, orig = mod.from_imports[base.id]
                return f"{b}.{orig}.{expr.attr}"
        return None
    if isinstance(expr, ast.Name):
        if expr.id in mod.from_imports:
            b, orig = mod.from_imports[expr.id]
            return f"{b}.{orig}"
        if expr.id in mod.mod_types or expr.id in mod.mod_aliases:
            return f"{mod.dotted}.{expr.id}"
        # a module-level lock assigned `_lock = threading.Lock()` is
        # recorded in mod_types; anything else (param/local) is unknown
        return None
    return None


# --------------------------------------------------------------- SCCs

def sccs(program: callgraph.Program) -> List[List[callgraph.FuncInfo]]:
    """Tarjan over caller->callee edges; emitted callee-SCCs-first
    (each SCC appears before every SCC that can reach it), which is
    exactly the bottom-up summary order."""
    fns = program.functions()
    index: Dict[Key, int] = {}
    low: Dict[Key, int] = {}
    on_stack: Set[Key] = set()
    stack: List[callgraph.FuncInfo] = []
    out: List[List[callgraph.FuncInfo]] = []
    counter = [0]

    def strongconnect(root: callgraph.FuncInfo) -> None:
        work = [(root, iter(program.callees(root)))]
        index[root.key] = low[root.key] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root.key)
        while work:
            fn, it = work[-1]
            advanced = False
            for _call, callee in it:
                k = callee.key
                if k not in index:
                    index[k] = low[k] = counter[0]
                    counter[0] += 1
                    stack.append(callee)
                    on_stack.add(k)
                    work.append((callee, iter(program.callees(callee))))
                    advanced = True
                    break
                if k in on_stack:
                    low[fn.key] = min(low[fn.key], index[k])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent.key] = min(low[parent.key], low[fn.key])
            if low[fn.key] == index[fn.key]:
                comp: List[callgraph.FuncInfo] = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w.key)
                    comp.append(w)
                    if w.key == fn.key:
                        break
                out.append(comp)

    for fn in fns:
        if fn.key not in index:
            strongconnect(fn)
    return out


# ------------------------------------------------------- base facts

def _base_summary(fn: callgraph.FuncInfo,
                  program: Optional[callgraph.Program] = None
                  ) -> FnSummary:
    s = FnSummary()
    mod = fn.module
    node = fn.node
    tracked = _Staticness(traced_params(node))
    for sub in walk_pruned(node):
        if isinstance(sub, ast.Await):
            hit = awaits_io(sub.value)
            if hit is not None and s.awaits_io is None and fn.is_async:
                s.awaits_io = (hit, "")
            if s.suspends is None and fn.is_async:
                sus = await_suspends(sub)
                if sus is not None:
                    s.suspends = (sus, "")
        if isinstance(sub, (ast.AsyncFor, ast.AsyncWith)) and \
                s.suspends is None and fn.is_async:
            s.suspends = (
                "async-for" if isinstance(sub, ast.AsyncFor)
                else "async-with", "",
            )
        if fn.cls is not None:
            for attr in attr_mutations(sub):
                s.mutates.add(f"{mod.dotted}.{fn.cls}.{attr}")
        if stmt_invalidates_arena(sub) and s.invalidates is None:
            s.invalidates = "arena"
        if isinstance(sub, (ast.With, ast.AsyncWith)):
            for item in sub.items:
                tok = lock_token(item.context_expr, fn, program)
                if tok is not None:
                    s.acquires.add(tok)
                    s.has_lock_ctx = True
        if not isinstance(sub, ast.Call):
            continue
        tail = call_tail(sub)
        line = getattr(sub, "lineno", 1)
        if callgraph.is_native_entry(tail) and s.native is None:
            s.native = tail
        name = dotted_name(sub.func)
        if not fn.is_async and s.blocks is None and \
                is_blocking_call(name, sub) and \
                not mod.suppressed(line, "ASYNC101"):
            s.blocks = (name, "")
        if tail in ("item", "tolist") and not sub.args and \
                s.sync_always is None and not mod.suppressed(
                    line, "DEVICE201"):
            s.sync_always = ("DEVICE201", tail, "")
        elif (isinstance(sub.func, ast.Name)
                and sub.func.id in _CASTS and sub.args
                and not tracked.is_static(sub.args[0])
                and s.sync_traced is None
                and not mod.suppressed(line, "DEVICE201")):
            s.sync_traced = ("DEVICE201", sub.func.id, "")
            s.sync_traced_params = _expr_params(
                [sub.args[0]], tracked.traced
            )
        elif (name.startswith(("np.", "numpy."))
                and sub.args
                and any(not tracked.is_static(a) for a in sub.args)
                and s.sync_traced is None
                and not mod.suppressed(line, "DEVICE203")):
            s.sync_traced = ("DEVICE203", name, "")
            s.sync_traced_params = _expr_params(
                sub.args, tracked.traced
            )
    return s


def _expr_params(exprs, params: Set[str]) -> Tuple[str, ...]:
    names: Set[str] = set()
    for e in exprs:
        for sub in ast.walk(e):
            if isinstance(sub, ast.Name) and sub.id in params:
                names.add(sub.id)
    return tuple(sorted(names))


# ------------------------------------------------------- propagation

def _update(fn: callgraph.FuncInfo, s: FnSummary,
            program: callgraph.Program,
            summaries: Dict[Key, FnSummary]) -> bool:
    changed = False
    awaited = None
    tracked = None
    for call, callee in program.callees(fn):
        cs = summaries.get(callee.key)
        if cs is None:
            continue
        if s.blocks is None and cs.blocks is not None and \
                not fn.is_async and not callee.is_async:
            s.blocks = (cs.blocks[0], callee.name)
            changed = True
        if s.awaits_io is None and cs.awaits_io is not None and \
                fn.is_async and callee.is_async:
            if awaited is None:
                awaited = awaited_calls(fn.node)
            if id(call) in awaited:
                s.awaits_io = (cs.awaits_io[0], callee.name)
                changed = True
        if s.sync_always is None and cs.sync_always is not None:
            rule, nm, _via = cs.sync_always
            s.sync_always = (rule, nm, callee.name)
            changed = True
        if s.sync_traced is None and cs.sync_traced is not None:
            if tracked is None:
                tracked = _Staticness(traced_params(fn.node))
            flow = flow_params(call, callee, cs.sync_traced_params,
                               tracked)
            if flow is not None:
                rule, nm, _via = cs.sync_traced
                s.sync_traced = (rule, nm, callee.name)
                s.sync_traced_params = tuple(sorted(
                    flow & traced_params(fn.node)
                ))
                changed = True
        if s.invalidates is None and cs.invalidates is not None:
            s.invalidates = f"via:{callee.name}"
            changed = True
        if s.native is None and cs.native is not None:
            s.native = cs.native
            changed = True
        if not cs.acquires <= s.acquires:
            s.acquires |= cs.acquires
            changed = True
        if s.suspends is None and cs.suspends is not None and \
                fn.is_async and callee.is_async:
            if awaited is None:
                awaited = awaited_calls(fn.node)
            if id(call) in awaited:
                s.suspends = (cs.suspends[0], callee.name)
                changed = True
        if not cs.mutates <= s.mutates:
            s.mutates |= cs.mutates
            changed = True
    return changed


def summarize(
    program: callgraph.Program,
) -> Dict[Key, FnSummary]:
    summaries: Dict[Key, FnSummary] = {}
    for comp in sccs(program):
        for fn in comp:
            summaries[fn.key] = _base_summary(fn, program)
        # iterate the SCC to a fixpoint (singletons converge in one
        # pass; mutual recursion in a few — facts are monotone)
        for _ in range(len(comp) + 1):
            any_change = False
            for fn in comp:
                if _update(fn, summaries[fn.key], program, summaries):
                    any_change = True
            if not any_change:
                break
    return summaries


def summary_sig(s: FnSummary) -> str:
    """Stable serialization of one summary — the unit the program-
    findings cache digests: a caller's cached interprocedural findings
    are valid exactly while its own source and its direct callees'
    summary_sigs are unchanged."""
    return repr((
        s.blocks, s.awaits_io, s.sync_always, s.sync_traced,
        s.sync_traced_params, s.invalidates, s.native,
        tuple(sorted(s.acquires)), s.has_lock_ctx, s.suspends,
        tuple(sorted(s.mutates)),
    ))


__all__ = [
    "FnSummary", "MUTATOR_TAILS", "SUSPEND_AWAIT_NAMES",
    "attr_mutations", "await_suspends", "awaited_calls", "flow_params",
    "lock_token", "sccs", "self_attr_of", "stmt_invalidates_arena",
    "summarize", "summary_sig", "traced_params", "walk_pruned",
]
