"""brokerlint: repo-aware AST analysis for the broker.

Rule families: async-concurrency (ASYNC1xx), device-purity
(DEVICE2xx), failpoint-coverage (FP301), dispatch-perf
(PERF401/PERF402), native buffer-lifetime (NATIVE5xx), lock
discipline (LOCK4xx).  ASYNC101 and DEVICE201/203 also run
transitively over the resolved call graph (callgraph.py/dataflow.py).
Run as a tier-1 gate by tests/test_lint.py and standalone via
``python -m tools.brokerlint``.
"""

from .engine import (
    DEFAULT_BASELINE, DEFAULT_PATHS, Finding, analyze_program,
    analyze_source, diff_baseline, load_baseline, run_lint,
)
from .failpointrules import SEAM_FUNCS, Seam
from .perfrules import DISPATCH_FUNCS, DispatchFn

__all__ = [
    "DEFAULT_BASELINE", "DEFAULT_PATHS", "DISPATCH_FUNCS",
    "DispatchFn", "Finding", "SEAM_FUNCS", "Seam", "analyze_program",
    "analyze_source", "diff_baseline", "load_baseline", "run_lint",
]
