"""Async-concurrency rules (ASYNC1xx).

The PR-1 postmortem family: the flaky-shutdown deadlock (bpo-37658
cancel-swallow under ``asyncio.wait_for``) froze tier-1 at ~25% and
was found by luck.  These rules catch that class statically:

  ASYNC101  blocking call (``time.sleep``, sync subprocess/socket/
            HTTP) inside ``async def`` — stalls the whole event loop.
  ASYNC102  sync wait (``Future.result()`` / thread-style ``join()``)
            inside ``async def`` — deadlocks when the result is
            produced by the same loop.
  ASYNC103  ``asyncio.Lock``/``Condition``/``Semaphore`` held across
            an await that performs IO — one slow peer stalls every
            other holder; when the serialization IS the design (per-
            connection ordering / backpressure), suppress with a
            justification comment.
  ASYNC104  ``task.cancel()`` then ``await task`` (bare or under
            ``asyncio.wait_for``) in a stop/close path — a cancel
            landing as an inner ``wait_for``'s future resolves is
            swallowed (bpo-37658) and the await hangs shutdown
            forever; use ``aio.cancel_and_wait``.
  ASYNC105  ``create_task``/``ensure_future`` result dropped — the
            task is GC-bait (may vanish mid-flight) and its exception
            is never retrieved.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .engine import (
    IO_AWAIT_NAMES, ModuleContext, awaits_io, call_tail, dotted_name,
)

# dotted callee names that block the event loop (ASYNC101)
_BLOCKING_EXACT = {
    "time.sleep",
    "os.system",
    "os.popen",
    "os.waitpid",
    "select.select",
    "socket.create_connection",
    "socket.getaddrinfo",
    "urllib.request.urlopen",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.getoutput",
}
_BLOCKING_PREFIXES = ("requests.",)

_STOPPISH = ("stop", "close", "shutdown", "aclose", "terminate")

_LOCKISH = ("lock", "sem", "cond", "mutex")


def is_blocking_call(name: str, node: ast.Call) -> bool:
    """Event-loop-blocking callee?  ``time.sleep(0)`` — the literal
    GIL-yield idiom the engine's chunked copies use — is NOT a block:
    it never parks the thread, it only lets another one run."""
    if not (name in _BLOCKING_EXACT
            or name.startswith(_BLOCKING_PREFIXES)):
        return False
    if name == "time.sleep" and len(node.args) == 1 and isinstance(
        node.args[0], ast.Constant
    ) and node.args[0].value == 0:
        return False
    return True


def _is_stop_path(name: str) -> bool:
    low = name.lower()
    return any(s in low for s in _STOPPISH)


def _is_lockish(expr: ast.AST) -> bool:
    return any(tok in dotted_name(expr).lower() for tok in _LOCKISH)


def _numeric_const(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and isinstance(
        node.value, (int, float)
    ) and not isinstance(node.value, bool)


class _AsyncVisitor(ast.NodeVisitor):
    def __init__(self, ctx: ModuleContext) -> None:
        self.ctx = ctx
        self.stack: List[str] = []          # qualname parts
        self.fn_stack: List[bool] = []      # is-async per function frame

    # ------------------------------------------------------- plumbing

    @property
    def qualname(self) -> str:
        return ".".join(self.stack) or "<module>"

    @property
    def in_async(self) -> bool:
        """True when the INNERMOST enclosing function is async (a sync
        closure inside an async def — e.g. a done-callback — is sync
        code and may legally call ``.result()``)."""
        return bool(self.fn_stack) and self.fn_stack[-1]

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def _visit_fn(self, node, is_async: bool) -> None:
        self.stack.append(node.name)
        self.fn_stack.append(is_async)
        if is_async:
            self._check_cancel_await(node)
        self.generic_visit(node)
        self.fn_stack.pop()
        self.stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_fn(node, False)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_fn(node, True)

    # -------------------------------------------------- ASYNC101/102

    def visit_Call(self, node: ast.Call) -> None:
        if self.in_async:
            name = dotted_name(node.func)
            if is_blocking_call(name, node):
                self.ctx.report(
                    node, "ASYNC101", self.qualname,
                    f"blocking call `{name}` inside async function "
                    f"stalls the event loop (await the async "
                    f"equivalent instead)",
                    detail=name,
                )
            tail = call_tail(node)
            if tail == "result" and not node.args and not node.keywords:
                self.ctx.report(
                    node, "ASYNC102", self.qualname,
                    "`.result()` inside async function blocks the "
                    "loop (await the future instead)",
                    detail="result",
                )
            elif tail == "join" and self._thread_join_shaped(node):
                self.ctx.report(
                    node, "ASYNC102", self.qualname,
                    "thread-style `.join()` inside async function "
                    "blocks the loop (await, or run in an executor)",
                    detail="join",
                )
        self.generic_visit(node)

    @staticmethod
    def _thread_join_shaped(node: ast.Call) -> bool:
        """``t.join()`` / ``t.join(5)`` / ``t.join(timeout=5)`` —
        signatures ``str.join``/``os.path.join`` can never have."""
        if node.keywords:
            return all(k.arg == "timeout" for k in node.keywords)
        if not node.args:
            return True
        return len(node.args) == 1 and _numeric_const(node.args[0])

    # ------------------------------------------------------- ASYNC103

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        if any(_is_lockish(item.context_expr) for item in node.items):
            io_call = self._body_io_await(node.body)
            if io_call is not None:
                self.ctx.report(
                    node, "ASYNC103", self.qualname,
                    f"asyncio lock held across IO await "
                    f"(`{io_call}`): one slow peer stalls every other "
                    f"holder; narrow the critical section (suppress "
                    f"with a justification when the serialization is "
                    f"the design)",
                    detail=io_call,
                )
        self.generic_visit(node)

    def _body_io_await(self, body) -> Optional[str]:
        hits: List[str] = []

        def walk(node: ast.AST) -> None:
            # a PRUNING walk (ast.walk can't skip subtrees): nested
            # defs/lambdas don't run under the lock, so their awaits
            # must not count against it
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                if isinstance(child, ast.Await) and not hits:
                    hit = awaits_io(child.value, self.ctx.io_methods)
                    if hit is not None:
                        hits.append(hit)
                        return
                walk(child)

        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # a def statement directly in the with body
            walk(stmt)
            if hits:
                return hits[0]
        return None

    # ------------------------------------------------------- ASYNC104

    def _check_cancel_await(self, fn: ast.AsyncFunctionDef) -> None:
        if not _is_stop_path(fn.name):
            return
        cancelled: Set[str] = set()
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and call_tail(node) == "cancel"
                    and isinstance(node.func, ast.Attribute)):
                cancelled.add(ast.dump(node.func.value))
        if not cancelled:
            return
        for node in ast.walk(fn):
            if not isinstance(node, ast.Await):
                continue
            target = node.value
            if isinstance(target, ast.Call) and call_tail(target) in (
                "wait_for", "wait"
            ) and target.args:
                target = target.args[0]
                if isinstance(target, ast.List):  # asyncio.wait([t])
                    target = target.elts[0] if target.elts else target
            if isinstance(target, ast.Call):
                continue  # awaiting a fresh coroutine, not the task
            if ast.dump(target) in cancelled:
                self.ctx.report(
                    node, "ASYNC104", self.qualname,
                    "cancel()-then-await in a stop/close path hangs "
                    "when the cancel is swallowed by an inner "
                    "wait_for (bpo-37658) — use "
                    "aio.cancel_and_wait(task)",
                    detail=dotted_name(target) or "task",
                )

    # ------------------------------------------------------- ASYNC105

    def visit_Expr(self, node: ast.Expr) -> None:
        if isinstance(node.value, ast.Call):
            tail = call_tail(node.value)
            if tail in ("create_task", "ensure_future"):
                self.ctx.report(
                    node, "ASYNC105", self.qualname,
                    f"`{tail}` result dropped: the task may be "
                    f"garbage-collected mid-flight and its exception "
                    f"is never retrieved — retain a reference or add "
                    f"a done-callback",
                    detail=tail,
                )
        self.generic_visit(node)


def check(ctx: ModuleContext) -> None:
    _AsyncVisitor(ctx).visit(ctx.tree)


def check_program(program, summaries, ctxs) -> None:
    """Transitive ASYNC101: a plain call from ``async def`` to a SYNC
    function whose summary (transitively, through the resolved call
    graph) executes a blocking call.  The intra-function rule sees
    ``time.sleep`` in the async body; this one sees
    ``self._helper()`` → ``helper2()`` → ``subprocess.run`` across
    modules.  A justified inline ignore at the BLOCKING SITE stops
    the fact from propagating at the source (one annotation instead
    of one per caller)."""
    for fn in program.functions():
        if not fn.is_async:
            continue
        ctx = ctxs.get(fn.module.path)
        if ctx is None:
            continue
        for call, callee in program.callees(fn):
            if callee.is_async:
                continue  # calling an async fn only builds a coroutine
            s = summaries.get(callee.key)
            if s is None or s.blocks is None:
                continue
            bname, via = s.blocks
            chain = f"{callee.name} -> {via}" if via else callee.name
            ctx.report(
                call, "ASYNC101", fn.qualname,
                f"`{callee.name}()` transitively executes blocking "
                f"`{bname}` (via `{chain}`) inside async function — "
                f"stalls the event loop; offload to an executor, "
                f"make the chain async, or justify with an inline "
                f"ignore at the blocking site",
                detail=f"via:{callee.name}:{bname}",
            )


__all__ = ["check", "check_program", "IO_AWAIT_NAMES"]
