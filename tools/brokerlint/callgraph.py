"""Whole-program index + resolved call graph (the interprocedural
substrate under the NATIVE5xx/LOCK4xx families and the transitive
DEVICE/ASYNC upgrades).

PR 5 made the dispatch hot path depend on invariants that live ACROSS
functions: a cached ``native_views`` pointer must die before any arena
growth, a host sync two helper calls deep inside a ``@jax.jit`` region
still destroys the perf story, and a lock-order inversion split across
modules hangs the broker just as dead as one in a single function.
The PR-2 analyzer is intra-function, so all of those are invisible to
it.  This module builds what the rules need to see them:

  * a per-file **ModuleIndex** — every function/method by dotted
    qualname, classes with their methods/bases, import aliases
    (``import x as y`` / ``from . import z``), module-level aliases
    (``g = f``, ``g = functools.partial(f, ...)``), instance-attribute
    types (``self.router = Router(...)``), and parameter/variable type
    annotations — cached by file (mtime, size) so repeated whole-tree
    runs re-parse nothing that didn't change;
  * a **Program** over the indexed files with ``resolve_call``:
    direct calls, ``self.``/``cls.`` methods (own class, one level of
    base classes, ``self.x = self._m`` attribute aliasing), calls
    through import aliases, one-level local aliasing
    (``fn = self._m; fn()``), ``functools.partial``, and
    attribute/annotation-typed receivers
    (``enc: "C.DispatchEncoder"`` → ``enc.slot_for`` resolves).

Resolution is deliberately an UNDER-approximation: a name the index
cannot pin to exactly one function yields no edge.  Rules built on top
stay quiet rather than spam — the same contract as the staticness
classifier in devicerules.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .engine import call_tail, dotted_name

# known GIL-released native entry points: the C ABI symbol prefixes of
# native/*.cpp (da_=dispatchasm, ht_=hosttrie, td_=tokdict,
# su_=sortutil, dslog_=dslog).  A call whose tail matches is a "native
# call" base fact; wrappers (ops.dispatchasm.assemble_run, ...) pick
# it up transitively through their summaries.
NATIVE_ENTRY_PREFIXES: Tuple[str, ...] = (
    "da_", "ht_", "td_", "su_", "dslog_",
)


def is_native_entry(tail: str) -> bool:
    return tail.startswith(NATIVE_ENTRY_PREFIXES)


def module_dotted(path: str) -> str:
    """'emqx_tpu/broker/session.py' -> 'emqx_tpu.broker.session';
    '__init__.py' names the package itself."""
    p = path[:-3] if path.endswith(".py") else path
    parts = [x for x in p.split("/") if x]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class FuncInfo:
    """One function/method in the program (identity: path, qualname)."""

    __slots__ = ("module", "qualname", "node", "is_async", "cls",
                 "name", "_locals")

    def __init__(self, module: "ModuleIndex", qualname: str,
                 node: ast.AST, cls: Optional[str]) -> None:
        self.module = module
        self.qualname = qualname
        self.node = node
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        self.cls = cls               # enclosing class name (or None)
        self.name = node.name        # bare name
        self._locals = None          # lazy per-function alias/type maps

    @property
    def key(self) -> Tuple[str, str]:
        return (self.module.path, self.qualname)

    def __repr__(self) -> str:  # debugging aid only
        return f"<FuncInfo {self.module.path}:{self.qualname}>"


class _ClassInfo:
    __slots__ = ("name", "methods", "bases", "attr_aliases",
                 "attr_types")

    def __init__(self, name: str) -> None:
        self.name = name
        self.methods: Dict[str, str] = {}     # bare -> qualname
        self.bases: List[ast.expr] = []       # base class expressions
        # self.x = self._m  ->  attr_aliases['x'] = '_m'
        self.attr_aliases: Dict[str, str] = {}
        # self.x = Router(...)  ->  attr_types['x'] = <ctor expr>
        self.attr_types: Dict[str, ast.expr] = {}


class ModuleIndex:
    """Parse + index of one source file (shared with ModuleContext:
    the tree is parsed once per (mtime, size) and reused by both the
    per-file rule families and the program passes)."""

    def __init__(self, path: str, source: str) -> None:
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree: ast.Module = ast.parse(source, filename=path)
        self.dotted = module_dotted(path)
        self.funcs: Dict[str, FuncInfo] = {}       # qualname -> info
        self.classes: Dict[str, _ClassInfo] = {}
        self.import_mods: Dict[str, str] = {}      # alias -> module
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self.mod_aliases: Dict[str, str] = {}      # g = f (top level)
        self.mod_types: Dict[str, ast.expr] = {}   # x = Cls() (top)
        # run-to-run caches (valid for this (mtime, size) index):
        self.file_cache = None     # (findings, io_methods, fp_methods)
        self.wrapped_cache = None  # devicerules._wrapped_names result
        # per-file PROGRAM findings cache: (dep_digest, findings).
        # NOT keyed by this file's identity alone — the digest covers
        # the dependency summaries, so editing ONLY a callee
        # invalidates the caller's entry (engine._dep_digest)
        self.program_cache = None
        self.from_cache = False    # did index_file serve this warm?
        self._index()

    # ------------------------------------------------------- indexing

    def _pkg_parts(self) -> List[str]:
        parts = self.dotted.split(".") if self.dotted else []
        if self.path.endswith("__init__.py"):
            return parts
        return parts[:-1]

    def _rel_base(self, level: int) -> Optional[str]:
        pkg = self._pkg_parts()
        if level - 1 > len(pkg):
            return None
        base = pkg[: len(pkg) - (level - 1)] if level > 1 else pkg
        return ".".join(base)

    def _index(self) -> None:
        stack: List[str] = []

        def walk(node: ast.AST, cls: Optional[_ClassInfo]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    stack.append(child.name)
                    ci = self.classes.setdefault(
                        child.name, _ClassInfo(child.name)
                    )
                    ci.bases = list(child.bases)
                    walk(child, ci)
                    stack.pop()
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    stack.append(child.name)
                    qual = ".".join(stack)
                    fi = FuncInfo(self, qual, child,
                                  cls.name if cls else None)
                    self.funcs[qual] = fi
                    if cls is not None and len(stack) == 2:
                        cls.methods[child.name] = qual
                    if cls is not None:
                        self._scan_self_assigns(child, cls)
                    # nested defs index under their parent's qualname
                    walk(child, None)
                    stack.pop()
                else:
                    if not stack:
                        self._index_toplevel(child)
                    elif isinstance(child, (ast.Import,
                                            ast.ImportFrom)):
                        # function-level imports (the lazy-import
                        # idiom) index too; top-level entries win on
                        # a name conflict
                        self._index_import(child, top=False)
                    walk(child, cls)

        walk(self.tree, None)

    def _index_import(self, node: ast.AST, top: bool) -> None:
        if isinstance(node, ast.Import):
            for a in node.names:
                key = a.asname or a.name.split(".")[0]
                if top:
                    self.import_mods[key] = a.name
                else:
                    self.import_mods.setdefault(key, a.name)
        elif isinstance(node, ast.ImportFrom):
            base = (self._rel_base(node.level) if node.level
                    else node.module)
            if node.level and node.module:
                base = f"{base}.{node.module}" if base else node.module
            if base is None:
                return
            for a in node.names:
                if a.name == "*":
                    continue
                key = a.asname or a.name
                if top:
                    self.from_imports[key] = (base, a.name)
                else:
                    self.from_imports.setdefault(key, (base, a.name))

    def _index_toplevel(self, node: ast.AST) -> None:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            self._index_import(node, top=True)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                v = _alias_target(node.value)
                if v is not None:
                    self.mod_aliases[t.id] = v
                elif isinstance(node.value, ast.Call):
                    self.mod_types[t.id] = node.value.func

    def _scan_self_assigns(self, fn: ast.AST, cls: _ClassInfo) -> None:
        """Record ``self.x = self._m`` aliases and
        ``self.x = Router(...)`` instance-attribute types (one level:
        the constructor expression resolves at lookup time)."""
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1):
                continue
            t = node.targets[0]
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            v = node.value
            if (isinstance(v, ast.Attribute)
                    and isinstance(v.value, ast.Name)
                    and v.value.id == "self"):
                cls.attr_aliases.setdefault(t.attr, v.attr)
            elif isinstance(v, ast.Call) and not isinstance(
                v.func, ast.Lambda
            ):
                cls.attr_types.setdefault(t.attr, v.func)

    # ---------------------------------------------------- suppression

    def suppressed(self, line: int, rule: str) -> bool:
        """Same contract as ModuleContext (delegates to the ONE
        shared matcher) — base facts (e.g. a justified blocking call
        in a loader) respect inline ignores so they don't propagate
        through summaries either."""
        from .engine import site_suppressed

        return site_suppressed(self.lines, line, rule)


def _alias_target(value: ast.expr) -> Optional[str]:
    """The aliased NAME for ``g = f`` / ``g = functools.partial(f,..)``
    (None when the rhs is not an alias shape)."""
    if isinstance(value, ast.Name):
        return value.id
    if isinstance(value, ast.Call) and dotted_name(
        value.func
    ).endswith("partial") and value.args:
        a = value.args[0]
        if isinstance(a, ast.Name):
            return a.id
        if isinstance(a, ast.Attribute):
            return dotted_name(a)
    return None


# per-file index cache: abspath -> (mtime_ns, size, ModuleIndex).
# run_lint hits this once per file per run; editing a file (new mtime
# or size) invalidates exactly that entry.
_INDEX_CACHE: Dict[str, Tuple[int, int, ModuleIndex]] = {}


def index_file(abspath: str, rel: str) -> ModuleIndex:
    st = os.stat(abspath)
    key = (st.st_mtime_ns, st.st_size)
    hit = _INDEX_CACHE.get(abspath)
    if hit is not None and (hit[0], hit[1]) == key and \
            hit[2].path == rel:
        hit[2].from_cache = True
        return hit[2]
    with open(abspath, "r") as f:
        source = f.read()
    idx = ModuleIndex(rel, source)  # may raise SyntaxError (caller)
    _INDEX_CACHE[abspath] = (key[0], key[1], idx)
    return idx


class Program:
    """The indexed modules plus cross-module call resolution."""

    def __init__(self, modules: Dict[str, ModuleIndex]) -> None:
        self.modules = modules                       # rel path -> idx
        self.by_dotted: Dict[str, ModuleIndex] = {
            m.dotted: m for m in modules.values()
        }
        self._edges: Optional[Dict[Tuple[str, str],
                                   List[Tuple[ast.Call, FuncInfo]]]] \
            = None

    # ------------------------------------------------------ iteration

    def functions(self) -> List[FuncInfo]:
        out: List[FuncInfo] = []
        for m in self.modules.values():
            out.extend(m.funcs.values())
        return out

    # ------------------------------------------------------- lookups

    def _module_for(self, dotted: str) -> Optional[ModuleIndex]:
        return self.by_dotted.get(dotted)

    def lookup_toplevel(self, mod: ModuleIndex,
                        name: str) -> Optional[FuncInfo]:
        fi = mod.funcs.get(name)
        if fi is not None:
            return fi
        alias = mod.mod_aliases.get(name)
        if alias is not None and alias != name:
            return self.resolve_name(mod, alias)
        return None

    def lookup_class(self, mod: ModuleIndex,
                     name: str) -> Optional[Tuple[ModuleIndex,
                                                  _ClassInfo]]:
        ci = mod.classes.get(name)
        if ci is not None:
            return (mod, ci)
        imp = mod.from_imports.get(name)
        if imp is not None:
            base, orig = imp
            target = self._module_for(base)
            if target is not None and orig in target.classes:
                return (target, target.classes[orig])
            # `from x import y` where y is a submodule holding nothing
            # by this name: give up
        return None

    def _class_ref(self, mod: ModuleIndex,
                   expr: ast.expr) -> Optional[Tuple[ModuleIndex,
                                                     _ClassInfo]]:
        """Resolve a class-naming expression (``Router``, ``C.Foo``,
        ``Optional[Session]``, a string annotation's parsed body) to
        its _ClassInfo."""
        if isinstance(expr, ast.Constant) and isinstance(
            expr.value, str
        ):
            try:
                expr = ast.parse(expr.value, mode="eval").body
            except SyntaxError:
                return None
        # unwrap Optional[X] / typing wrappers one level
        if isinstance(expr, ast.Subscript) and dotted_name(
            expr.value
        ).rpartition(".")[2] in ("Optional",):
            expr = expr.slice
        if isinstance(expr, ast.Name):
            return self.lookup_class(mod, expr.id)
        if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ):
            base = expr.value.id
            target_mod = None
            if base in mod.import_mods:
                target_mod = self._module_for(mod.import_mods[base])
            elif base in mod.from_imports:
                b, orig = mod.from_imports[base]
                target_mod = self._module_for(f"{b}.{orig}") or \
                    self._module_for(b)
            if target_mod is not None:
                ci = target_mod.classes.get(expr.attr)
                if ci is not None:
                    return (target_mod, ci)
        return None

    def _method_in(self, mod: ModuleIndex, ci: _ClassInfo, name: str,
                   depth: int = 0) -> Optional[FuncInfo]:
        qual = ci.methods.get(name)
        if qual is not None:
            return mod.funcs.get(qual)
        alias = ci.attr_aliases.get(name)
        if alias is not None and alias != name:
            qual = ci.methods.get(alias)
            if qual is not None:
                return mod.funcs.get(qual)
        if depth < 1:  # one level of base classes
            for b in ci.bases:
                ref = self._class_ref(mod, b)
                if ref is not None:
                    hit = self._method_in(ref[0], ref[1], name,
                                          depth + 1)
                    if hit is not None:
                        return hit
        return None

    def resolve_name(self, mod: ModuleIndex,
                     name: str) -> Optional[FuncInfo]:
        """A bare NAME in module scope: local function, alias chain,
        constructor (``Cls()`` resolves to ``Cls.__init__``), or
        from-import of a function in an indexed module."""
        fi = mod.funcs.get(name)
        if fi is not None:
            return fi
        alias = mod.mod_aliases.get(name)
        if alias is not None and alias != name:
            return self.resolve_name(mod, alias)
        ref = self.lookup_class(mod, name)
        if ref is not None:
            return self._method_in(ref[0], ref[1], "__init__")
        imp = mod.from_imports.get(name)
        if imp is not None:
            base, orig = imp
            target = self._module_for(base)
            if target is not None:
                return self.lookup_toplevel(target, orig)
        return None

    # ------------------------------------------- per-function locals

    def _fn_locals(self, fn: FuncInfo) -> Tuple[Dict[str, str],
                                                Dict[str, str],
                                                Dict[str, ast.AST]]:
        """(local one-level aliases, self-attr aliases, local var
        types) for `fn`: ``g = self._m`` / ``g = partial(f, ..)``
        aliases, ``nat = self._native`` self-attribute aliases, plus
        ``x = Router(...)`` / ``x = self.cm.lookup(...)`` (typed by
        constructor or the callee's return annotation) / annotated
        params & AnnAssigns."""
        if fn._locals is not None:
            return fn._locals
        aliases: Dict[str, str] = {}
        self_aliases: Dict[str, str] = {}
        types: Dict[str, ast.AST] = {}
        node = fn.node
        args = node.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            if a.annotation is not None:
                types[a.arg] = a.annotation
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                t = sub.targets[0].id
                v = _alias_target(sub.value)
                if v is not None:
                    aliases.setdefault(t, v)
                elif isinstance(sub.value, ast.Attribute) and \
                        isinstance(sub.value.value, ast.Name) and \
                        sub.value.value.id in ("self", "cls"):
                    self_aliases.setdefault(t, sub.value.attr)
                elif isinstance(sub.value, ast.Call):
                    # store the whole Call: the type may come from
                    # the constructor OR the callee's return
                    # annotation
                    types.setdefault(t, sub.value)
            elif isinstance(sub, ast.AnnAssign) and isinstance(
                sub.target, ast.Name
            ):
                types.setdefault(sub.target.id, sub.annotation)
        fn._locals = (aliases, self_aliases, types)
        return fn._locals

    def _type_of_local(self, fn: FuncInfo, name: str,
                       _depth: int = 0
                       ) -> Optional[Tuple[ModuleIndex, _ClassInfo]]:
        """The class a local/param resolves to: annotation,
        constructor call, self-attr alias through the class's
        attr_types, or the return annotation of the call that bound
        it (``session = self.cm.lookup(cid)`` with
        ``lookup() -> Optional[Session]``)."""
        if _depth > 3:
            return None
        mod = fn.module
        _aliases, self_aliases, types = self._fn_locals(fn)
        attr = self_aliases.get(name)
        if attr is not None and fn.cls is not None:
            ci = mod.classes.get(fn.cls)
            if ci is not None:
                ctor = ci.attr_types.get(attr)
                if ctor is not None:
                    return self._class_ref(mod, ctor)
            return None
        ann = types.get(name)
        if ann is None:
            return None
        if isinstance(ann, ast.Call):
            ref = self._class_ref(mod, ann.func)
            if ref is not None:
                return ref
            callee = self._resolve_expr(ann.func, fn, depth=_depth + 1)
            if callee is not None and getattr(
                callee.node, "returns", None
            ) is not None:
                return self._class_ref(callee.module,
                                       callee.node.returns)
            return None
        return self._class_ref(mod, ann)

    # -------------------------------------------------- call resolve

    def resolve_call(self, call: ast.Call,
                     fn: FuncInfo) -> Optional[FuncInfo]:
        return self._resolve_expr(call.func, fn, depth=0)

    def _resolve_expr(self, f: ast.expr, fn: FuncInfo,
                      depth: int) -> Optional[FuncInfo]:
        if depth > 4:
            return None
        mod = fn.module
        if isinstance(f, ast.Name):
            aliases, self_aliases, _types = self._fn_locals(fn)
            tgt = self_aliases.get(f.id)
            if tgt is not None:
                # `h = self._m; h()` resolves as the aliased method
                return self._resolve_self_attr(fn, tgt)
            tgt = aliases.get(f.id)
            if tgt is not None and tgt != f.id:
                hit = self._resolve_self_attr(fn, tgt)
                if hit is not None:
                    return hit
                return self.resolve_name(mod, tgt)
            return self.resolve_name(mod, f.id)
        if isinstance(f, ast.Attribute):
            base = f.value
            # self.m() / cls.m()
            if isinstance(base, ast.Name) and base.id in (
                "self", "cls"
            ):
                return self._resolve_self_attr(fn, f.attr)
            # self.attr.m(): typed instance attribute receiver
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id in ("self", "cls")
                    and fn.cls is not None):
                ci = mod.classes.get(fn.cls)
                if ci is not None:
                    ctor = ci.attr_types.get(base.attr)
                    if ctor is not None:
                        ref = self._class_ref(mod, ctor)
                        if ref is not None:
                            return self._method_in(ref[0], ref[1],
                                                   f.attr)
                return None
            if isinstance(base, ast.Name):
                # import alias: mod.f() / pkg-level from-import
                if base.id in mod.import_mods:
                    target = self._module_for(mod.import_mods[base.id])
                    if target is not None:
                        return self.lookup_toplevel(target, f.attr)
                    return None
                if base.id in mod.from_imports:
                    b, orig = mod.from_imports[base.id]
                    target = self._module_for(f"{b}.{orig}")
                    if target is not None:
                        return self.lookup_toplevel(target, f.attr)
                    target = self._module_for(b)
                    if target is not None:
                        # `from x import y` where y is a class
                        ci = target.classes.get(orig)
                        if ci is not None:
                            return self._method_in(target, ci, f.attr)
                    return None
                # ClassName.method(...)
                ref = self.lookup_class(mod, base.id)
                if ref is not None:
                    return self._method_in(ref[0], ref[1], f.attr)
                # typed local/param receiver: enc.slot_for() — via
                # annotation, constructor, self-attr alias, or the
                # binding call's return annotation
                ref = self._type_of_local(fn, base.id, depth + 1)
                if ref is not None:
                    return self._method_in(ref[0], ref[1], f.attr)
            return None
        return None

    def _resolve_self_attr(self, fn: FuncInfo,
                           attr: str) -> Optional[FuncInfo]:
        if fn.cls is None:
            return None
        mod = fn.module
        ci = mod.classes.get(fn.cls)
        if ci is None:
            return None
        return self._method_in(mod, ci, attr)

    # ------------------------------------------------------- edges

    def callees(self, fn: FuncInfo) -> List[Tuple[ast.Call, FuncInfo]]:
        """Resolved (call node, callee) pairs lexically in `fn`
        (nested defs pruned — they are their own FuncInfos)."""
        edges = self._edges
        if edges is None:
            edges = self._edges = {}
        hit = edges.get(fn.key)
        if hit is not None:
            return hit
        out: List[Tuple[ast.Call, FuncInfo]] = []

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)) and child is not \
                        fn.node:
                    continue
                if isinstance(child, ast.Call):
                    callee = self.resolve_call(child, fn)
                    if callee is not None and callee is not fn:
                        out.append((child, callee))
                walk(child)

        walk(fn.node)
        edges[fn.key] = out
        return out


def build_program(modules: Dict[str, ModuleIndex]) -> Program:
    return Program(modules)


__all__ = [
    "FuncInfo", "ModuleIndex", "NATIVE_ENTRY_PREFIXES", "Program",
    "build_program", "index_file", "is_native_entry", "module_dotted",
]
