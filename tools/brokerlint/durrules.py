"""Metadata-durability rules (DUR701, DUR702).

PR 15 made every DS metadata sidecar go through ONE write path —
``emqx_tpu.ds.atomicio.atomic_write_json`` (tmp + fsync +
``os.replace`` + dir fsync, CRC trailer, the ``ds.meta.write``
failpoint seam).  The failure mode it closes: a bare
``open(path, "w")`` / ``json.dump`` leaves a torn file at power fail,
and the old ``except ...: {}`` loaders silently reset replay progress
— acked QoS1 backlogs gone with no alarm.  This rule keeps the unsafe
pattern from coming back.

Scope: every module under ``emqx_tpu/ds/`` — the package that owns the
sidecars and every module reachable from the
``SEAM_FUNCS["ds.meta.write"]`` helper (the seam and all its callers
live in this package; the path scope is the static, drift-free way to
say so).

Findings:

  * ``open(<path>, "w")`` (or any write/append text mode) where the
    path expression is not visibly a ``*.tmp`` staging file — metadata
    must go through the atomic-write helper.  "Visibly tmp" is
    intentionally syntactic: a ``... + ".tmp"`` concatenation, a
    string literal / f-string ending in ``.tmp``, or a name/attribute
    whose spelling contains ``tmp``.  (The helper's own staging write
    passes this test; anything else takes a justified
    ``# brokerlint: ignore[DUR701]``.)
  * ``json.dump(obj, open(<non-tmp path>, "w"))`` — the inlined form
    of the same mistake.

Binary log writes (``"wb"`` etc.) are the storage engine's own domain
(native dslog) and are not flagged.

DUR702 (PR 16): STORE-metadata snapshots (census, LTS index) must be
written through ``ds.journal.MetaJournal.fold`` — never by a direct
``atomic_write_json`` call.  The fold owns the snapshot-then-truncate
ordering that makes a crash at any point idempotent; a stray direct
snapshot write next to a live journal breaks that algebra (the journal
would replay stale deltas over a newer snapshot, or the fold's
truncation would discard deltas the stray write never folded).  So:
any ``atomic_write_json`` call in ``emqx_tpu/ds/`` is a finding unless
it lives in ``journal.py`` (the fold itself) or in one of the audited
SESSION-checkpoint writers in ``persist.py`` (``_DUR702_ALLOWED``) —
those sidecars are whole-file by design (small, bounded by session
count, not store size) and carry no journal.  Intentional exceptions
take a justified ``# brokerlint: ignore[DUR702]``.
"""

from __future__ import annotations

import ast

from .engine import ModuleContext, dotted_name

_DS_PATH_MARKER = "emqx_tpu/ds/"

# DUR702: the one module whose writes ARE the fold path, plus the
# audited session-checkpoint writers (whole-file by design — bounded
# by session count, not store size; no journal to get out of sync
# with).  Growing persist.py?  A new sidecar either takes a journal
# (then fold writes it) or joins this list with a review.
_DUR702_FOLD_MODULE = "emqx_tpu/ds/journal.py"
_DUR702_ALLOWED = {
    "emqx_tpu/ds/persist.py": frozenset({
        "DurableSessions.__init__",        # layout marker, once
        "DurableSessions.save",            # session checkpoint
        "DurableSessions.save_state",      # session checkpoint
        "DurableSessions._save_share_members",
        "DurableSessions._flush_share_progress",
    }),
}


def _is_write_mode(call: ast.Call) -> str:
    """The text-write mode string of an ``open`` call, or ''."""
    if not (isinstance(call.func, ast.Name) and call.func.id == "open"):
        return ""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if not isinstance(mode, ast.Constant) or not isinstance(
        mode.value, str
    ):
        return ""
    m = mode.value
    if "b" in m:
        return ""  # binary: the log engine's domain, not a sidecar
    return m if ("w" in m or "a" in m or "x" in m) else ""


def _looks_tmp(node: ast.AST) -> bool:
    """Is this path expression visibly a .tmp staging file?"""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, str) and node.value.endswith(".tmp")
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _looks_tmp(node.right) or _looks_tmp(node.left)
    if isinstance(node, ast.JoinedStr):
        vals = node.values
        return bool(vals) and _looks_tmp(vals[-1])
    if isinstance(node, ast.Name):
        return "tmp" in node.id.lower()
    if isinstance(node, ast.Attribute):
        return "tmp" in node.attr.lower()
    if isinstance(node, ast.Call):
        # os.path.join(..., x): judge by the last component
        if dotted_name(node.func).endswith("join") and node.args:
            return _looks_tmp(node.args[-1])
    return False


def _qual_spans(tree: ast.Module):
    """(lineno, end_lineno, qualname) for every function, for
    enclosing-context naming."""
    spans = []

    def walk(node, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                spans.append((
                    child.lineno,
                    getattr(child, "end_lineno", child.lineno)
                    or child.lineno,
                    f"{prefix}{child.name}",
                ))
                walk(child, f"{prefix}{child.name}.")

    walk(tree, "")
    return spans


def _qualname_at(spans, line: int) -> str:
    best, best_width = "<module>", None
    for lo, hi, q in spans:
        if lo <= line <= hi and (best_width is None
                                 or hi - lo <= best_width):
            best, best_width = q, hi - lo
    return best


def _report(ctx: ModuleContext, spans, node: ast.AST,
            what: str) -> None:
    ctx.report(
        node, "DUR701",
        _qualname_at(spans, getattr(node, "lineno", 1)),
        f"{what} to a non-.tmp path inside emqx_tpu/ds/ — metadata "
        "sidecars must go through ds.atomicio.atomic_write_json "
        "(atomic replace + fsync + CRC; the ds.meta.write seam)",
        detail=what,
    )


def _check_dur702(ctx: ModuleContext, spans, node: ast.Call,
                  path: str) -> None:
    """Direct snapshot writes outside the fold path (DUR702)."""
    if not dotted_name(node.func).endswith("atomic_write_json"):
        return
    if path.endswith(_DUR702_FOLD_MODULE):
        return  # the fold itself
    allowed = next(
        (q for sfx, q in _DUR702_ALLOWED.items() if path.endswith(sfx)),
        frozenset(),
    )
    qual = _qualname_at(spans, getattr(node, "lineno", 1))
    if qual in allowed:
        return
    ctx.report(
        node, "DUR702", qual,
        "store-metadata snapshot written directly — snapshots in "
        "emqx_tpu/ds/ must go through MetaJournal.fold (snapshot-"
        "then-truncate keeps journal replay idempotent); session "
        "checkpoints belong on the durrules._DUR702_ALLOWED audit "
        "list",
        detail="atomic_write_json",
    )


def check(ctx: ModuleContext) -> None:
    path = ctx.path.replace("\\", "/")
    if _DS_PATH_MARKER not in path:
        return
    spans = _qual_spans(ctx.tree)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        _check_dur702(ctx, spans, node, path)
        mode = _is_write_mode(node)
        if mode and node.args and not _looks_tmp(node.args[0]):
            _report(ctx, spans, node, f'open(..., "{mode}")')
            continue
        if dotted_name(node.func).endswith("json.dump"):
            # only the inlined open(...) form is judged here — a
            # file-object variable was already judged at its open()
            if len(node.args) >= 2 and isinstance(
                node.args[1], ast.Call
            ):
                inner = node.args[1]
                if _is_write_mode(inner) and inner.args and \
                        not _looks_tmp(inner.args[0]):
                    _report(ctx, spans, node, "json.dump")
