"""Lock-discipline rules (LOCK4xx) — interprocedural.

The multicore scale-out (N broker workers × one match service over a
shared-memory ring) hangs on lock discipline that today lives in
comments: which locks nest in which order, which may be held at a
GIL-released native boundary, and which are shared between the event
loop and worker threads.  These rules build the program-wide
lock-acquisition graph (lock identities normalized across modules by
`dataflow.lock_token`) and enforce the discipline statically:

  LOCK401  potential lock-order inversion: the acquisition graph has
           A→B (B acquired — directly or through any resolved callee
           — while A is held) and a path B⇝A somewhere else in the
           program.  Two threads taking the two paths deadlock.
           Reported at every edge on the cycle.
  LOCK402  lock held across a suspension boundary the intra-function
           ASYNC103 cannot see: an await that (transitively, through
           resolved async callees) performs IO, a sync ``with`` lock
           wrapping an IO await, or any call that (transitively)
           enters a GIL-released native entry point — one slow peer
           or one long native splice stalls every other holder.
           When the serialization IS the design (e.g. a lock that
           exists precisely because the native call drops the GIL),
           suppress with a justification saying so.
  LOCK403  one lock acquired both inside ``async def`` (event-loop
           context) and inside sync ``def`` (thread context) without
           a documented owner: a threading lock taken on the loop
           stalls the loop for as long as any thread holds it.
           Document with a ``# lock-ownership: <rule>`` comment on
           the loop-side acquisition (or restructure).

ASYNC103 stays the fast intra-function rule; LOCK402 only reports
what it cannot see (≥2-level IO resolution, sync-with shapes, native
boundaries), so the two never double-report one site.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import callgraph, dataflow
from .engine import ModuleContext, awaits_io, call_tail

# a site: (path, qualname, line)
_Site = Tuple[str, str, int]

_OWNERSHIP_TOKEN = "lock-ownership:"


class _LockGraph:
    def __init__(self) -> None:
        # (a, b) -> sites where b was acquired while a held
        self.edges: Dict[Tuple[str, str], List[_Site]] = {}

    def add(self, a: str, b: str, site: _Site) -> None:
        if a == b:
            return
        sites = self.edges.setdefault((a, b), [])
        if site not in sites:
            sites.append(site)

    def succ(self) -> Dict[str, Set[str]]:
        out: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            out.setdefault(a, set()).add(b)
        return out

    def cycle_edges(self) -> List[Tuple[str, str]]:
        succ = self.succ()
        out = []
        for (a, b) in sorted(self.edges):
            # inversion iff a is reachable back from b
            seen: Set[str] = set()
            stack = [b]
            hit = False
            while stack:
                n = stack.pop()
                if n == a:
                    hit = True
                    break
                if n in seen:
                    continue
                seen.add(n)
                stack.extend(succ.get(n, ()))
            if hit:
                out.append((a, b))
        return out


def _has_ownership_comment(ctx: ModuleContext, line: int) -> bool:
    """``# lock-ownership: ...`` on the acquisition line or anywhere
    in the contiguous comment block directly above it."""
    if 1 <= line <= len(ctx.lines) and \
            _OWNERSHIP_TOKEN in ctx.lines[line - 1]:
        return True
    cand = line - 1
    while 1 <= cand <= len(ctx.lines) and \
            ctx.lines[cand - 1].lstrip().startswith("#"):
        if _OWNERSHIP_TOKEN in ctx.lines[cand - 1]:
            return True
        cand -= 1
    return False


class _FnLockWalk:
    """One function's held-lock walk: collects order edges (direct
    nesting AND held-across-call via callee ``acquires`` summaries),
    dual-context acquisitions, and LOCK402 findings."""

    def __init__(self, fn: callgraph.FuncInfo,
                 program: callgraph.Program, summaries: Dict,
                 ctx: ModuleContext, graph: _LockGraph,
                 acq_ctx: Dict[str, Dict[str, List[_Site]]]) -> None:
        self.fn = fn
        self.program = program
        self.summaries = summaries
        self.ctx = ctx
        self.graph = graph
        self.acq_ctx = acq_ctx
        self._callees = {
            id(call): callee for call, callee in program.callees(fn)
        }

    def run(self) -> None:
        for child in ast.iter_child_nodes(self.fn.node):
            self._process(child, [])

    # held: list of (token, is_sync_with) innermost-last
    def _process(self, node: ast.AST,
                 held: List[Tuple[str, bool]]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # a nested def does not run under the lock
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new: List[Tuple[str, bool]] = []
            is_sync = isinstance(node, ast.With)
            for item in node.items:
                tok = dataflow.lock_token(item.context_expr, self.fn,
                                              self.program)
                if tok is None:
                    continue
                site = (self.fn.module.path, self.fn.qualname,
                        node.lineno)
                for h, _s in held + new:
                    self.graph.add(h, tok, site)
                kind = "async" if self.fn.is_async else "sync"
                self.acq_ctx.setdefault(tok, {}).setdefault(
                    kind, []
                ).append(site)
                new.append((tok, is_sync))
            inner = held + new
            for stmt in node.body:
                self._process(stmt, inner)
            return
        if held:
            if isinstance(node, ast.Await):
                self._check_await(node, held)
            elif isinstance(node, ast.Call):
                self._check_call(node, held)
        for child in ast.iter_child_nodes(node):
            self._process(child, held)

    def _check_await(self, node: ast.Await, held) -> None:
        direct = awaits_io(node.value, self.ctx.io_methods)
        held_sync = [h for h, s in held if s]
        if direct is not None:
            # ASYNC103 sees async-with holders; a SYNC `with` lock
            # wrapping an IO await is invisible to it — ours
            if held_sync:
                self.ctx.report(
                    node, "LOCK402", self.fn.qualname,
                    f"sync `with` lock `{held_sync[-1]}` held across "
                    f"IO await (`{direct}`): the loop parks here "
                    f"with the lock taken and every thread contender "
                    f"blocks — narrow the critical section",
                    detail=f"sync-with:{direct}",
                )
            return
        # transitive: the awaited call resolves to an async callee
        # whose summary (≥1 level deeper than ASYNC103's one-level
        # map) performs IO
        for sub in ast.walk(node.value):
            if not isinstance(sub, ast.Call):
                continue
            callee = self._callees.get(id(sub))
            if callee is None:
                continue
            cs = self.summaries.get(callee.key)
            if cs is None or cs.awaits_io is None:
                continue
            io_name, via = cs.awaits_io
            chain = f"{callee.name} -> {via}" if via else callee.name
            self.ctx.report(
                node, "LOCK402", self.fn.qualname,
                f"lock `{held[-1][0]}` held across await of "
                f"`{callee.name}()` which (transitively via "
                f"`{chain}`) performs IO (`{io_name}`): one slow "
                f"peer stalls every other holder",
                detail=f"await:{callee.name}:{io_name}",
            )
            return

    def _check_call(self, node: ast.Call, held) -> None:
        tail = call_tail(node)
        native: Optional[str] = None
        chain = ""
        if callgraph.is_native_entry(tail):
            native = tail
        else:
            callee = self._callees.get(id(node))
            if callee is not None:
                cs = self.summaries.get(callee.key)
                if cs is not None and cs.native is not None:
                    native = cs.native
                    chain = callee.name
        if native is not None:
            via = f" (via `{chain}`)" if chain else ""
            self.ctx.report(
                node, "LOCK402", self.fn.qualname,
                f"lock `{held[-1][0]}` held across GIL-released "
                f"native call `{native}`{via}: the holder drops the "
                f"GIL with the lock taken, so every contender stalls "
                f"for the whole native span (suppress with a "
                f"justification when the lock exists to serialize "
                f"the native structure itself)",
                detail=f"native:{native}",
            )
            return
        # held-across-call acquisition edges (H→T for every T the
        # callee transitively acquires)
        callee = self._callees.get(id(node))
        if callee is None:
            return
        cs = self.summaries.get(callee.key)
        if cs is None or not cs.acquires:
            return
        site = (self.fn.module.path, self.fn.qualname, node.lineno)
        for h, _s in held:
            for t in cs.acquires:
                self.graph.add(h, t, site)


def check_program(
    program: callgraph.Program,
    summaries: Dict,
    ctxs: Dict[str, ModuleContext],
) -> None:
    graph = _LockGraph()
    acq_ctx: Dict[str, Dict[str, List[_Site]]] = {}
    # 1. per-function walks: LOCK402 findings + graph/context data
    for fn in program.functions():
        ctx = ctxs.get(fn.module.path)
        if ctx is None:
            continue
        s = summaries.get(fn.key)
        if s is None or not s.has_lock_ctx:
            continue  # no token-resolved lock in the body: no walk
        _FnLockWalk(fn, program, summaries, ctx, graph, acq_ctx).run()
    # 2. LOCK401: lock-order inversions
    for (a, b) in graph.cycle_edges():
        for (path, qual, line) in graph.edges[(a, b)]:
            ctx = ctxs.get(path)
            if ctx is None:
                continue
            ctx.report_at(
                line, "LOCK401", qual,
                f"potential lock-order inversion: `{b}` acquired "
                f"while `{a}` is held, but elsewhere the program "
                f"acquires them in the opposite order — two threads "
                f"taking the two paths deadlock; pick ONE order and "
                f"enforce it",
                detail=f"{a}->{b}",
            )
    # 3. LOCK403: dual-context locks without documented ownership
    for tok, kinds in sorted(acq_ctx.items()):
        if "async" not in kinds or "sync" not in kinds:
            continue
        for (path, qual, line) in kinds["async"]:
            ctx = ctxs.get(path)
            if ctx is None or _has_ownership_comment(ctx, line):
                continue
            ctx.report_at(
                line, "LOCK403", qual,
                f"lock `{tok}` is acquired both here (event-loop "
                f"context) and in sync/thread context elsewhere: a "
                f"thread holding it stalls the loop — document the "
                f"ownership rule with a `# lock-ownership: ...` "
                f"comment or restructure",
                detail=tok,
            )


__all__ = ["check_program"]
