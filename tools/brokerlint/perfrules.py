"""Dispatch-perf rules (PERF401, PERF402).

PR 3 made fan-out single-encode: each unique PUBLISH body is
serialized once per dispatch window and only the packet id is patched
per subscriber (`codec.mqtt.DispatchEncoder`).  PERF401 enforces that
invariant the same way FP301 enforces failpoint seams:
``DISPATCH_FUNCS`` declares the dispatch-marked hot-loop functions,
and any ``serialize(``/``encode(`` call nested inside a loop in one
of them fires PERF401 — a per-subscriber re-encode sneaking back into
the fan-out path fails tier-1 instead of silently re-paying the cost
the window encoder removed.

PERF402 guards the other per-delivery cost PR 5 amortized: a clock
read (``time.time()``/``perf_counter()``/``datetime.now()``-shaped
call) inside a dispatch-marked loop.  The delivery runs take ONE
clock read per run (`Session.deliver`'s hoisted ``now``,
`deliver_run_native`'s bulk `Inflight.insert_run`); a per-iteration
clock sneaking back in is a finding.

An intentional in-loop call takes a justified inline
``# brokerlint: ignore[PERF401]`` / ``ignore[PERF402]``.  A declared
function that no longer exists is itself a finding, so the
declaration list cannot silently rot.
"""

from __future__ import annotations

import ast
from typing import List, NamedTuple, Sequence

from .engine import ModuleContext, call_tail


class DispatchFn(NamedTuple):
    path_suffix: str   # module path suffix, posix ('broker/broker.py')
    qualname: str      # dotted function name inside the module


# the window fan-out hot loops: expansion/grouping, per-client
# delivery, the session's packet builder, and the native-run fast
# path (decision scan + block bookkeeping)
DISPATCH_FUNCS = (
    DispatchFn("emqx_tpu/broker/broker.py", "Broker._dispatch_window"),
    DispatchFn("emqx_tpu/broker/broker.py", "Broker._deliver_run"),
    DispatchFn("emqx_tpu/broker/session.py", "Session.deliver"),
    DispatchFn("emqx_tpu/broker/session.py", "Session.deliver_run_native"),
    DispatchFn("emqx_tpu/broker/session.py", "Session.alloc_packet_ids"),
)

# callee tails that mean "re-encode a wire frame"
_ENCODE_TAILS = {"serialize", "encode", "encode_publish"}

# callee tails that mean "read a clock" (time module, datetime
# classmethods, monotonic/perf counters) — once per run, not per
# delivery (PERF402)
_CLOCK_TAILS = {
    "time", "time_ns", "monotonic", "monotonic_ns",
    "perf_counter", "perf_counter_ns", "now", "utcnow", "today",
}


def _function_map(tree: ast.Module):
    """qualname -> FunctionDef/AsyncFunctionDef for the whole module."""
    out = {}

    def walk(node, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                out[f"{prefix}{child.name}"] = child
                walk(child, f"{prefix}{child.name}.")

    walk(tree, "")
    return out


def _loop_calls(fn: ast.AST, tails) -> List[ast.Call]:
    """Calls with a callee tail in ``tails`` lexically inside a
    for/while loop of `fn` (nested def/lambda subtrees are pruned: a
    closure DEFINED in the loop is not a per-subscriber call)."""
    hits: List[ast.Call] = []

    def walk(node: ast.AST, in_loop: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)) and child is not fn:
                continue
            child_in_loop = in_loop or isinstance(
                child, (ast.For, ast.AsyncFor, ast.While)
            )
            if (
                in_loop
                and isinstance(child, ast.Call)
                and call_tail(child) in tails
            ):
                hits.append(child)
            walk(child, child_in_loop)

    walk(fn, False)
    return hits


def check(ctx: ModuleContext,
          dispatch: Sequence[DispatchFn] = DISPATCH_FUNCS) -> None:
    relevant = [d for d in dispatch if ctx.path.endswith(d.path_suffix)]
    if not relevant:
        return
    fns = _function_map(ctx.tree)
    for d in relevant:
        fn = fns.get(d.qualname)
        if fn is None:
            ctx.report(
                ctx.tree, "PERF401", d.qualname,
                f"declared dispatch function `{d.qualname}` not found "
                f"in {ctx.path} — update "
                f"tools/brokerlint/perfrules.py:DISPATCH_FUNCS",
                detail="missing",
            )
            continue
        for call in _loop_calls(fn, _ENCODE_TAILS):
            ctx.report(
                call, "PERF401", d.qualname,
                f"per-subscriber `{call_tail(call)}(` inside the "
                f"dispatch hot loop `{d.qualname}` — encode once per "
                f"window via codec.mqtt.DispatchEncoder instead",
                detail=call_tail(call),
            )
        for call in _loop_calls(fn, _CLOCK_TAILS):
            ctx.report(
                call, "PERF402", d.qualname,
                f"per-delivery clock read `{call_tail(call)}(` inside "
                f"the dispatch hot loop `{d.qualname}` — read the "
                f"clock once per run (hoist it above the loop)",
                detail=call_tail(call),
            )


__all__ = ["check", "DispatchFn", "DISPATCH_FUNCS"]
