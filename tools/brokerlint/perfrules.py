"""Dispatch-perf rules (PERF401, PERF402, PERF403).

PR 3 made fan-out single-encode: each unique PUBLISH body is
serialized once per dispatch window and only the packet id is patched
per subscriber (`codec.mqtt.DispatchEncoder`).  PERF401 enforces that
invariant the same way FP301 enforces failpoint seams:
``DISPATCH_FUNCS`` declares the dispatch-marked hot-loop functions,
and any ``serialize(``/``encode(`` call nested inside a loop in one
of them fires PERF401 — a per-subscriber re-encode sneaking back into
the fan-out path fails tier-1 instead of silently re-paying the cost
the window encoder removed.

PERF402 guards the other per-delivery cost PR 5 amortized: a clock
read (``time.time()``/``perf_counter()``/``datetime.now()``-shaped
call) inside a dispatch-marked loop.  The delivery runs take ONE
clock read per run (`Session.deliver`'s hoisted ``now``,
`deliver_run_native`'s bulk `Inflight.insert_run`); a per-iteration
clock sneaking back in is a finding.

PERF403 guards what PR 9's decision columns amortized: a SubOpts
field read (``opts.qos``, ``opts.no_local``, ``opts.
retain_as_published``, ``opts.subid``, ...) inside a dispatch-marked
loop.  The window computes every per-delivery decision as ONE
vectorized pass over the router's attribute columns
(`Router.opts_columns` + `ops.match_kernel.decide_batch[_host]`); a
per-delivery Python attribute read sneaking back into the hot loops
re-pays the cost the columns removed.  The scalar referee paths
(`Session.deliver`, `deliver_run_native`, the detached-queue branch)
keep their reads under justified inline ignores — they ARE the
reference semantics the columns are property-tested against.

An intentional in-loop site takes a justified inline
``# brokerlint: ignore[PERF401]`` / ``[PERF402]`` / ``[PERF403]``.
A declared function that no longer exists is itself a finding, so
the declaration list cannot silently rot.
"""

from __future__ import annotations

import ast
from typing import List, NamedTuple, Sequence

from .engine import ModuleContext, call_tail, dotted_name


class DispatchFn(NamedTuple):
    path_suffix: str   # module path suffix, posix ('broker/broker.py')
    qualname: str      # dotted function name inside the module


# the window fan-out hot loops: expansion/grouping, per-client
# delivery (columns + scalar), the session's packet builder, the
# native-run fast path (decision scan + block bookkeeping), and the
# durable-replay hot path (scheduler round + window build + the
# scalar resume referee) — a mass reconnect drives these exactly as
# hard as live fan-out drives the rest
DISPATCH_FUNCS = (
    DispatchFn("emqx_tpu/broker/broker.py", "Broker._dispatch_window"),
    DispatchFn("emqx_tpu/broker/broker.py", "Broker._dispatch_columns"),
    DispatchFn("emqx_tpu/broker/broker.py", "Broker._dispatch_scalar"),
    DispatchFn("emqx_tpu/broker/broker.py", "Broker._deliver_run"),
    # rule-engine hot path (the rules x window matrix): one column
    # extraction + one matrix eval per window, actions per PASSING
    # (rule, message) only — no per-candidate encode/clock/SubOpts
    # work may creep back in
    DispatchFn("emqx_tpu/rules/engine.py", "RuleEngine.apply_batch"),
    DispatchFn("emqx_tpu/rules/columns.py", "WindowColumns.__init__"),
    # windowed egress (PR 20): batched SELECT materialization and the
    # sink flush loop move per-ROW work to per-WINDOW — keep it there
    DispatchFn("emqx_tpu/rules/select.py", "materialize_rows"),
    DispatchFn("emqx_tpu/rules/engine.py",
               "RuleEngine._run_rule_batched"),
    DispatchFn("emqx_tpu/resources.py", "BufferWorker._flush_once"),
    DispatchFn("emqx_tpu/engine.py", "MatchEngine.rules_eval_window"),
    DispatchFn("emqx_tpu/broker/broker.py", "Broker._resume_enqueue"),
    DispatchFn("emqx_tpu/broker/session.py", "Session.deliver"),
    DispatchFn("emqx_tpu/broker/session.py", "Session.deliver_run_native"),
    DispatchFn("emqx_tpu/broker/session.py", "Session.alloc_packet_ids"),
    DispatchFn("emqx_tpu/broker/resume.py", "ResumeScheduler.drain_once"),
    DispatchFn("emqx_tpu/broker/resume.py",
               "ResumeScheduler._drain_window"),
    DispatchFn("emqx_tpu/broker/resume.py",
               "ResumeScheduler._append_run"),
    # cluster forward reliability hot path (PR 11): one encode + one
    # clock read per peer frame, span work gated on the sampled copy
    DispatchFn("emqx_tpu/cluster/node.py",
               "ClusterNode._flush_forwards"),
    DispatchFn("emqx_tpu/cluster/node.py",
               "ClusterNode._handle_forward_batch"),
    DispatchFn("emqx_tpu/cluster/node.py",
               "ClusterNode._handle_fwd_ack"),
    DispatchFn("emqx_tpu/cluster/quic_transport.py",
               "_send_datagrams"),
    # overload ladder (olp): the level machine and the shed
    # accounting both sit inside dispatch/tick paths — no per-unit
    # clock reads, encodes, or unguarded trace work may creep in
    # (the shed MASK itself is policed via _dispatch_columns above)
    DispatchFn("emqx_tpu/olp.py", "LoadMonitor.observe"),
    DispatchFn("emqx_tpu/olp.py", "LoadMonitor.shed"),
)

# callee tails that mean "re-encode a wire frame"
_ENCODE_TAILS = {"serialize", "encode", "encode_publish"}

# callee tails that mean "read a clock" (time module, datetime
# classmethods, monotonic/perf counters) — once per run, not per
# delivery (PERF402)
_CLOCK_TAILS = {
    "time", "time_ns", "monotonic", "monotonic_ns",
    "perf_counter", "perf_counter_ns", "now", "utcnow", "today",
}

# SubOpts fields the window decision columns replace: reading one of
# these per delivery inside a dispatch loop is PERF403.  The receiver
# must LOOK like a SubOpts binding (its dotted tail contains "opts"),
# so `msg.qos` and `packet.qos` stay clean.
_SUBOPT_FIELDS = {
    "qos", "no_local", "retain_as_published", "retain_handling",
    "subid", "share_group",
}


def _function_map(tree: ast.Module):
    """qualname -> FunctionDef/AsyncFunctionDef for the whole module."""
    out = {}

    def walk(node, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                out[f"{prefix}{child.name}"] = child
                walk(child, f"{prefix}{child.name}.")

    walk(tree, "")
    return out


def _loop_calls(fn: ast.AST, tails) -> List[ast.Call]:
    """Calls with a callee tail in ``tails`` lexically inside a
    for/while loop of `fn` (nested def/lambda subtrees are pruned: a
    closure DEFINED in the loop is not a per-subscriber call)."""
    hits: List[ast.Call] = []

    def walk(node: ast.AST, in_loop: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)) and child is not fn:
                continue
            child_in_loop = in_loop or isinstance(
                child, (ast.For, ast.AsyncFor, ast.While)
            )
            if (
                in_loop
                and isinstance(child, ast.Call)
                and call_tail(child) in tails
            ):
                hits.append(child)
            walk(child, child_in_loop)

    walk(fn, False)
    return hits


def _loop_opts_reads(fn: ast.AST) -> List[ast.Attribute]:
    """SubOpts field reads (`opts.qos`-shaped Attribute nodes whose
    receiver's dotted tail names an opts binding) executed PER
    ITERATION of a for/while loop in `fn`.  A ``for`` statement's
    target/iterable evaluate once per loop, so they inherit the
    enclosing context; a ``while`` test runs every iteration, so it
    counts as loop body.  Nested def/lambda subtrees pruned as in
    `_loop_calls`."""
    hits: List[ast.Attribute] = []

    def visit(node: ast.AST, in_loop: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            return
        if (
            in_loop
            and isinstance(node, ast.Attribute)
            and node.attr in _SUBOPT_FIELDS
        ):
            base = dotted_name(node.value)
            if base and "opts" in base.split(".")[-1]:
                hits.append(node)
        if isinstance(node, (ast.For, ast.AsyncFor)):
            visit(node.target, in_loop)
            visit(node.iter, in_loop)
            for sub in node.body:
                visit(sub, True)
            for sub in node.orelse:  # else-suite: once per loop
                visit(sub, in_loop)
            return
        if isinstance(node, ast.While):
            visit(node.test, True)  # re-evaluated every iteration
            for sub in node.body:
                visit(sub, True)
            for sub in node.orelse:
                visit(sub, in_loop)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, in_loop)

    visit(fn, False)
    return hits


def check(ctx: ModuleContext,
          dispatch: Sequence[DispatchFn] = DISPATCH_FUNCS) -> None:
    relevant = [d for d in dispatch if ctx.path.endswith(d.path_suffix)]
    if not relevant:
        return
    fns = _function_map(ctx.tree)
    for d in relevant:
        fn = fns.get(d.qualname)
        if fn is None:
            ctx.report(
                ctx.tree, "PERF401", d.qualname,
                f"declared dispatch function `{d.qualname}` not found "
                f"in {ctx.path} — update "
                f"tools/brokerlint/perfrules.py:DISPATCH_FUNCS",
                detail="missing",
            )
            continue
        for call in _loop_calls(fn, _ENCODE_TAILS):
            ctx.report(
                call, "PERF401", d.qualname,
                f"per-subscriber `{call_tail(call)}(` inside the "
                f"dispatch hot loop `{d.qualname}` — encode once per "
                f"window via codec.mqtt.DispatchEncoder instead",
                detail=call_tail(call),
            )
        for call in _loop_calls(fn, _CLOCK_TAILS):
            ctx.report(
                call, "PERF402", d.qualname,
                f"per-delivery clock read `{call_tail(call)}(` inside "
                f"the dispatch hot loop `{d.qualname}` — read the "
                f"clock once per run (hoist it above the loop)",
                detail=call_tail(call),
            )
        for attr in _loop_opts_reads(fn):
            ctx.report(
                attr, "PERF403", d.qualname,
                f"per-delivery SubOpts read `.{attr.attr}` inside the "
                f"dispatch hot loop `{d.qualname}` — consume the "
                f"window decision columns (Router.opts_columns + "
                f"decide_batch) instead of per-delivery attribute "
                f"reads",
                detail=attr.attr,
            )


__all__ = ["check", "DispatchFn", "DISPATCH_FUNCS"]
