"""Native buffer-lifetime rules (NATIVE5xx) — interprocedural.

PR 5's native dispatch fast path hangs correctness on buffer-lifetime
conventions no test can fully cover: the ``DispatchEncoder`` keeps
cached ctypes pointers (``native_views``/``span_arrays``) into a
growable ``arena`` bytearray, and the GIL-released ``da_assemble_run``
call dereferences them with no Python object keeping anything alive.
These rules make the conventions machine-checked:

  NATIVE501  use-after-invalidation: a local bound to cached
             ``native_views()``/``span_arrays()`` pointers is still
             live when a call that can (transitively) grow or clear
             the encoder arena runs — ``slot_for`` appends to
             ``self.arena``, a bytearray resize moves the buffer, and
             the cached pointer now dangles into freed memory.  Take
             the views AFTER the last slot miss (the shape
             ``Session.deliver_run_native`` uses).
  NATIVE502  unstable buffer at a ctypes boundary:
               * ``X.ctypes.data`` — a raw address with no owning
                 reference; if ``X`` is a temporary the pointer
                 dangles immediately (use ``data_as`` on a bound
                 array);
               * ``<call>.ctypes.data_as(...)`` — pointer taken from
                 an unnamed temporary array; bind the array to a
                 local that outlives the native call;
               * ``from_buffer(<call>)`` — pinning a temporary
                 buffer that dies with the expression;
               * ``from_buffer(self.arena)``-style exports of a
                 RESIZABLE buffer — legal only under the
                 release-before-growth discipline; the site must
                 carry a justified inline ignore documenting it.

Both families run on the whole-program pass: the invalidation summary
(`FnSummary.invalidates`) propagates through the resolved call graph,
so ``enc.slot_for`` two helpers deep still invalidates the caller's
cached views.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from . import callgraph, dataflow
from .engine import ModuleContext, call_tail

# calls that hand out cached ctypes pointers into the arena
_VIEW_TAILS = {"native_views", "span_arrays"}


def _check_fn(
    ctx: ModuleContext,
    fn: callgraph.FuncInfo,
    program: callgraph.Program,
    summaries: Dict,
) -> None:
    """ONE pruned walk per function: NATIVE502 shapes inline, plus
    the per-function facts NATIVE501 needs (view binds, direct
    invalidation sites, last-use lines)."""
    qual = fn.qualname
    binds: List[Tuple[str, int]] = []      # views local -> bind line
    inv_sites: List[Tuple[int, str]] = []  # direct arena mutations
    loads: Dict[str, List[int]] = {}       # name -> Load lines
    stores: Dict[str, List[int]] = {}      # name -> Store lines
    for node in dataflow.walk_pruned(fn.node):
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                loads.setdefault(node.id, []).append(node.lineno)
            elif isinstance(node.ctx, ast.Store):
                stores.setdefault(node.id, []).append(node.lineno)
            continue
        if dataflow.stmt_invalidates_arena(node):
            inv_sites.append((node.lineno, "arena"))
        if isinstance(node, ast.Assign):
            targets: List[ast.Name] = []
            if len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                targets = [node.targets[0]]
            elif len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Tuple
            ):
                targets = [e for e in node.targets[0].elts
                           if isinstance(e, ast.Name)]
            if targets and any(
                isinstance(sub, ast.Call)
                and call_tail(sub) in _VIEW_TAILS
                for sub in ast.walk(node.value)
            ):
                binds.extend((t.id, node.lineno) for t in targets)
            continue
        if isinstance(node, ast.Attribute) and node.attr == "data" \
                and isinstance(node.value, ast.Attribute) \
                and node.value.attr == "ctypes":
            ctx.report(
                node, "NATIVE502", qual,
                "`.ctypes.data` yields a raw address with no owning "
                "reference — a GIL-released callee can observe freed "
                "memory; use `.ctypes.data_as(...)` on an array bound "
                "to a local that outlives the call",
                detail="ctypes.data",
            )
        if not isinstance(node, ast.Call):
            continue
        tail = call_tail(node)
        if tail == "data_as" and isinstance(node.func, ast.Attribute):
            recv = node.func.value
            if isinstance(recv, ast.Attribute) and \
                    recv.attr == "ctypes" and isinstance(
                        recv.value, ast.Call):
                ctx.report(
                    node, "NATIVE502", qual,
                    "pointer taken from an unnamed temporary array "
                    "(`<call>.ctypes.data_as`): nothing keeps the "
                    "array alive across the native call — bind it to "
                    "a local first",
                    detail="temp-data_as",
                )
        elif tail == "from_buffer" and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Call):
                ctx.report(
                    node, "NATIVE502", qual,
                    "`from_buffer` pins a TEMPORARY buffer that dies "
                    "with this expression — the native callee "
                    "dereferences freed memory; bind the buffer to a "
                    "local that outlives the call",
                    detail="temp-from_buffer",
                )
            elif (isinstance(arg, ast.Attribute)
                  and arg.attr == "arena") or (
                      isinstance(arg, ast.Name) and arg.id == "arena"):
                ctx.report(
                    node, "NATIVE502", qual,
                    "`from_buffer` export of a RESIZABLE arena "
                    "buffer: any growth while the export lives moves "
                    "the bytes under the pointer — only legal under "
                    "the release-before-growth discipline (suppress "
                    "with a justification naming it)",
                    detail="resizable-from_buffer",
                )
    if not binds:
        return
    # NATIVE501: add call-edge invalidations, then window-check each
    # views local between its bind and last use
    for call, callee in program.callees(fn):
        cs = summaries.get(callee.key)
        if cs is not None and cs.invalidates is not None:
            inv_sites.append((call.lineno, callee.name))
    if not inv_sites:
        return
    for name, bind_line in binds:
        # this bind's live window ends at the next Store of the same
        # name: re-taking the views after the last slot miss (the
        # remediation the message recommends) starts a NEW window
        next_store = min(
            (s for s in stores.get(name, ()) if s > bind_line),
            default=None,
        )
        last = max(
            (l for l in loads.get(name, ())
             if l > bind_line
             and (next_store is None or l <= next_store)),
            default=0,
        )
        if last <= bind_line:
            continue
        for line, what in inv_sites:
            if bind_line < line <= last:
                ctx.report_at(
                    line, "NATIVE501", fn.qualname,
                    f"cached native views `{name}` (bound line "
                    f"{bind_line}) are still live here, but "
                    f"`{what}` can grow/clear the encoder arena — "
                    f"the ctypes pointers dangle after a resize; "
                    f"re-take the views after the last slot miss",
                    detail=f"{name}:{what}",
                )


def check_program(
    program: callgraph.Program,
    summaries: Dict,
    ctxs: Dict[str, ModuleContext],
) -> None:
    for fn in program.functions():
        ctx = ctxs.get(fn.module.path)
        if ctx is None:
            continue
        _check_fn(ctx, fn, program, summaries)


__all__ = ["check_program"]
