"""Async-atomicity race rules (RACE8xx) + metrics contract (MET901).

EMQX gets its concurrency safety from the BEAM: every broker/router
singleton is a gen_server whose state only ONE process mutates.  Our
port shares mutable singleton state across asyncio tasks, worker
threads (SyncGate flusher, executors, rebuild threads) and
GIL-released native calls; most of it is guarded by nothing except
event-loop atomicity — which silently stops holding the moment
someone adds an ``await`` (or a ``da_``/``dslog_`` call) in the
middle of a read-modify-write.  These rules make that invariant
machine-checked over the ``SHARED_CLASSES`` roster (the long-lived
singletons whose attributes are multi-context state):

  RACE801  check-then-act / read-modify-write on a shared attribute
           spanning a SUSPENSION (an await that can genuinely yield
           the loop — resolved transitively through the ``suspends``
           summaries — or a GIL-released native boundary when the
           attr is also thread-written).  Canonical hit:
           ``if x in self._pending: … await … self._pending.pop(x)``.
           A re-read of the attribute after the suspension (the
           re-check remediation) closes the window.
  RACE802  iteration over a shared dict/list/set while the loop body
           can suspend (another task mutates mid-iteration) or calls
           a known mutator of that same attribute (RuntimeError:
           dict changed size — the in-production shape).  Iterate a
           snapshot (``list(self.x)``) or restructure.
  RACE803  thread<->loop crossing: an attribute mutated from worker-
           thread context (functions reachable from Thread targets /
           ``to_thread`` / ``run_in_executor`` / executor ``submit``)
           and read on the event loop, with no lock around the
           mutation, no ``call_soon_threadsafe`` hand-off, and no
           ``# loop-ownership:`` comment (the annotation contract
           mirrors LOCK403's ``# lock-ownership:``).
  RACE804  non-idempotent multi-field update torn across a
           suspension: two attributes the class elsewhere updates
           ATOMICALLY (the relatedness evidence) updated here with a
           suspension between them — a task scheduled in the window
           observes one advanced without the other (cursor without
           watermark).

  MET901   metrics contract: a literal counter name at a
           ``*.metrics.inc(...)`` site must exist in the metrics
           registry (``METRICS``) or match a declared
           ``EXTRA_METRIC_PREFIXES`` family — a typo'd name silently
           lands in the ``_extra`` dict and no dashboard ever sees
           it.  Dynamic names (f-strings, variables) are skipped:
           under-approximate, never guess.

Shared-state model: an attribute of a roster class is *shared* when
it is written from >= 2 distinct methods (two task contexts can hold
the pen) or from >= 1 thread-context function (the LOCK403 dual-
context detection, generalized from locks to state).  Everything
here under-approximates: unresolved calls are not suspension or
mutation evidence, and a site under a token-resolved lock is treated
as protected (lock *discipline* is LOCK4xx's job).

The runtime counterpart is ``emqx_tpu/testing/interleave.py`` +
``tools/racesim``: a seeded scheduler shim that forces adversarial
task switches at exactly the suspension points these rules reason
about, so every burned-down finding carries a reproduced-failure (or
proven-fixed) schedule.
"""

from __future__ import annotations

import ast
from typing import (
    Dict, Iterable, List, NamedTuple, Optional, Sequence, Set, Tuple,
)

from . import callgraph, dataflow
from .engine import ModuleContext, call_tail, dotted_name

Key = Tuple[str, str]

_LOOP_OWNERSHIP_TOKEN = "loop-ownership:"


class SharedClass(NamedTuple):
    path_suffix: str   # module path suffix, posix
    name: str          # class name inside that module


# The long-lived singletons whose self-attributes are multi-context
# shared state (one instance, touched by many tasks/threads for the
# broker's whole life).  Per-connection/per-session objects do NOT
# belong here: a channel's state is owned by its one reader task, and
# rostering it would drown the signal.  tests/test_lint.py
# cross-checks every entry against the real tree (rot guard).
SHARED_CLASSES: Tuple[SharedClass, ...] = (
    SharedClass("emqx_tpu/broker/broker.py", "Broker"),
    SharedClass("emqx_tpu/router.py", "Router"),
    SharedClass("emqx_tpu/cluster/node.py", "ClusterNode"),
    SharedClass("emqx_tpu/ds/persist.py", "DurableSessions"),
    SharedClass("emqx_tpu/ds/sharded.py", "ShardedStorage"),
    SharedClass("emqx_tpu/broker/resume.py", "ResumeScheduler"),
    SharedClass("emqx_tpu/ds/durability.py", "SyncGate"),
    SharedClass("emqx_tpu/ds/durability.py", "GateGroup"),
    SharedClass("emqx_tpu/olp.py", "LoadMonitor"),
    # multicore worker<->service handoff state: the shm ring's free
    # list (submits from executor threads, releases from the reader
    # thread) and the service client's attach/seq/completion state
    SharedClass("emqx_tpu/broker/shmring.py", "WindowRing"),
    SharedClass("emqx_tpu/broker/matchclient.py", "ServiceMatchEngine"),
    SharedClass("emqx_tpu/ops/matchsvc.py", "MatchService"),
)

_METRIC_CALL_TAILS = {"inc", "observe", "inc_bulk"}


# ------------------------------------------------- thread-context map

def _spawn_targets(call: ast.Call) -> Iterable[ast.expr]:
    """Callable-reference argument positions of the thread-spawning
    shapes: Thread(target=f), to_thread(f, ...),
    loop.run_in_executor(exec, f, ...), executor.submit(f, ...)."""
    tail = call_tail(call)
    if tail == "Thread":
        for kw in call.keywords:
            if kw.arg == "target":
                yield kw.value
    elif tail == "to_thread" and call.args:
        yield call.args[0]
    elif tail == "run_in_executor" and len(call.args) >= 2:
        yield call.args[1]
    elif tail == "submit" and call.args:
        yield call.args[0]


def _unwrap_partial(expr: ast.expr) -> ast.expr:
    if isinstance(expr, ast.Call) and call_tail(expr) == "partial" \
            and expr.args:
        return expr.args[0]
    return expr


def thread_context_keys(program: callgraph.Program) -> Set[Key]:
    """Function keys that can execute on a worker thread: resolved
    Thread/to_thread/run_in_executor/submit targets, ``run`` methods
    of ``threading.Thread`` subclasses, and everything reachable from
    them through resolved SYNC call edges.  (call_soon_threadsafe
    hand-offs do NOT mark their callback: the callback runs on the
    loop — that is exactly the remediation RACE803 accepts.)"""
    entries: Set[Key] = set()
    fns = program.functions()
    by_key = {fn.key: fn for fn in fns}
    for fn in fns:
        for node in dataflow.walk_pruned(fn.node):
            if not isinstance(node, ast.Call):
                continue
            for argexpr in _spawn_targets(node):
                tgt = program._resolve_expr(
                    _unwrap_partial(argexpr), fn, depth=0
                )
                if tgt is not None and not tgt.is_async:
                    entries.add(tgt.key)
    for mod in program.modules.values():
        for ci in mod.classes.values():
            if any(
                dotted_name(b).rpartition(".")[2] == "Thread"
                for b in ci.bases
            ):
                run_q = ci.methods.get("run")
                if run_q is not None and (mod.path, run_q) in by_key:
                    entries.add((mod.path, run_q))
    marked = set(entries)
    work = list(entries)
    while work:
        fn = by_key.get(work.pop())
        if fn is None:
            continue
        for _call, callee in program.callees(fn):
            if callee.is_async:
                continue  # a bare thread cannot run a coroutine
            if callee.key not in marked:
                marked.add(callee.key)
                work.append(callee.key)
    return marked


# ------------------------------------------------- per-class modeling

class _Site(NamedTuple):
    fn: callgraph.FuncInfo
    line: int
    locked: bool


class _ClassModel:
    """One roster class's shared-state facts, collected by a flat
    line-ordered scan of every method (the recursive window walk for
    RACE801/804 runs separately, per async method)."""

    def __init__(self, mod: callgraph.ModuleIndex, name: str) -> None:
        self.mod = mod
        self.name = name
        self.token_prefix = f"{mod.dotted}.{name}."
        self.methods: List[callgraph.FuncInfo] = []
        self.writer_methods: Dict[str, Set[str]] = {}
        self.written_attrs: Set[str] = set()
        self.thread_written: Set[str] = set()
        self.thread_write_sites: Dict[str, List[_Site]] = {}
        self.loop_access: Dict[str, List[_Site]] = {}
        self.related: Set[frozenset] = set()
        self.shared: Set[str] = set()

    def token(self, attr: str) -> str:
        return self.token_prefix + attr


def _lock_spans(fn: callgraph.FuncInfo,
                program: callgraph.Program) -> List[Tuple[int, int]]:
    """Line ranges of ``with <lock-token>`` bodies in this function —
    a site inside one is treated as lock-protected."""
    spans: List[Tuple[int, int]] = []
    for node in dataflow.walk_pruned(fn.node):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        if any(
            dataflow.lock_token(i.context_expr, fn, program) is not None
            for i in node.items
        ):
            if node.body:
                spans.append((
                    node.body[0].lineno,
                    getattr(node, "end_lineno", node.lineno),
                ))
    return spans


def _in_spans(line: int, spans: Sequence[Tuple[int, int]]) -> bool:
    return any(lo <= line <= hi for lo, hi in spans)


def _suspension_name(node: ast.Await,
                     callees: Dict[int, callgraph.FuncInfo],
                     summaries: Dict) -> Optional[str]:
    """Can this await genuinely yield the loop?  Base classification
    first (bare future / known suspending tail), then the resolved
    callee's transitive ``suspends`` summary."""
    name = dataflow.await_suspends(node)
    if name is not None:
        return name
    for sub in ast.walk(node.value):
        if isinstance(sub, ast.Call):
            callee = callees.get(id(sub))
            if callee is None:
                continue
            cs = summaries.get(callee.key)
            if cs is not None and cs.suspends is not None:
                return f"{callee.name} -> {cs.suspends[0]}"
    return None


def _scan_method(model: _ClassModel, fn: callgraph.FuncInfo,
                 program: callgraph.Program, summaries: Dict,
                 thread_keys: Set[Key]) -> None:
    """Flat facts for one method: writer attribution, thread-side
    write sites, loop-side accesses, atomic co-write (relatedness)
    runs, suspension lines."""
    spans = _lock_spans(fn, program)
    callees = {id(c): f for c, f in program.callees(fn)}
    writes: List[Tuple[int, str]] = []
    reads: List[Tuple[int, str]] = []
    susp_lines: List[int] = []
    mut_recv: Set[int] = set()
    for node in dataflow.walk_pruned(fn.node):
        for attr in dataflow.attr_mutations(node):
            writes.append((node.lineno, attr))
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ) and node.func.attr in dataflow.MUTATOR_TAILS:
            mut_recv.add(id(node.func.value))
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = node.targets if isinstance(
                node, (ast.Assign, ast.Delete)) else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript):
                    mut_recv.add(id(t.value))
        elif isinstance(node, ast.Await):
            if fn.is_async and _suspension_name(
                node, callees, summaries
            ) is not None:
                susp_lines.append(node.lineno)
        elif isinstance(node, (ast.AsyncFor, ast.AsyncWith)):
            susp_lines.append(node.lineno)
        elif isinstance(node, ast.Attribute) and isinstance(
            node.ctx, ast.Load
        ) and id(node) not in mut_recv:
            attr = dataflow.self_attr_of(node)
            if attr is not None:
                reads.append((node.lineno, attr))
    is_thread = (not fn.is_async) and fn.key in thread_keys
    for line, attr in writes:
        model.writer_methods.setdefault(attr, set()).add(fn.qualname)
        model.written_attrs.add(attr)
        site = _Site(fn, line, _in_spans(line, spans))
        if is_thread:
            model.thread_written.add(attr)
            model.thread_write_sites.setdefault(attr, []).append(site)
        if fn.is_async:
            model.loop_access.setdefault(attr, []).append(site)
    if fn.is_async:
        for line, attr in reads:
            model.loop_access.setdefault(attr, []).append(
                _Site(fn, line, _in_spans(line, spans))
            )
    # relatedness: all pairs of DIFFERENT attrs written within one
    # suspension-free run are atomically co-updated somewhere — the
    # evidence RACE804 requires before calling a torn pair a bug.
    # Constructors don't count: __init__ assigns EVERY field in one
    # run, which would make all pairs "related" and degenerate
    # RACE804 into "any two writes torn across a suspension".
    if fn.node.name in ("__init__", "__new__"):
        return
    susp_sorted = sorted(susp_lines)
    run_attrs: Set[str] = set()
    prev_line = None
    for line, attr in sorted(writes):
        if prev_line is not None and any(
            prev_line < s <= line for s in susp_sorted
        ):
            _note_related(model, run_attrs)
            run_attrs = set()
        run_attrs.add(attr)
        prev_line = line
    _note_related(model, run_attrs)


def _note_related(model: _ClassModel, attrs: Set[str]) -> None:
    # 2-3 co-written fields is an atomic pair/triple (cursor +
    # watermark); a wider run is a bulk reset (start() clearing ten
    # dicts) and would cross-product RACE804 into noise
    if not 2 <= len(attrs) <= 3:
        return
    ordered = sorted(attrs)
    for i, a in enumerate(ordered):
        for b in ordered[i + 1:]:
            model.related.add(frozenset((a, b)))


# ------------------------------------------- RACE801/804 window walk

class _WindowWalk:
    """Execution-ordered walk of one async method, tracking per shared
    attr the read->suspend->write window (RACE801) and the
    write->suspend->related-write tear (RACE804).  Branches are
    processed independently and merged (worst rank wins); loop bodies
    run twice so back-edge windows surface."""

    def __init__(self, fn: callgraph.FuncInfo, model: _ClassModel,
                 ctx: ModuleContext, program: callgraph.Program,
                 summaries: Dict) -> None:
        self.fn = fn
        self.model = model
        self.ctx = ctx
        self.program = program
        self.summaries = summaries
        self.callees = {id(c): f for c, f in program.callees(fn)}
        # attr -> (read_line,) armed / (read_line, sus_name, sus_line)
        self.rank: Dict[str, Tuple] = {}
        self.written: Dict[str, int] = {}
        self.torn: Dict[str, Tuple[int, str, int]] = {}
        self.reported: Set[Tuple] = set()

    # ------------------------------------------------------- driving

    def run(self) -> None:
        self._stmts(self.fn.node.body, False)

    def _stmts(self, body: Sequence[ast.stmt], locked: bool) -> None:
        for st in body:
            self._stmt(st, locked)

    def _stmt(self, st: ast.stmt, locked: bool) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return
        if isinstance(st, ast.If):
            self._scan(st.test, locked)
            snap = self._snapshot()
            self._stmts(st.body, locked)
            branch = self._snapshot()
            self._restore(snap)
            self._stmts(st.orelse, locked)
            self._merge(branch)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._scan(st.iter, locked)
            for _ in range(2):
                if isinstance(st, ast.AsyncFor):
                    self._suspend("async-for", st.lineno)
                self._stmts(st.body, locked)
            self._stmts(st.orelse, locked)
            return
        if isinstance(st, ast.While):
            self._scan(st.test, locked)
            for _ in range(2):
                self._stmts(st.body, locked)
                self._scan(st.test, locked)
            self._stmts(st.orelse, locked)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            lk = locked
            for item in st.items:
                self._scan(item.context_expr, locked)
                if dataflow.lock_token(
                    item.context_expr, self.fn, self.program
                ) is not None:
                    lk = True
            if isinstance(st, ast.AsyncWith):
                self._suspend("async-with", st.lineno)
            self._stmts(st.body, lk)
            return
        if isinstance(st, ast.Try):
            self._stmts(st.body, locked)
            for h in st.handlers:
                self._stmts(h.body, locked)
            self._stmts(st.orelse, locked)
            self._stmts(st.finalbody, locked)
            return
        self._scan(st, locked)
        if isinstance(st, (ast.Continue, ast.Break, ast.Return,
                           ast.Raise)):
            # the straight-line path ends here: a loop back-edge
            # re-checks at the top, a return/raise leaves the method —
            # no window survives the jump
            self.rank.clear()
            self.written.clear()
            self.torn.clear()

    # ------------------------------------------------- event scanning

    def _scan(self, root: ast.AST, locked: bool) -> None:
        """One simple statement / expression subtree: collect events
        in source order (target writes of assignment statements are
        scheduled at the statement END — the value is read first) and
        apply them."""
        events: List[Tuple[int, int, int, str, str]] = []
        mut_recv: Set[int] = set()
        seq = 0

        def walk(node: ast.AST) -> None:
            nonlocal seq
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                walk(child)
            self._node_events(node, events, mut_recv)
            seq += 1

        walk(root)
        self._node_events(root, events, mut_recv)
        # drop reads that are merely mutator receivers / store bases
        out = [e for e in events
               if e[3] != "read" or e[2] not in mut_recv]
        out.sort(key=lambda e: (e[0], e[1]))
        for line, _col, _nid, kind, arg in out:
            if kind == "read":
                self._read(arg, line, locked)
            elif kind == "write":
                self._write(arg, line, locked, direct=True)
            elif kind == "write-callee":
                self._write(arg, line, locked, direct=False)
            elif kind == "suspend":
                self._suspend(arg, line)
            elif kind == "native":
                self._native(arg, line)

    def _node_events(self, node: ast.AST, events: List,
                     mut_recv: Set[int]) -> None:
        model = self.model
        if isinstance(node, ast.Attribute) and isinstance(
            node.ctx, ast.Load
        ):
            attr = dataflow.self_attr_of(node)
            if attr is not None and attr in model.shared:
                events.append((node.lineno, node.col_offset, id(node),
                               "read", attr))
            return
        if isinstance(node, ast.Await):
            name = _suspension_name(node, self.callees, self.summaries)
            if name is not None:
                events.append((node.lineno, node.col_offset, id(node),
                               "suspend", name))
            return
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in dataflow.MUTATOR_TAILS:
                mut_recv.add(id(node.func.value))
            for attr in dataflow.attr_mutations(node):
                if attr in model.shared:
                    events.append((node.lineno, node.col_offset,
                                   id(node), "write", attr))
            tail = call_tail(node)
            native = None
            if callgraph.is_native_entry(tail):
                native = tail
            else:
                callee = self.callees.get(id(node))
                if callee is not None:
                    cs = self.summaries.get(callee.key)
                    if cs is not None:
                        if cs.native is not None:
                            native = cs.native
                        for tok in cs.mutates:
                            if tok.startswith(model.token_prefix):
                                attr = tok[len(model.token_prefix):]
                                if attr in model.shared:
                                    # callee writes complete RACE801
                                    # windows but are NOT torn-pair
                                    # events: a helper whose summary
                                    # mutates a dozen attrs is a bulk
                                    # transition, not a cursor+
                                    # watermark pair
                                    events.append((
                                        node.lineno, node.col_offset,
                                        id(node), "write-callee", attr,
                                    ))
            if native is not None:
                events.append((node.lineno, node.col_offset, id(node),
                               "native", native))
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete,
                             ast.AnnAssign)):
            targets = node.targets if isinstance(
                node, (ast.Assign, ast.Delete)
            ) else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript):
                    mut_recv.add(id(t.value))
            end = (getattr(node, "end_lineno", node.lineno),
                   getattr(node, "end_col_offset", 10 ** 6))
            for attr in dataflow.attr_mutations(node):
                if attr in self.model.shared:
                    events.append((end[0], end[1], id(node),
                                   "write", attr))

    # --------------------------------------------------- event effects

    def _read(self, attr: str, line: int, locked: bool) -> None:
        if locked:
            return
        self.rank[attr] = (line,)  # armed (a later read RE-arms:
        # the re-check-after-await remediation clears the window)

    def _suspend(self, name: str, line: int) -> None:
        for attr, st in list(self.rank.items()):
            if len(st) == 1:
                self.rank[attr] = (st[0], name, line)
        for attr, wline in self.written.items():
            self.torn[attr] = (wline, name, line)
        self.written.clear()

    def _native(self, name: str, line: int) -> None:
        # a GIL-released span only breaks loop-atomicity for state a
        # worker thread also writes
        for attr, st in list(self.rank.items()):
            if len(st) == 1 and attr in self.model.thread_written:
                self.rank[attr] = (st[0], f"native `{name}`", line)

    def _write(self, attr: str, line: int, locked: bool,
               direct: bool = True) -> None:
        if locked:
            self.rank.pop(attr, None)
            self.written.pop(attr, None)
            self.torn.pop(attr, None)
            return
        st = self.rank.get(attr)
        if st is not None and len(st) == 3:
            key = ("RACE801", attr)  # one report per attr per method
            if key not in self.reported:
                self.reported.add(key)
                self.ctx.report_at(
                    line, "RACE801", self.fn.qualname,
                    f"check-then-act on shared `self.{attr}` spans a "
                    f"suspension: read at line {st[0]}, but `{st[1]}` "
                    f"(line {st[2]}) can yield the event loop before "
                    f"this write — another task can mutate "
                    f"`{attr}` in the window; re-check after the "
                    f"await or restructure",
                    detail=attr,
                )
        for other, (wline, sname, sline) in list(self.torn.items()):
            if not direct or other == attr:
                continue
            if frozenset((other, attr)) not in self.model.related:
                continue
            # one report per torn pair per method
            key = ("RACE804", frozenset((other, attr)))
            if key in self.reported:
                continue
            self.reported.add(key)
            self.ctx.report_at(
                line, "RACE804", self.fn.qualname,
                f"multi-field update torn across a suspension: "
                f"`self.{other}` (line {wline}) and `self.{attr}` "
                f"are updated atomically elsewhere in this class, "
                f"but `{sname}` (line {sline}) can yield between "
                f"them here — a task scheduled in the window sees "
                f"`{other}` advanced without `{attr}`",
                detail="+".join(sorted((other, attr))),
            )
        self.rank.pop(attr, None)
        self.torn.pop(attr, None)
        if direct:
            self.written[attr] = line

    # ------------------------------------------------- branch algebra

    def _snapshot(self):
        return (dict(self.rank), dict(self.written), dict(self.torn))

    def _restore(self, snap) -> None:
        self.rank = dict(snap[0])
        self.written = dict(snap[1])
        self.torn = dict(snap[2])

    def _merge(self, other) -> None:
        orank, owritten, otorn = other
        for attr, st in orank.items():
            cur = self.rank.get(attr)
            if cur is None or len(st) > len(cur):
                self.rank[attr] = st
        for attr, line in owritten.items():
            self.written.setdefault(attr, line)
        for attr, t in otorn.items():
            self.torn.setdefault(attr, t)


# --------------------------------------------------------- RACE802

def _iterated_attr(it: ast.expr) -> Optional[str]:
    attr = dataflow.self_attr_of(it)
    if attr is not None:
        return attr
    if isinstance(it, ast.Call) and isinstance(
        it.func, ast.Attribute
    ) and it.func.attr in ("items", "keys", "values") and not it.args:
        return dataflow.self_attr_of(it.func.value)
    return None


def _check_iteration(model: _ClassModel, fn: callgraph.FuncInfo,
                     ctx: ModuleContext, program: callgraph.Program,
                     summaries: Dict) -> None:
    callees = {id(c): f for c, f in program.callees(fn)}
    for node in dataflow.walk_pruned(fn.node):
        if not isinstance(node, ast.For):
            continue
        attr = _iterated_attr(node.iter)
        if attr is None or attr not in model.written_attrs:
            continue
        token = model.token(attr)
        cause: Optional[str] = None
        for sub in ast.walk(node):
            if sub is node.iter or isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if attr in dataflow.attr_mutations(sub):
                cause = f"the body mutates `self.{attr}` directly"
                break
            if isinstance(sub, ast.Call):
                callee = callees.get(id(sub))
                if callee is not None:
                    cs = summaries.get(callee.key)
                    if cs is not None and token in cs.mutates:
                        cause = (f"`{callee.name}()` (resolved) "
                                 f"mutates `self.{attr}`")
                        break
            if fn.is_async and attr in model.shared and isinstance(
                sub, ast.Await
            ):
                name = _suspension_name(sub, callees, summaries)
                if name is not None:
                    cause = (f"`{name}` can suspend mid-iteration "
                             f"and another task mutates "
                             f"`self.{attr}`")
                    break
        if cause is None:
            continue
        ctx.report_at(
            node.lineno, "RACE802", fn.qualname,
            f"iterating `self.{attr}` while {cause}: the container "
            f"can change under the live iterator (RuntimeError / "
            f"skipped entries in production) — iterate a snapshot "
            f"(`list(self.{attr})`) or restructure",
            detail=attr,
        )


# --------------------------------------------------------- RACE803

def _has_loop_comment(ctx: ModuleContext, line: int) -> bool:
    """``# loop-ownership: ...`` on the mutation line or anywhere in
    the contiguous comment block directly above it (the LOCK403
    annotation contract, applied to state instead of locks)."""
    if 1 <= line <= len(ctx.lines) and \
            _LOOP_OWNERSHIP_TOKEN in ctx.lines[line - 1]:
        return True
    cand = line - 1
    while 1 <= cand <= len(ctx.lines) and \
            ctx.lines[cand - 1].lstrip().startswith("#"):
        if _LOOP_OWNERSHIP_TOKEN in ctx.lines[cand - 1]:
            return True
        cand -= 1
    return False


def _check_thread_crossings(model: _ClassModel,
                            ctxs: Dict[str, ModuleContext]) -> None:
    for attr, sites in sorted(model.thread_write_sites.items()):
        loop_sites = model.loop_access.get(attr)
        if not loop_sites:
            continue
        ls = loop_sites[0]
        for site in sites:
            if site.locked:
                continue  # lock discipline is LOCK4xx's beat
            ctx = ctxs.get(site.fn.module.path)
            if ctx is None or _has_loop_comment(ctx, site.line):
                continue
            ctx.report_at(
                site.line, "RACE803", site.fn.qualname,
                f"`self.{attr}` is mutated here on a WORKER THREAD "
                f"but read on the event loop "
                f"(`{ls.fn.qualname}` line {ls.line}) with no lock "
                f"around this mutation — hand the mutation to the "
                f"loop with `call_soon_threadsafe`, lock both "
                f"sides, or document the ownership rule with a "
                f"`# loop-ownership: ...` comment",
                detail=attr,
            )


# ----------------------------------------------------------- MET901

def _find_registry(program: callgraph.Program):
    """(names, prefixes, registry_path) from the module defining a
    top-level ``METRICS`` tuple of string literals (plus the optional
    ``EXTRA_METRIC_PREFIXES`` families); None when the program has no
    registry — fixture programs without one skip MET901 entirely."""
    for path in sorted(program.modules):
        mod = program.modules[path]
        names: Optional[Set[str]] = None
        prefixes: Tuple[str, ...] = ()
        for st in mod.tree.body:
            target = None
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                target = st.targets[0].id
            elif isinstance(st, ast.AnnAssign) and isinstance(
                st.target, ast.Name
            ) and st.value is not None:
                target = st.target.id
            if target not in ("METRICS", "EXTRA_METRIC_PREFIXES"):
                continue
            value = st.value
            if not isinstance(value, (ast.Tuple, ast.List)):
                continue
            lits = [
                e.value for e in value.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)
            ]
            if target == "METRICS":
                names = set(lits)
            else:
                prefixes = tuple(lits)
        if names is not None:
            return names, prefixes, path
    return None


def _metric_name_ok(name: str, names: Set[str],
                    prefixes: Tuple[str, ...]) -> bool:
    return name in names or any(name.startswith(p) for p in prefixes)


def _is_metrics_recv(expr: ast.expr) -> bool:
    name = dotted_name(expr)
    return name == "metrics" or name.endswith(".metrics")


def _check_metrics(registry, fn_node: ast.AST, qualname: str,
                   ctx: ModuleContext) -> None:
    names, prefixes, _reg_path = registry
    # walk_pruned skips nested def/lambda subtrees for ANY root, so
    # the module-level pass sees only top/class-level statements and
    # every function gets exactly one pass of its own
    for node in dataflow.walk_pruned(fn_node):
        if not isinstance(node, ast.Call) or not isinstance(
            node.func, ast.Attribute
        ):
            continue
        if node.func.attr not in _METRIC_CALL_TAILS:
            continue
        if not _is_metrics_recv(node.func.value):
            continue
        if not node.args:
            continue
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            continue  # dynamic name: under-approximate, skip
        if _metric_name_ok(arg.value, names, prefixes):
            continue
        ctx.report(
            node, "MET901", qualname,
            f"counter `{arg.value}` is not in the metrics registry "
            f"(METRICS) and matches no EXTRA_METRIC_PREFIXES family "
            f"— it lands in the untyped `_extra` dict and no "
            f"dashboard/aggregation ever sees it; register the name "
            f"or fix the typo",
            detail=arg.value,
        )


# ------------------------------------------------------ orchestration

class RaceContext:
    """Everything the race pass computed once per program: thread
    marks, roster class models, the metrics registry.  The engine
    also digests `file_extra` into each file's program-findings cache
    key — the pieces of RACE/MET input that live OUTSIDE the file's
    own source and its direct callee summaries."""

    def __init__(self, program: callgraph.Program, summaries: Dict,
                 shared: Optional[Sequence[SharedClass]]) -> None:
        self.program = program
        self.summaries = summaries
        self.shared_spec = tuple(
            SHARED_CLASSES if shared is None else shared
        )
        self.thread_keys = thread_context_keys(program)
        self.registry = _find_registry(program)
        self.models: List[_ClassModel] = []
        self._build_models()

    def _build_models(self) -> None:
        program, summaries = self.program, self.summaries
        by_mod: Dict[str, List[callgraph.FuncInfo]] = {}
        for fn in program.functions():
            by_mod.setdefault(fn.module.path, []).append(fn)
        for spec in self.shared_spec:
            for path in sorted(program.modules):
                if not path.endswith(spec.path_suffix):
                    continue
                mod = program.modules[path]
                if spec.name not in mod.classes:
                    continue
                model = _ClassModel(mod, spec.name)
                for fn in by_mod.get(path, ()):
                    if fn.cls == spec.name:
                        model.methods.append(fn)
                        _scan_method(model, fn, program, summaries,
                                     self.thread_keys)
                model.shared = {
                    a for a, ms in model.writer_methods.items()
                    if len(ms) >= 2
                } | model.thread_written
                self.models.append(model)

    def file_extra(self, path: str) -> str:
        """Cache-key component for one file: its functions' thread
        marks, the registry signature, and whether a roster class
        lives here (whose model mixes facts from EVERY method of the
        class — all same-file — plus the thread marks above)."""
        marks = sorted(
            q for (p, q) in self.thread_keys if p == path
        )
        reg = None
        if self.registry is not None:
            names, prefixes, reg_path = self.registry
            reg = (tuple(sorted(names)), prefixes, reg_path)
        roster = sorted(
            m.name for m in self.models if m.mod.path == path
        )
        return repr((marks, reg, roster, self.shared_spec))


def prepare(program: callgraph.Program, summaries: Dict,
            shared: Optional[Sequence[SharedClass]] = None
            ) -> RaceContext:
    return RaceContext(program, summaries, shared)


def check_local(rc: RaceContext,
                ctxs: Dict[str, ModuleContext]) -> None:
    """The per-file families (cacheable by dependency digest):
    RACE801/802/804 over roster classes, MET901 over every module."""
    program, summaries = rc.program, rc.summaries
    for model in rc.models:
        ctx = ctxs.get(model.mod.path)
        if ctx is None:
            continue
        for fn in model.methods:
            if fn.is_async:
                _WindowWalk(fn, model, ctx, program, summaries).run()
            _check_iteration(model, fn, ctx, program, summaries)
    if rc.registry is None:
        return
    reg_path = rc.registry[2]
    for path, ctx in ctxs.items():
        if path == reg_path:
            continue
        mod = program.modules.get(path)
        if mod is None:
            continue
        for fn in mod.funcs.values():
            _check_metrics(rc.registry, fn.node, fn.qualname, ctx)
        _check_metrics(rc.registry, mod.tree, "<module>", ctx)


def check_global(rc: RaceContext,
                 ctxs: Dict[str, ModuleContext]) -> None:
    """The cross-file family: RACE803 thread<->loop crossings (its
    inputs — thread reachability — span the whole program, so its
    findings are recomputed every run, never cached per-file)."""
    for model in rc.models:
        _check_thread_crossings(model, ctxs)


def check_program(
    program: callgraph.Program,
    summaries: Dict,
    ctxs: Dict[str, ModuleContext],
    shared: Optional[Sequence[SharedClass]] = None,
) -> None:
    """One-shot entry (fixture tests / analyze_source): prepare +
    local + global."""
    rc = prepare(program, summaries, shared)
    check_local(rc, ctxs)
    check_global(rc, ctxs)


__all__ = [
    "RaceContext", "SHARED_CLASSES", "SharedClass", "check_global",
    "check_local", "check_program", "prepare", "thread_context_keys",
]
