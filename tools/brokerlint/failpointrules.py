"""Failpoint-coverage rule (FP301).

ROADMAP used to carry a manual reminder that every new IO seam takes a
``failpoints.evaluate`` call; this rule is that reminder, enforced.
``SEAM_FUNCS`` declares the broker's real failure seams — the
functions where a fault injected in chaos runs exercises the SAME
recovery path a production fault would.  Each declared function must
contain a ``failpoints.evaluate``/``evaluate_async`` call, either
directly or through one same-module helper (``self._send_failpoint``
-style indirection resolves one level).

Growing the broker?  Add the new seam here AND in
``emqx_tpu.failpoints.SEAMS`` (the disabled-guard test iterates that
tuple), then give it a chaos test.
"""

from __future__ import annotations

import ast
from typing import List, NamedTuple, Sequence, Tuple

from .engine import ModuleContext, call_tail, is_failpoint_call


class Seam(NamedTuple):
    path_suffix: str   # module path suffix, posix ('cluster/transport.py')
    qualname: str      # dotted function name inside the module
    seam: str          # the failpoints.SEAMS name it must evaluate


# Kept in sync with emqx_tpu/failpoints.py SEAMS (tests/test_lint.py
# cross-checks the seam names against that tuple).
SEAM_FUNCS: Tuple[Seam, ...] = (
    Seam("emqx_tpu/engine.py", "MatchEngine._flat_dispatch",
         "engine.device_step"),
    Seam("emqx_tpu/engine.py", "MatchEngine._decide_device",
         "dispatch.decide.device"),
    Seam("emqx_tpu/engine.py", "MatchEngine._rules_device",
         "dispatch.rules.device"),
    Seam("emqx_tpu/cluster/transport.py", "NodeTransport.cast",
         "cluster.transport.send"),
    Seam("emqx_tpu/cluster/transport.py", "NodeTransport.cast_bin",
         "cluster.transport.send"),
    Seam("emqx_tpu/cluster/transport.py", "NodeTransport.call",
         "cluster.transport.send"),
    Seam("emqx_tpu/cluster/transport.py", "NodeTransport._on_conn",
         "cluster.transport.recv"),
    Seam("emqx_tpu/cluster/raft.py", "RaftNode._on_rpc",
         "cluster.raft.rpc"),
    Seam("emqx_tpu/ds/replication.py", "ReplicaStore.store_checkpoint",
         "ds.replication.store"),
    Seam("emqx_tpu/ds/replication.py", "ReplicaStore.append_messages",
         "ds.replication.store"),
    Seam("emqx_tpu/kafka.py", "KafkaClient.produce", "kafka.produce"),
    Seam("emqx_tpu/resources.py", "BufferWorker._run",
         "resource.buffer.query"),
    Seam("emqx_tpu/resources.py", "BufferWorker._flush_once",
         "resource.batch.flush"),
    Seam("emqx_tpu/bridge_mqtt.py", "MqttEgressResource.on_query_batch",
         "bridge.mqtt.send"),
    Seam("emqx_tpu/exhook/client.py", "ExhookClient._call",
         "exhook.call"),
    Seam("emqx_tpu/ds/beamformer.py", "Beamformer.poll",
         "ds.beamformer.poll"),
    Seam("emqx_tpu/cluster_link.py", "LinkServer._on_publish",
         "cluster.link.forward"),
    Seam("emqx_tpu/s3.py", "S3Client._request", "s3.request"),
    Seam("emqx_tpu/ds/persist.py", "DurableSessions._replay_read",
         "ds.replay.read"),
    Seam("emqx_tpu/ds/native.py", "DsLog.append", "ds.store.append"),
    Seam("emqx_tpu/ds/native.py", "DsLog.sync", "ds.store.sync"),
    Seam("emqx_tpu/ds/atomicio.py", "atomic_write_json",
         "ds.meta.write"),
    Seam("emqx_tpu/broker/resume.py", "ResumeScheduler._commit",
         "session.resume.commit"),
    Seam("emqx_tpu/cluster/quic_transport.py",
         "QuicPeerLink._transmit", "cluster.quic.send"),
    Seam("emqx_tpu/cluster/quic_transport.py",
         "QuicPeerLink._on_datagram", "cluster.quic.recv"),
    Seam("emqx_tpu/cluster/quic_transport.py",
         "QuicPeerEndpoint.transmit", "cluster.quic.send"),
    Seam("emqx_tpu/cluster/quic_transport.py",
         "QuicPeerEndpoint.on_datagram", "cluster.quic.recv"),
    Seam("emqx_tpu/cluster/node.py", "ClusterNode._send_fwd_ack",
         "cluster.forward.ack"),
    Seam("emqx_tpu/olp.py", "LoadMonitor.sample", "olp.sample"),
    Seam("emqx_tpu/olp.py", "LoadMonitor.shed", "olp.shed"),
    Seam("emqx_tpu/ds/journal.py", "MetaJournal.append",
         "ds.journal.append"),
    Seam("emqx_tpu/ds/native.py", "DsLog.gc", "ds.gc.reclaim"),
    Seam("emqx_tpu/broker/matchclient.py",
         "ServiceMatchEngine._ring_submit", "multicore.ring.submit"),
    Seam("emqx_tpu/broker/matchclient.py",
         "ServiceMatchEngine._ring_decide", "multicore.ring.submit"),
    Seam("emqx_tpu/broker/matchclient.py",
         "ServiceMatchEngine._ring_complete",
         "multicore.ring.complete"),
    Seam("emqx_tpu/broker/matchclient.py",
         "ServiceMatchEngine._reconnect_once",
         "multicore.service.restart"),
)


def _function_map(tree: ast.Module):
    """qualname -> FunctionDef/AsyncFunctionDef for the whole module."""
    out = {}

    def walk(node, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                out[f"{prefix}{child.name}"] = child
                walk(child, f"{prefix}{child.name}.")

    walk(tree, "")
    return out


def _evaluates_failpoint(fn, ctx: ModuleContext) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            if is_failpoint_call(node):
                return True
            # one level of same-module indirection:
            # `await self._send_failpoint(node)` counts when that
            # helper's body evaluates a failpoint
            if call_tail(node) in ctx.failpoint_methods:
                return True
    return False


def check(ctx: ModuleContext,
          seams: Sequence[Seam] = SEAM_FUNCS) -> None:
    relevant: List[Seam] = [
        s for s in seams if ctx.path.endswith(s.path_suffix)
    ]
    if not relevant:
        return
    fns = _function_map(ctx.tree)
    for s in relevant:
        fn = fns.get(s.qualname)
        if fn is None:
            ctx.report(
                ctx.tree, "FP301", s.qualname,
                f"declared failpoint seam function `{s.qualname}` not "
                f"found in {ctx.path} — update "
                f"tools/brokerlint/failpointrules.py:SEAM_FUNCS",
                detail=f"missing:{s.seam}",
            )
            continue
        if not _evaluates_failpoint(fn, ctx):
            ctx.report(
                fn, "FP301", s.qualname,
                f"IO seam `{s.qualname}` must evaluate failpoint "
                f"`{s.seam}` (failpoints.evaluate/_async) so chaos "
                f"runs can exercise its recovery path",
                detail=s.seam,
            )


__all__ = ["check", "Seam", "SEAM_FUNCS"]
