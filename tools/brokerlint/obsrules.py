"""Observability-cost rules (OBS601, OBS602).

PR 8 threads a per-message lifecycle tracer through the dispatch path
under one invariant: tracing work happens OUTSIDE the dispatch hot
loops (one ``window_spans`` call per window), and anything span- or
context-shaped that DOES sit in a loop must be behind a sampled-check
— otherwise every unsampled delivery pays allocation for a feature
that is off 99%+ of the time, un-doing the PR 3/5 wins the batched
pipeline bought.

OBS601 enforces it the way PERF401/402 guard encode and clock costs:
inside a loop of a ``DISPATCH_FUNCS``-marked function, a call whose
receiver chain names the tracer (``tracer``/``lifecycle``/
``profiler`` attribute segments) or that constructs a trace object
(``TraceContext``/``Span``/``WindowRecord``) is a finding UNLESS an
enclosing ``if``'s test mentions the sampling decision (``sampled``,
``trace_ctx``/``tctx``/``ctx``, or ``_trace_fwd``).  Intentional
exceptions take a justified inline ``# brokerlint: ignore[OBS601]``.

OBS602 holds the flight recorder (flightrec.py) to its own stricter
contract: the recorder is ALWAYS ON, so there is no sampled-guard to
hide behind — any flight-recorder call inside a dispatch hot loop must
be the preallocated O(1) ring append (``.record(...)``), and its
argument tree must not allocate (no dict/list/set/tuple/f-string
displays, no comprehensions, no calls beyond scalar coercions like
``float``/``int``/``len``).  ``fl.note(...)``, ``fl.trigger(...)`` and
friends are cold-path API and a finding when they appear in a loop.
"""

from __future__ import annotations

import ast
from typing import List, Sequence, Tuple

from .engine import ModuleContext, dotted_name
from .perfrules import DISPATCH_FUNCS, DispatchFn, _function_map

# attribute-chain segments that mean "this receiver is a tracer"
_TRACER_SEGMENTS = {"tracer", "lifecycle", "profiler"}

# constructors that allocate per-message trace objects
_TRACE_CTORS = {"TraceContext", "Span", "WindowRecord", "PendingForward"}

# an enclosing if-test mentioning any of these counts as the
# sampled-guard (the decision object, or the decision itself —
# ``span``/``ctx`` cover the `if span is not None:` idiom, where the
# object only exists because the message was sampled)
_GUARD_TOKENS = ("sampled", "trace_ctx", "tctx", "_trace_fwd", "ctx",
                 "span")


def _is_tracing_call(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    if not name:
        return False
    segments = name.split(".")
    if segments[-1] in _TRACE_CTORS:
        return True
    # receiver segments only: `self.tracer.start(...)` is a tracer
    # call; a function named `tracer()` alone is not a receiver chain
    return any(seg in _TRACER_SEGMENTS for seg in segments[:-1])


def _guard_hit(test: ast.AST) -> bool:
    try:
        src = ast.unparse(test)
    except Exception:  # pragma: no cover - unparse of exotic nodes
        return False
    return any(tok in src for tok in _GUARD_TOKENS)


def _walk(fn: ast.AST) -> List[Tuple[ast.Call, bool]]:
    """(tracing_call, guarded) pairs lexically inside a loop of `fn`;
    nested def/lambda subtrees are pruned (a closure defined in the
    loop is not per-delivery work), and descending into the body of an
    ``if`` whose test mentions the sampling decision marks everything
    under it as guarded."""
    hits: List[Tuple[ast.Call, bool]] = []

    def walk(node: ast.AST, in_loop: bool, guarded: bool) -> None:
        if isinstance(node, ast.If):
            # handled at ENTRY (not only as someone's child) so guards
            # nested under other ifs/loops still mark their bodies; a
            # loop that is itself a DIRECT child of the if body must
            # still flip in_loop for its subtree
            hit = _guard_hit(node.test)
            walk(node.test, in_loop, guarded)
            for sub in node.body:
                walk(sub, in_loop or isinstance(
                    sub, (ast.For, ast.AsyncFor, ast.While)
                ), guarded or hit)
            for sub in node.orelse:
                walk(sub, in_loop or isinstance(
                    sub, (ast.For, ast.AsyncFor, ast.While)
                ), guarded)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)) and child is not fn:
                continue
            child_in_loop = in_loop or isinstance(
                child, (ast.For, ast.AsyncFor, ast.While)
            )
            if (
                in_loop
                and isinstance(child, ast.Call)
                and _is_tracing_call(child)
            ):
                hits.append((child, guarded))
            walk(child, child_in_loop, guarded)

    walk(fn, False, False)
    return hits


# ------------------------------------------------------------- OBS602

# attribute-chain segments that mean "this receiver is the flight
# recorder" — `self.flight.record(...)`, the hoisted-local idiom
# `fl.record(...)`, and module-level `flightrec.X(...)`
_FLIGHT_SEGMENTS = {"flight", "flightrec", "fl"}

# the ONLY flight-recorder method allowed inside a dispatch loop: the
# preallocated O(1) ring append
_FLIGHT_HOT_OK = {"record"}

# scalar coercions that do not allocate per-call — everything else in
# a record() argument tree is a finding
_SCALAR_CALLS = {"float", "int", "len", "bool", "abs", "min", "max"}

# AST displays/comprehensions that allocate a fresh container (or
# string) per evaluation
_ALLOC_NODES = (
    ast.Dict, ast.List, ast.Set, ast.Tuple, ast.ListComp, ast.SetComp,
    ast.DictComp, ast.GeneratorExp, ast.JoinedStr, ast.Starred,
    ast.Await,
)


def _is_flight_call(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    if not name:
        return False
    segments = name.split(".")
    # receiver segments only: a local variable named `record` or a
    # plain function `flight()` is not a flight-recorder method call
    return len(segments) > 1 and any(
        seg in _FLIGHT_SEGMENTS for seg in segments[:-1]
    )


def _alloc_in_args(call: ast.Call) -> str:
    """First allocating construct in the call's argument tree, or ""
    when every argument is scalar-shaped (names, attributes,
    constants, arithmetic, and _SCALAR_CALLS coercions)."""
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        for node in ast.walk(arg):
            if isinstance(node, _ALLOC_NODES):
                return type(node).__name__
            if isinstance(node, ast.Call):
                inner = dotted_name(node.func) or "<call>"
                if inner.split(".")[-1] not in _SCALAR_CALLS:
                    return f"{inner}()"
    return ""


def _walk_flight(fn: ast.AST) -> List[ast.Call]:
    """Flight-recorder calls lexically inside a loop of `fn`; nested
    def/lambda subtrees are pruned, and — unlike OBS601 — there is NO
    guard exemption: the recorder is always on, so an enclosing if
    cannot make the work free."""
    hits: List[ast.Call] = []

    def walk(node: ast.AST, in_loop: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)) and child is not fn:
                continue
            child_in_loop = in_loop or isinstance(
                child, (ast.For, ast.AsyncFor, ast.While)
            )
            if (
                in_loop
                and isinstance(child, ast.Call)
                and _is_flight_call(child)
            ):
                hits.append(child)
            walk(child, child_in_loop)

    walk(fn, False)
    return hits


def _check_obs602(ctx: ModuleContext, d: DispatchFn,
                  fn: ast.AST) -> None:
    for call in _walk_flight(fn):
        name = dotted_name(call.func) or "<flight>"
        tail = name.split(".")[-1]
        if tail not in _FLIGHT_HOT_OK:
            ctx.report(
                call, "OBS602", d.qualname,
                f"flight-recorder call `{name}(` inside the dispatch "
                f"hot loop `{d.qualname}` is not the O(1) ring append "
                f"— only `.record(...)` may run per-iteration; "
                f"`note`/`trigger`/`status` are cold-path API",
                detail=name,
            )
            continue
        alloc = _alloc_in_args(call)
        if alloc:
            ctx.report(
                call, "OBS602", d.qualname,
                f"`{name}(` in the dispatch hot loop `{d.qualname}` "
                f"allocates in its argument tree ({alloc}) — the "
                f"always-on recorder's loop contract is scalar args "
                f"only (names, constants, arithmetic, float/int/len)",
                detail=f"{name}+{alloc}",
            )


def check(ctx: ModuleContext,
          dispatch: Sequence[DispatchFn] = DISPATCH_FUNCS) -> None:
    relevant = [d for d in dispatch if ctx.path.endswith(d.path_suffix)]
    if not relevant:
        return
    fns = _function_map(ctx.tree)
    for d in relevant:
        fn = fns.get(d.qualname)
        if fn is None:
            continue  # PERF401 already reports the missing declaration
        for call, guarded in _walk(fn):
            if guarded:
                continue
            name = dotted_name(call.func)
            ctx.report(
                call, "OBS601", d.qualname,
                f"unguarded trace/span work `{name}(` inside the "
                f"dispatch hot loop `{d.qualname}` — gate it behind "
                f"the sampled-check (`if <ctx> is not None:`) or hoist "
                f"it to the once-per-window emission",
                detail=name,
            )
        _check_obs602(ctx, d, fn)


__all__ = ["check"]
