"""Write-trace recording + crash-state materialization.

The method is the crash-consistency literature's (ALICE, OSDI '14):
capture the logical write trace of a workload, then for every crash
point materialize the on-disk state a power failure there could have
left, boot recovery on it, and assert invariants.  The trace is
captured at the SAME seams chaos runs use — `DsLog` journals every
open/append/sync through its class-level ``recorder`` hook, the
atomic-write helper journals every completed metadata replace — so
what the simulator replays is exactly what the broker wrote.

Crash-state model (the legal-states envelope we enumerate):

  * the dslog segment files are append-only and written sequentially,
    so a crash persists a PREFIX of the append trace, with the record
    at the cut possibly torn at any byte boundary (``torn_bytes``);
    enumerating every prefix subsumes every "suffix beyond the last
    fsync lost" state and is strictly more adversarial (it also
    covers losing suffixes that HAD been fsynced — recovery must
    merely never lose what the workload's acks claim);
  * a metadata write (tmp + rename) at the cut can land as: nothing
    (old file kept — rename not persisted), the staging ``.tmp`` file
    holding a partial document next to the old file, or — the
    no-fsync power-fail case the CRC trailer exists for — the rename
    persisted with TORN content (``meta_variant="replaced-torn"``);
  * cross-file reordering: a metadata write in the un-fsynced tail
    may be lost while LATER appends persist (``skip_meta_index``) —
    the ALICE reordering case that matters here, since sidecars and
    the log live in different files;
  * the metadata JOURNALS (PR 16's incremental sidecars) are
    append-only like the segment log: a crash mid-``jappend``
    persists a torn prefix of the frame blob, and a ``jtrunc``
    (the fold's truncation) resets the materialized journal.

`sync_covered_index` maps a crash point to the last fsync the prefix
completed, which is what the workload's ack ledger is keyed by: in
``always`` mode a PUBACK exists only for messages a completed sync
covers, so "zero acked loss at every crash point" is assertable
purely from the trace.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import List, NamedTuple, Optional

_HDR = struct.Struct("<IIIQQ")  # len, crc32, stream, ts, seq
_DEFAULT_SEG_BYTES = 64 << 20


class Op(NamedTuple):
    kind: str            # "open" | "append" | "sync" | "meta"
                         # | "jappend" | "jtrunc"
    path: str            # dir (open/append/sync) or file path
                         # (meta/jappend/jtrunc)
    stream: int = 0
    ts: int = 0
    seq: int = 0
    data: bytes = b""    # record payload / final meta document
    seg_bytes: int = 0   # open only
    fsynced: bool = False  # meta only


class CrashRecorder:
    """Install on the live seams, run a workload, keep the trace."""

    def __init__(self) -> None:
        self.ops: List[Op] = []

    # ------------------------------------------------- seam callbacks

    def on_open(self, directory: str, seg_bytes: int) -> None:
        self.ops.append(Op("open", directory, seg_bytes=seg_bytes))

    def on_append(self, directory: str, stream: int, ts: int,
                  seq: int, data: bytes) -> None:
        self.ops.append(
            Op("append", directory, stream=stream, ts=ts, seq=seq,
               data=bytes(data))
        )

    def on_sync(self, directory: str) -> None:
        self.ops.append(Op("sync", directory))

    def on_meta(self, path: str, content: bytes,
                fsynced: bool) -> None:
        self.ops.append(Op("meta", path, data=content, fsynced=fsynced))

    def on_jappend(self, path: str, blob: bytes) -> None:
        self.ops.append(Op("jappend", path, data=bytes(blob)))

    def on_jtrunc(self, path: str) -> None:
        self.ops.append(Op("jtrunc", path))

    # ------------------------------------------------------- install

    def install(self) -> None:
        from emqx_tpu.ds import atomicio
        from emqx_tpu.ds.native import DsLog

        DsLog.recorder = self
        atomicio.recorder = self

    def uninstall(self) -> None:
        from emqx_tpu.ds import atomicio
        from emqx_tpu.ds.native import DsLog

        DsLog.recorder = None
        atomicio.recorder = None

    def __enter__(self) -> "CrashRecorder":
        self.install()
        return self

    def __exit__(self, *exc) -> None:
        self.uninstall()


def encode_record(op: Op) -> bytes:
    """The exact native/dslog.cpp on-disk record for an append op."""
    return _HDR.pack(
        len(op.data), zlib.crc32(op.data), op.stream, op.ts, op.seq
    ) + op.data


def sync_covered_index(ops: List[Op], crash_at: int) -> int:
    """Index of the last sync op the crash prefix COMPLETED, or -1.
    (A sync op is journaled after its fsync returns, so presence in
    the prefix == the flush landed.)"""
    last = -1
    for i in range(min(crash_at, len(ops))):
        if ops[i].kind == "sync":
            last = i
    return last


class _SegWriter:
    """Mirror of the native segment-roll discipline, per store dir."""

    def __init__(self, seg_bytes: int) -> None:
        self.seg_bytes = seg_bytes or _DEFAULT_SEG_BYTES
        self.cur_seg = 0
        self.cur_size = 0
        self.segs = {0: bytearray()}

    def append(self, blob: bytes) -> None:
        if self.cur_size >= self.seg_bytes:
            self.cur_seg += 1
            self.cur_size = 0
            self.segs[self.cur_seg] = bytearray()
        self.segs[self.cur_seg] += blob
        self.cur_size += len(blob)

    def write_out(self, out_dir: str) -> None:
        os.makedirs(out_dir, exist_ok=True)
        for seg, buf in self.segs.items():
            with open(
                os.path.join(out_dir, "seg-%06d.log" % seg), "wb"
            ) as f:
                f.write(buf)


def materialize(
    ops: List[Op],
    crash_at: int,
    src_root: str,
    out_root: str,
    torn_bytes: Optional[int] = None,
    meta_variant: str = "old",
    skip_meta_index: Optional[int] = None,
) -> None:
    """Build under ``out_root`` the on-disk state of a crash at op
    index ``crash_at`` (ops[:crash_at] happened; the op AT crash_at is
    the one possibly caught mid-flight).

    ``torn_bytes``: when the op at ``crash_at`` is an append, how many
    bytes of its record hit the disk (byte-granular tearing); when it
    is a meta write, a prefix length of its document for the
    ``meta_variant`` in play.

    ``meta_variant`` (op at crash_at is a meta write):
      * ``old``           rename did not persist: previous content
                          (or absence) survives — the default;
      * ``tmp-partial``   the staging file holds ``torn_bytes`` of the
                          new document, target keeps the old content;
      * ``replaced-torn`` the rename persisted but the data pages did
                          not: target holds a torn prefix — the state
                          the CRC trailer turns from silent reset into
                          an alarmed conservative recovery.

    ``skip_meta_index``: drop that meta op from the prefix while
    keeping everything after it (cross-file reordering: the sidecar
    write was lost although later log appends persisted).
    """
    crash_at = min(crash_at, len(ops))

    def out_path(p: str) -> str:
        rel = os.path.relpath(p, src_root)
        assert not rel.startswith(".."), (p, src_root)
        return os.path.join(out_root, rel)

    writers = {}
    metas = {}
    journals = {}
    for i in range(crash_at):
        op = ops[i]
        if op.kind == "open":
            writers.setdefault(op.path, _SegWriter(op.seg_bytes))
        elif op.kind == "append":
            writers.setdefault(
                op.path, _SegWriter(0)
            ).append(encode_record(op))
        elif op.kind == "meta":
            if i != skip_meta_index:
                metas[op.path] = op.data
        elif op.kind == "jappend":
            journals.setdefault(op.path, bytearray()).extend(op.data)
        elif op.kind == "jtrunc":
            journals[op.path] = bytearray()
        # sync: no state transition to materialize

    # the op caught mid-flight
    if crash_at < len(ops) and torn_bytes is not None:
        op = ops[crash_at]
        if op.kind == "append":
            blob = encode_record(op)
            writers.setdefault(op.path, _SegWriter(0)).append(
                blob[: max(0, min(torn_bytes, len(blob) - 1))]
            )
        elif op.kind == "jappend":
            # the journal is append-only like the segment log: a crash
            # mid-append persists a torn prefix of the frame blob
            journals.setdefault(op.path, bytearray()).extend(
                op.data[: max(0, min(torn_bytes, len(op.data) - 1))]
            )
        elif op.kind == "meta":
            cut = max(1, min(torn_bytes, len(op.data) - 1))
            if meta_variant == "tmp-partial":
                metas[op.path + ".tmp"] = op.data[:cut]
            elif meta_variant == "replaced-torn":
                metas[op.path] = op.data[:cut]
            # "old": nothing — the previous content stands
        # jtrunc mid-flight: truncation either happened or it did not;
        # both states are already enumerated by adjacent crash points

    for d, w in writers.items():
        w.write_out(out_path(d))
    for p, content in metas.items():
        target = out_path(p)
        os.makedirs(os.path.dirname(target), exist_ok=True)
        with open(target, "wb") as f:
            f.write(content)
    for p, buf in journals.items():
        target = out_path(p)
        os.makedirs(os.path.dirname(target), exist_ok=True)
        with open(target, "wb") as f:
            f.write(bytes(buf))
