"""Crash-point simulation harness for the DS durability contract.

See sim.py: a recording layer under the storage writes (the
``ds.store.append`` / ``ds.store.sync`` / ``ds.meta.write`` seams'
journaling taps) plus a materializer that can rebuild the on-disk
state at ANY crash point of a recorded write trace — un-fsynced
suffixes dropped, records torn mid-write at byte granularity, and
metadata rename outcomes enumerated (old kept / staging file partial /
replaced-but-torn) — so `tests/test_crash_recovery.py` can boot a
fresh broker on every materialized prefix and assert the recovery
invariants (ALICE, Pillai et al. OSDI '14; CrashMonkey, Mohan et al.
OSDI '18).
"""

from .sim import CrashRecorder, Op, materialize, sync_covered_index

__all__ = ["CrashRecorder", "Op", "materialize", "sync_covered_index"]
