"""Priority-ordered hook chains: the extension spine.

Re-expresses the reference's hook system (`emqx_hooks:run/2`,
`run_fold/3`, /root/reference/apps/emqx/src/emqx_hooks.erl; hookpoint
inventory emqx_hookpoints.erl:40-71) without the gen_server: a plain
registry of callback chains, sorted by descending priority then
registration order.  Callbacks signal flow control by return value:

  * ``run`` (notify):   return ``STOP`` to halt the chain, anything
    else to continue.
  * ``run_fold`` (transform): return ``STOP`` to halt keeping the
    current accumulator, ``STOP_WITH(v)`` to halt replacing it,
    ``None`` to pass the accumulator through unchanged, any other
    value to replace the accumulator and continue.
"""

from __future__ import annotations

import bisect
import functools
import itertools
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

# the core hookpoints (emqx_hookpoints.erl:40-71); registration is not
# limited to these, but they document the broker's extension surface
HOOKPOINTS = (
    "client.connect",
    "client.connack",
    "client.connected",
    "client.disconnected",
    "client.authenticate",
    "client.authorize",
    "client.subscribe",
    "client.unsubscribe",
    "session.created",
    "session.subscribed",
    "session.unsubscribed",
    "session.resumed",
    "session.discarded",
    "session.takenover",
    "session.terminated",
    "message.publish",
    "message.puback",
    "message.delivered",
    "message.acked",
    "message.dropped",
    "delivery.dropped",
)


class _Stop:
    __slots__ = ("value", "has_value")

    def __init__(self, value: Any = None, has_value: bool = False):
        self.value = value
        self.has_value = has_value

    def __repr__(self) -> str:
        return f"STOP_WITH({self.value!r})" if self.has_value else "STOP"


STOP = _Stop()


def STOP_WITH(value: Any) -> _Stop:
    return _Stop(value, True)


def with_async(sync_fn: Callable[..., Any],
               async_fn: Callable[..., Any]) -> Callable[..., Any]:
    """Pair a blocking callback with a coroutine twin.  Chains walked
    by ``run_fold``/``run`` call ``sync_fn``; the ``*_async`` walkers
    prefer ``async_fn`` so IO-backed hooks (exhook verdict RPCs) wait
    off the event loop instead of stalling every connection on it."""

    @functools.wraps(sync_fn)
    def wrapper(*args: Any) -> Any:
        return sync_fn(*args)

    wrapper.async_fn = async_fn  # type: ignore[attr-defined]
    return wrapper


class Callback(NamedTuple):
    priority: int
    seq: int
    fn: Callable[..., Any]

    def sort_key(self) -> Tuple[int, int]:
        # higher priority first; ties in registration order
        return (-self.priority, self.seq)


class HookRegistry:
    def __init__(self) -> None:
        self._chains: Dict[str, List[Callback]] = {}
        self._seq = itertools.count()
        # names with >=1 async-capable callback, kept as counts so
        # `has_async` is an O(1) hot-path check (the publish/authorize
        # paths consult it per packet)
        self._async_counts: Dict[str, int] = {}

    def add(
        self, name: str, fn: Callable[..., Any], priority: int = 0
    ) -> Callback:
        cb = Callback(priority, next(self._seq), fn)
        chain = self._chains.setdefault(name, [])
        bisect.insort(chain, cb, key=Callback.sort_key)
        if getattr(fn, "async_fn", None) is not None:
            self._async_counts[name] = self._async_counts.get(name, 0) + 1
        return cb

    def delete(self, name: str, fn_or_cb: Any) -> bool:
        chain = self._chains.get(name, [])
        for i, cb in enumerate(chain):
            if cb is fn_or_cb or cb.fn is fn_or_cb:
                del chain[i]
                if getattr(cb.fn, "async_fn", None) is not None:
                    n = self._async_counts.get(name, 1) - 1
                    if n <= 0:
                        self._async_counts.pop(name, None)
                    else:
                        self._async_counts[name] = n
                return True
        return False

    def has_async(self, name: str) -> bool:
        return name in self._async_counts

    def has(self, name: str) -> bool:
        """O(1) is-anything-registered probe: the dispatch window uses
        it to skip the ``message.delivered`` walk (and the per-run
        delivery-list materialization feeding it) entirely when nobody
        registered a callback."""
        return bool(self._chains.get(name))

    def callbacks(self, name: str) -> List[Callback]:
        return list(self._chains.get(name, ()))

    def run(self, name: str, *args: Any) -> None:
        """Notify chain: each callback sees the same args; a ``STOP``
        return halts the chain (emqx_hooks:run/2).  Iterates a
        SNAPSHOT: registrations may land from other threads (e.g. an
        exhook dial completing in an executor) mid-dispatch."""
        for cb in tuple(self._chains.get(name, ())):
            res = cb.fn(*args)
            if isinstance(res, _Stop):
                return

    def run_fold(self, name: str, args: Tuple[Any, ...], acc: Any) -> Any:
        """Transform chain: callbacks get ``(*args, acc)`` and may
        replace the accumulator (emqx_hooks:run_fold/3).  Snapshot
        iteration, as in `run`."""
        for cb in tuple(self._chains.get(name, ())):
            res = cb.fn(*args, acc)
            if isinstance(res, _Stop):
                return res.value if res.has_value else acc
            if res is not None:
                acc = res
        return acc

    async def run_fold_async(
        self, name: str, args: Tuple[Any, ...], acc: Any
    ) -> Any:
        """`run_fold` that awaits async-capable callbacks (registered
        via `with_async`) so IO hooks never block the event loop; pure
        callbacks run inline with identical semantics."""
        for cb in tuple(self._chains.get(name, ())):
            afn = getattr(cb.fn, "async_fn", None)
            if afn is not None:
                res = await afn(*args, acc)
            else:
                res = cb.fn(*args, acc)
            if isinstance(res, _Stop):
                return res.value if res.has_value else acc
            if res is not None:
                acc = res
        return acc


# the default, process-global registry (the reference's hooks live in a
# single ets table owned by one gen_server; one module-level registry
# is the direct analogue for a single broker instance)
_global: Optional[HookRegistry] = None


def global_registry() -> HookRegistry:
    global _global
    if _global is None:
        _global = HookRegistry()
    return _global
