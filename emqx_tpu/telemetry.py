"""Opt-in telemetry reporter.

The `emqx_telemetry` role (/root/reference/apps/emqx_telemetry/src:
periodic anonymous usage reports).  Disabled by default; when enabled
it POSTs a small JSON snapshot (version, uptime, counts — never
payloads, topics, or client identifiers) to the configured URL on an
interval, via the buffered resource layer so an unreachable endpoint
never affects the broker.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Optional

from .resources import BufferWorker, HttpSink
from .sys_topics import VERSION


class TelemetryReporter:
    def __init__(
        self,
        broker,
        url: str,
        interval: float = 7 * 24 * 3600.0,
    ) -> None:
        self.broker = broker
        self.url = url
        self.interval = interval
        self.node_uuid = str(uuid.uuid4())  # random per boot, not stable
        self._worker: Optional[BufferWorker] = None
        self._last: Optional[float] = None  # None => report on first tick

    async def start(self) -> None:
        self._worker = BufferWorker(
            HttpSink(self.url),
            max_buffer=8,
            max_retries=3,
            # a reporter that POSTs weekly must not HEAD-probe a dead
            # endpoint every second
            health_interval=max(self.interval, 60.0),
        )
        await self._worker.start()

    async def stop(self) -> None:
        if self._worker is not None:
            await self._worker.stop()
            self._worker = None

    def report(self) -> dict:
        b = self.broker
        return {
            "uuid": self.node_uuid,
            "version": VERSION,
            "uptime": int(time.time() - b.metrics.start_time),
            "connections": len(b.cm),
            "subscriptions": b.router.subscription_count(),
            "rules": len(b.rules.rules),
            "gateways": [g["name"] for g in b.gateways.info()],
            "cluster_size": (
                1 + len(b.external.peers_alive())
                if b.external is not None
                else 1
            ),
        }

    def tick(self, now: Optional[float] = None) -> bool:
        if self._worker is None:
            return False
        # monotonic basis: wall-clock steps must not skew the interval
        now = now if now is not None else time.monotonic()
        if self._last is not None and now - self._last < self.interval:
            return False
        self._last = now
        self._worker.enqueue(json.dumps(self.report()))
        return True
