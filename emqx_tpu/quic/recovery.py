"""Selective-ACK loss recovery bookkeeping (RFC 9002 shapes).

Crypto-free on purpose: connection.py needs the ``cryptography``
package for packet protection, but WHICH bytes each packet carried and
WHICH of them were acked is pure range arithmetic — keeping it here
lets the recovery model be unit-tested in environments without the
crypto dependency.

The model (per packet-number space):

  * every ack-eliciting packet records the (offset, length) ranges of
    CRYPTO and STREAM data it carried (`SentPacket`);
  * an ACK frame acks exact packet numbers — only the ranges THOSE
    packets carried become acked (`RangeTracker`), so an ack of the
    latest packet no longer implies anything about earlier ones
    (the pre-selective-ack model treated it as cumulative, and a lost
    earlier packet's bytes were never retransmitted: the receiver
    wedged until idle timeout);
  * a packet ``kPacketThreshold`` (3, RFC 9002 §6.1.1) below the
    largest acked is declared lost: its still-unacked ranges are
    queued for retransmission;
  * PTO declares every in-flight packet lost the same way (the
    timer-driven fallback when acks stop entirely).

Send-stream watermarks advance only over the CONTIGUOUS acked prefix,
so the buffer trim (base-offset rebase, PR 1) stays exact under
selective loss.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

PACKET_THRESHOLD = 3  # RFC 9002 §6.1.1 kPacketThreshold


class RangeTracker:
    """Sorted, disjoint, half-open ``[start, end)`` ranges."""

    __slots__ = ("ranges",)

    def __init__(self) -> None:
        self.ranges: List[Tuple[int, int]] = []

    def add(self, start: int, end: int) -> None:
        if end <= start:
            return
        out: List[Tuple[int, int]] = []
        for s, e in self.ranges:
            if e < start or s > end:  # disjoint (touching merges)
                out.append((s, e))
            else:
                start, end = min(s, start), max(e, end)
        out.append((start, end))
        out.sort()
        self.ranges = out

    def contiguous_from(self, base: int) -> int:
        """Furthest offset reachable from `base` through acked ranges
        (== `base` when the next byte is unacked)."""
        for s, e in self.ranges:
            if s <= base < e or s == base:
                return max(e, base)
        return base

    def missing_within(self, start: int, end: int) -> List[Tuple[int, int]]:
        """The sub-ranges of ``[start, end)`` NOT yet acked."""
        out: List[Tuple[int, int]] = []
        cur = start
        for s, e in self.ranges:
            if e <= cur:
                continue
            if s >= end:
                break
            if s > cur:
                out.append((cur, min(s, end)))
            cur = max(cur, e)
            if cur >= end:
                return out
        if cur < end:
            out.append((cur, end))
        return out

    def prune_below(self, floor: int) -> None:
        """Drop bookkeeping for bytes below `floor` (already consumed
        by the contiguous watermark) to bound long-lived connections."""
        self.ranges = [
            (max(s, floor), e) for s, e in self.ranges if e > floor
        ]


class SentPacket:
    """What one ack-eliciting packet carried."""

    __slots__ = ("crypto", "streams", "fins")

    def __init__(self) -> None:
        self.crypto: List[Tuple[int, int]] = []        # (off, end)
        self.streams: List[Tuple[int, int, int]] = []  # (sid, off, end)
        self.fins: List[int] = []                      # sids with FIN

    @property
    def ack_eliciting(self) -> bool:
        return bool(self.crypto or self.streams or self.fins)


class RecoverySpace:
    """Per packet-number space: in-flight packets + acked-range state
    for the crypto stream (application streams keep their trackers on
    the stream objects; this class still routes their packet records).
    """

    __slots__ = ("sent", "crypto_acked", "crypto_retx",
                 "largest_acked")

    def __init__(self) -> None:
        self.sent: Dict[int, SentPacket] = {}
        self.crypto_acked = RangeTracker()
        self.crypto_retx: List[Tuple[int, int]] = []
        self.largest_acked = -1

    # ------------------------------------------------------ recording

    def record(self, pn: int, pkt: SentPacket) -> None:
        if pkt.ack_eliciting:
            self.sent[pn] = pkt

    # ----------------------------------------------------------- acks

    def on_ack_range(self, lo: int, hi: int) -> List[SentPacket]:
        """Pop and return the records of acked packet numbers."""
        lo = max(lo, 0)
        self.largest_acked = max(self.largest_acked, hi)
        out: List[SentPacket] = []
        if hi - lo > len(self.sent) * 4:  # sparse dict, wide range
            for pn in [p for p in self.sent if lo <= p <= hi]:
                out.append(self.sent.pop(pn))
        else:
            for pn in range(lo, hi + 1):
                pkt = self.sent.pop(pn, None)
                if pkt is not None:
                    out.append(pkt)
        for pkt in out:
            for off, end in pkt.crypto:
                self.crypto_acked.add(off, end)
        return out

    def detect_lost(self) -> List[SentPacket]:
        """Packets `PACKET_THRESHOLD` below the largest acked are lost
        (RFC 9002 time-threshold is approximated by the PTO timer)."""
        cutoff = self.largest_acked - PACKET_THRESHOLD
        lost_pns = sorted(pn for pn in self.sent if pn <= cutoff)
        return [self.sent.pop(pn) for pn in lost_pns]

    def on_pto(self) -> List[SentPacket]:
        """Declare everything in flight lost (ack stream went quiet)."""
        pns = sorted(self.sent)
        return [self.sent.pop(pn) for pn in pns]

    # ------------------------------------------------- retransmission

    def queue_crypto_retx(self, ranges: List[Tuple[int, int]]) -> None:
        """Queue the still-unacked parts of lost crypto ranges."""
        for off, end in ranges:
            for s, e in self.crypto_acked.missing_within(off, end):
                self.crypto_retx.append((s, e))

    def take_crypto_retx(self) -> List[Tuple[int, int]]:
        """Drain the retx queue, re-filtering against acks that landed
        after queueing (a spurious-loss ack beats a retransmit)."""
        out: List[Tuple[int, int]] = []
        for off, end in self.crypto_retx:
            out.extend(self.crypto_acked.missing_within(off, end))
        self.crypto_retx = []
        return out
