"""QUIC v1 transport (RFC 9000/9001) — from-scratch, for the quic
listener class the reference ships via MsQuic
(/root/reference/apps/emqx/src/emqx_quic_connection.erl,
emqx_listeners.erl:448).  Neither aioquic nor msquic exists in this
environment, so the transport is implemented directly: a TLS 1.3
handshake core (tls13.py) on `cryptography` primitives and the QUIC
packet/frame/connection layer (connection.py), scoped to what an MQTT
listener needs — see each module's docstring for the explicit cuts."""

# connection imported lazily (listener/tests): from .connection import QuicConnection
