"""QUIC v1 packet protection + connection state machine (sans-IO).

RFC 9000/9001 scoped to an MQTT listener's needs (the reference's
quicer/MsQuic slot, emqx_quic_connection.erl):

  * Initial/Handshake/1-RTT packet spaces with AES-128-GCM protection
    and AES-ECB header protection; initial secrets from the v1 salt;
  * CRYPTO carries the embedded TLS 1.3 handshake (tls13.py); ACK,
    STREAM (OFF|LEN|FIN), PING, PADDING, CONNECTION_CLOSE,
    HANDSHAKE_DONE frames;
  * client coalesces + pads its first flight to 1200 bytes; server
    coalesces Initial+Handshake replies;
  * loss recovery is selective-ack based (recovery.py): each outgoing
    packet records the (offset, length) CRYPTO/STREAM ranges it
    carried, an ACK advances exactly those ranges, a packet 3 below
    the largest acked (or any in-flight packet at PTO) is declared
    lost and its unacked ranges retransmitted — so an earlier lost
    packet is recovered even while later packets keep being acked;
    congestion control is a fixed window — honest cut: loopback/LAN
    listeners, not WAN bulk transfer;
  * explicit cuts: version negotiation, Retry, 0-RTT, key update,
    connection migration, stateless reset, flow-control ENFORCEMENT
    (windows are advertised large and respected by our own peer).

Sans-IO: `receive_datagram` in, `datagrams_to_send` out, `events()`
for the listener; asyncio lives in broker/quic_listener.py."""

from __future__ import annotations

import hashlib
import hmac
import os
import struct
from typing import Dict, List, Optional, Tuple

# optional: AES-GCM packet protection needs `cryptography`; the PSK
# cluster profile (integrity-only, stdlib hmac) does not, and the
# inter-node transport must work in environments without the package
try:
    from cryptography.hazmat.primitives.ciphers import (
        Cipher, algorithms, modes,
    )
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    HAVE_CRYPTO = True
except ImportError:  # pragma: no cover - environment-dependent
    Cipher = algorithms = modes = AESGCM = None  # type: ignore
    HAVE_CRYPTO = False

from .recovery import RangeTracker, RecoverySpace, SentPacket
from .tls13 import HandshakeError, hkdf_expand_label, hkdf_extract

INITIAL_SALT_V1 = bytes.fromhex(
    "38762cf7f55934b34d179ae6a4c80cadccbb7f0a"
)
VERSION_1 = 0x00000001

EPOCH_INITIAL, EPOCH_HANDSHAKE, EPOCH_APP = 0, 2, 3

# frame types
F_PADDING = 0x00
F_PING = 0x01
F_ACK = 0x02
F_CRYPTO = 0x06
F_STREAM_BASE = 0x08
F_MAX_DATA = 0x10
F_CLOSE = 0x1C
F_CLOSE_APP = 0x1D
F_DONE = 0x1E


# ------------------------------------------------------------- varints

def enc_varint(v: int) -> bytes:
    if v < 0x40:
        return bytes([v])
    if v < 0x4000:
        return struct.pack(">H", v | 0x4000)
    if v < 0x40000000:
        return struct.pack(">I", v | 0x80000000)
    return struct.pack(">Q", v | 0xC000000000000000)


def dec_varint(data: bytes, off: int) -> Tuple[int, int]:
    first = data[off]
    kind = first >> 6
    if kind == 0:
        return first, off + 1
    if kind == 1:
        return struct.unpack_from(">H", data, off)[0] & 0x3FFF, off + 2
    if kind == 2:
        return (
            struct.unpack_from(">I", data, off)[0] & 0x3FFFFFFF, off + 4
        )
    return (
        struct.unpack_from(">Q", data, off)[0] & 0x3FFFFFFFFFFFFFFF,
        off + 8,
    )


# --------------------------------------------------------- key material

class Keys:
    def __init__(self, secret: bytes) -> None:
        if AESGCM is None:
            raise ImportError(
                "AES-GCM packet protection requires the "
                "`cryptography` package (the PSK cluster profile "
                "does not)"
            )
        self.aead = AESGCM(hkdf_expand_label(secret, "quic key", b"", 16))
        self.iv = hkdf_expand_label(secret, "quic iv", b"", 12)
        self.hp = hkdf_expand_label(secret, "quic hp", b"", 16)

    def nonce(self, pn: int) -> bytes:
        return bytes(
            b ^ ((pn >> (8 * (11 - i))) & 0xFF)
            for i, b in enumerate(self.iv)
        )

    def hp_mask(self, sample: bytes) -> bytes:
        c = Cipher(algorithms.AES(self.hp), modes.ECB()).encryptor()
        return c.update(sample)[:5]


class _PskAead:
    """AEAD-shaped integrity protection keyed by a pre-shared secret:
    ciphertext = plaintext || HMAC-SHA256(psk, nonce||aad||plaintext)
    truncated to 16 bytes.  NO confidentiality — the payload travels
    in the clear, authenticated.  This is the cluster peer transport's
    profile: the TCP inter-node transport is plaintext too, and the
    QUIC layer is used for its loss recovery and streams, not secrecy.
    A tampered or wrong-psk packet fails the tag check and is dropped
    exactly like an AEAD decrypt failure."""

    __slots__ = ("psk",)

    def __init__(self, psk: bytes) -> None:
        self.psk = psk

    def _tag(self, nonce: bytes, aad: bytes, data: bytes) -> bytes:
        return hmac.new(
            self.psk, nonce + aad + data, hashlib.sha256
        ).digest()[:16]

    def encrypt(self, nonce: bytes, data: bytes, aad: bytes) -> bytes:
        return data + self._tag(nonce, aad, data)

    def decrypt(self, nonce: bytes, ct: bytes, aad: bytes) -> bytes:
        data, tag = ct[:-16], ct[-16:]
        if not hmac.compare_digest(self._tag(nonce, aad, data), tag):
            raise ValueError("psk integrity tag mismatch")
        return data


class PskKeys:
    """`Keys`-shaped key material for the PSK profile: hmac integrity
    tag, identity header-protection mask (headers unprotected — both
    ends are in-repo cluster peers on a trusted network)."""

    __slots__ = ("aead", "iv")

    _ZERO_MASK = b"\x00" * 5

    def __init__(self, psk: bytes) -> None:
        self.aead = _PskAead(psk)
        self.iv = hashlib.sha256(psk + b"quic-psk-iv").digest()[:12]

    def nonce(self, pn: int) -> bytes:
        return bytes(
            b ^ ((pn >> (8 * (11 - i))) & 0xFF)
            for i, b in enumerate(self.iv)
        )

    def hp_mask(self, sample: bytes) -> bytes:
        return self._ZERO_MASK


def initial_keys(dcid: bytes) -> Tuple[Keys, Keys]:
    """(client_keys, server_keys) for the Initial space."""
    initial = hkdf_extract(INITIAL_SALT_V1, dcid)
    return (
        Keys(hkdf_expand_label(initial, "client in", b"", 32)),
        Keys(hkdf_expand_label(initial, "server in", b"", 32)),
    )


def encode_transport_params(scid: bytes,
                            odcid: Optional[bytes]) -> bytes:
    def tp(tid: int, val: bytes) -> bytes:
        return enc_varint(tid) + enc_varint(len(val)) + val

    out = b"".join([
        tp(0x01, enc_varint(30_000)),          # max_idle_timeout ms
        tp(0x03, enc_varint(65527)),           # max_udp_payload_size
        tp(0x04, enc_varint(1 << 25)),         # initial_max_data
        tp(0x05, enc_varint(1 << 24)),
        tp(0x06, enc_varint(1 << 24)),
        tp(0x07, enc_varint(1 << 24)),
        tp(0x08, enc_varint(128)),             # max_streams_bidi
        tp(0x09, enc_varint(128)),             # max_streams_uni
        tp(0x0F, scid),                        # initial_scid
    ])
    if odcid is not None:
        out += tp(0x00, odcid)                 # original_dcid (server)
    return out


class _SendStream:
    __slots__ = ("data", "base", "acked", "fin", "fin_sent",
                 "fin_acked", "acked_ranges", "retx")

    def __init__(self) -> None:
        self.data = b""     # unacked tail: stream bytes [base:]
        self.base = 0       # absolute offset of data[0] (acked prefix
        self.acked = 0      # is trimmed, so base tracks acked)
        self.fin = False
        self.fin_sent = False
        self.fin_acked = False
        # selective-ack state: which absolute ranges the peer acked
        # (watermarks advance only over the contiguous prefix) and
        # which lost ranges await retransmission
        self.acked_ranges = RangeTracker()
        self.retx: List[Tuple[int, int]] = []


class _RecvStream:
    __slots__ = ("chunks", "delivered", "fin_at")

    def __init__(self) -> None:
        self.chunks: Dict[int, bytes] = {}
        self.delivered = 0
        self.fin_at: Optional[int] = None


class QuicConnection:
    def __init__(
        self,
        is_server: bool,
        cert_der: Optional[bytes] = None,
        key=None,
        alpn: str = "mqtt",
        server_name: str = "localhost",
        psk: Optional[bytes] = None,
        cid: Optional[bytes] = None,
    ) -> None:
        """``psk`` selects the CLUSTER profile: no TLS handshake, both
        endpoints derive `PskKeys` from the shared secret and speak
        1-RTT short-header packets from the first datagram — loss
        recovery, streams, and packetization are the full QUIC
        machinery, protection is integrity-only (see `_PskAead`).  The
        connection id is symmetric (``cid``, scid == dcid): the server
        endpoint demuxes short headers by it and constructs its side
        with the same id."""
        self.is_server = is_server
        if psk is not None:
            c = cid if cid is not None else os.urandom(8)
            self.scid = c
            self.dcid = c
            self.original_dcid = c
            self.tls = None
        else:
            from .tls13 import Tls13  # requires `cryptography`

            self.scid = os.urandom(8)
            self.dcid = os.urandom(8)  # client: until server SCID learned
            self.original_dcid = self.dcid
            self.tls = Tls13(
                is_server,
                alpn=alpn,
                quic_tp=encode_transport_params(
                    self.scid, self.dcid if is_server else None
                ),
                cert_der=cert_der,
                key=key,
                server_name=server_name,
            )
        self._client_keys: Optional[Keys] = None
        self._server_keys: Optional[Keys] = None
        self._keys: Dict[int, Tuple[Optional[Keys], Optional[Keys]]] = {
            EPOCH_INITIAL: (None, None),
            EPOCH_HANDSHAKE: (None, None),
            EPOCH_APP: (None, None),
        }  # (send, recv) per epoch
        self._pn: Dict[int, int] = {0: 0, 2: 0, 3: 0}
        self._largest_recv: Dict[int, int] = {0: -1, 2: -1, 3: -1}
        self._recv_pns: Dict[int, set] = {0: set(), 2: set(), 3: set()}
        # dedup/ACK window floor: pns below it are treated as already
        # received and pruned from the set, bounding both the set and
        # the ACK frame on long-lived connections
        self._pn_floor: Dict[int, int] = {0: 0, 2: 0, 3: 0}
        self._PN_WINDOW = 2048
        self._ack_due: Dict[int, bool] = {0: False, 2: False, 3: False}
        # ack frequency (RFC 9000 §13.2.2: ack at least every 2nd
        # ack-eliciting packet): 1 = immediate; the PSK cluster
        # profile uses 2 — halving ack datagrams on the bulk forward
        # path — with `ack_flush()` (driver tick) covering tails
        self._ack_every = 1
        self._ack_pending: Dict[int, int] = {0: 0, 2: 0, 3: 0}
        # crypto send state per epoch: buffer + contiguous acked/sent
        self._crypto_out: Dict[int, bytes] = {0: b"", 2: b"", 3: b""}
        self._crypto_sent: Dict[int, int] = {0: 0, 2: 0, 3: 0}
        self._crypto_recv_off: Dict[int, int] = {0: 0, 2: 0, 3: 0}
        self._crypto_chunks: Dict[int, Dict[int, bytes]] = {
            0: {}, 2: {}, 3: {},
        }
        self._streams_out: Dict[int, _SendStream] = {}
        self._streams_sent: Dict[int, int] = {}
        # selective-ack loss recovery: per-space record of which
        # (offset, length) ranges each outgoing packet carried
        # (recovery.py; an ack advances exactly those ranges)
        self._spaces: Dict[int, RecoverySpace] = {
            EPOCH_INITIAL: RecoverySpace(),
            EPOCH_HANDSHAKE: RecoverySpace(),
            EPOCH_APP: RecoverySpace(),
        }
        self._streams_in: Dict[int, _RecvStream] = {}
        self._events: List[tuple] = []
        self.handshake_complete = False
        self._handshake_done_sent = False
        self._handshake_confirmed = False
        # RFC 9000 §8.1: a server treats the client address as
        # validated once a packet protected with handshake (or 1-RTT)
        # keys decrypts — those keys require the client to have
        # received our Initial flight at that address.  Until then the
        # listener caps send volume at 3x received and skips
        # timer-driven retransmits (anti-amplification).
        self.address_validated = not is_server
        self.closed = False
        self.close_code: Optional[int] = None
        self._out_datagrams: List[bytes] = []
        self._next_stream_id = 0 if is_server else 0
        # stream-chunk size per packet: 1100 keeps TLS-profile packets
        # under the 1280-byte internet path MTU floor (RFC 9000 §14);
        # the PSK cluster profile runs on loopback/LAN links whose MTU
        # the operator controls, so it packs bigger datagrams — fewer
        # packets per window frame, less per-packet host work
        self.max_stream_chunk = 1100
        if psk is not None:
            # PSK profile: app keys exist from the start, there is no
            # handshake to complete and no address to validate (the
            # transport's hello frame is the application handshake)
            k = PskKeys(psk)
            self._keys[EPOCH_APP] = (k, k)
            self.handshake_complete = True
            self._handshake_done_sent = True
            self._handshake_confirmed = True
            self.address_validated = True
            self.max_stream_chunk = 8192
            self._ack_every = 2
        elif is_server:
            pass  # keys derive from the first Initial's DCID
        else:
            ck, sk = initial_keys(self.dcid)
            self._keys[EPOCH_INITIAL] = (ck, sk)

    # ----------------------------------------------------------- API

    def connect(self) -> None:
        assert not self.is_server
        if self.tls is None:
            return  # PSK profile: no handshake flight to send
        self.tls.client_hello()
        self._flush()

    def send_stream(self, stream_id: int, data: bytes,
                    fin: bool = False) -> None:
        st = self._streams_out.setdefault(stream_id, _SendStream())
        st.data += data
        st.fin = st.fin or fin
        if self.handshake_complete:
            self._flush()

    def open_stream(self) -> int:
        """Next locally-initiated bidirectional stream id."""
        sid = self._next_stream_id + (1 if self.is_server else 0)
        self._next_stream_id += 4
        return sid

    def close(self, code: int = 0) -> None:
        if self.closed:
            return
        self.closed = True
        self.close_code = code
        epoch = (
            EPOCH_APP if self._keys[EPOCH_APP][0] else EPOCH_INITIAL
        )
        frame = (bytes([F_CLOSE_APP]) + enc_varint(code)
                 + enc_varint(0))
        pkt = self._build_packet(epoch, frame)
        if pkt:
            self._out_datagrams.append(pkt)

    def events(self) -> List[tuple]:
        evs, self._events = self._events, []
        return evs

    def datagrams_to_send(self) -> List[bytes]:
        out, self._out_datagrams = self._out_datagrams, []
        return out

    def has_inflight(self) -> bool:
        """Any ack-eliciting packet awaiting an ACK?  Drivers use this
        to gate PTO probes: no in-flight data means nothing a timeout
        could recover, so firing one would only spray duplicates."""
        return any(s.sent for s in self._spaces.values())

    def ack_flush(self) -> None:
        """Force out any ack withheld by the ack-frequency threshold
        (the driver's periodic tick calls this so a burst TAIL — one
        odd packet with nothing behind it — still acks promptly and
        the peer's PTO never fires on delivered data)."""
        flush = False
        for epoch, pending in self._ack_pending.items():
            if pending > 0 and not self._ack_due[epoch]:
                self._ack_due[epoch] = True
                flush = True
        if flush:
            self._flush()

    def on_timeout(self) -> None:
        """PTO: the ack stream went quiet — declare every in-flight
        packet lost, queue its still-unacked ranges, emit a fresh
        flight (exact ranges, not a full-history replay)."""
        for epoch in (EPOCH_INITIAL, EPOCH_HANDSHAKE, EPOCH_APP):
            self._requeue_lost(epoch, self._spaces[epoch].on_pto())
        self._flush()

    def _requeue_lost(self, epoch: int, lost: List[SentPacket]) -> None:
        """Queue the not-yet-acked ranges of lost packets for
        retransmission (acks that raced the loss declaration win)."""
        space = self._spaces[epoch]
        crypto: List[Tuple[int, int]] = []
        for pkt in lost:
            crypto.extend(pkt.crypto)
            for sid, off, end in pkt.streams:
                st = self._streams_out.get(sid)
                if st is None:
                    continue
                st.retx.extend(
                    st.acked_ranges.missing_within(off, end)
                )
            for sid in pkt.fins:
                st = self._streams_out.get(sid)
                if st is not None and not st.fin_acked:
                    st.fin_sent = False  # re-send the FIN
        space.queue_crypto_retx(crypto)

    # ------------------------------------------------------ receiving

    def receive_datagram(self, data: bytes) -> None:
        off = 0
        while off < len(data) and not self.closed:
            consumed = self._receive_packet(data, off)
            if consumed <= 0:
                break
            off += consumed
        self._flush()

    def _receive_packet(self, data: bytes, off: int) -> int:
        first = data[off]
        if first & 0x80:  # long header
            if self.tls is None:
                # PSK profile peers never send long headers; a stray
                # Initial (port scan, misdirected client) is ignored
                return 0
            version = struct.unpack_from(">I", data, off + 1)[0]
            if version != VERSION_1:
                return 0
            p = off + 5
            dcid_len = data[p]
            dcid = data[p + 1:p + 1 + dcid_len]
            p += 1 + dcid_len
            scid_len = data[p]
            scid = data[p + 1:p + 1 + scid_len]
            p += 1 + scid_len
            ptype = (first & 0x30) >> 4
            if ptype == 0:  # Initial
                tok_len, p = dec_varint(data, p)
                p += tok_len
                epoch = EPOCH_INITIAL
                if self.is_server and self._keys[EPOCH_INITIAL][0] is None:
                    ck, sk = initial_keys(dcid)
                    self._keys[EPOCH_INITIAL] = (sk, ck)
                    self.original_dcid = dcid
                    self.dcid = scid
            elif ptype == 2:  # Handshake
                epoch = EPOCH_HANDSHAKE
            else:
                return 0  # 0-RTT/Retry: out of scope
            if not self.is_server and scid:
                self.dcid = scid  # adopt the server's connection id
            length, p = dec_varint(data, p)
            return self._unprotect(
                data, off, p, length, epoch, long_header=True
            )
        # short header (1-RTT): dcid is OUR scid (8 bytes)
        p = off + 1 + 8
        remaining = len(data) - p
        return self._unprotect(
            data, off, p, remaining, EPOCH_APP, long_header=False
        )

    def _unprotect(self, data: bytes, pkt_start: int, pn_off: int,
                   length: int, epoch: int, long_header: bool) -> int:
        _send, recv = self._keys[epoch]
        if recv is None:
            return 0  # keys not available yet (reordered packet)
        sample = data[pn_off + 4:pn_off + 4 + 16]
        if len(sample) < 16:
            return 0
        mask = recv.hp_mask(sample)
        first = data[pkt_start] ^ (
            mask[0] & (0x0F if long_header else 0x1F)
        )
        pn_len = (first & 0x03) + 1
        pn_bytes = bytes(
            data[pn_off + i] ^ mask[1 + i] for i in range(pn_len)
        )
        pn_trunc = int.from_bytes(pn_bytes, "big")
        pn = self._decode_pn(epoch, pn_trunc, pn_len * 8)
        header = (
            bytes([first])
            + data[pkt_start + 1:pn_off]
            + pn_bytes
        )
        payload_len = length - pn_len
        ct = data[pn_off + pn_len:pn_off + pn_len + payload_len]
        try:
            pt = recv.aead.decrypt(recv.nonce(pn), ct, header)
        except Exception:
            return 0
        if self.is_server and epoch != EPOCH_INITIAL:
            self.address_validated = True
        if pn < self._pn_floor[epoch] or pn in self._recv_pns[epoch]:
            return pn_off + pn_len + payload_len - pkt_start
        self._recv_pns[epoch].add(pn)
        self._largest_recv[epoch] = max(self._largest_recv[epoch], pn)
        floor = self._largest_recv[epoch] - self._PN_WINDOW
        if floor > self._pn_floor[epoch]:
            self._pn_floor[epoch] = floor
            self._recv_pns[epoch] = {
                p for p in self._recv_pns[epoch] if p >= floor
            }
        self._process_frames(epoch, pt)
        return pn_off + pn_len + payload_len - pkt_start

    def _decode_pn(self, epoch: int, trunc: int, bits: int) -> int:
        expected = self._largest_recv[epoch] + 1
        win = 1 << bits
        candidate = (expected & ~(win - 1)) | trunc
        if candidate <= expected - win // 2 and candidate + win < (1 << 62):
            return candidate + win
        if candidate > expected + win // 2 and candidate >= win:
            return candidate - win
        return candidate

    # -------------------------------------------------------- frames

    def _process_frames(self, epoch: int, payload: bytes) -> None:
        off = 0
        ack_eliciting = False
        while off < len(payload):
            ftype = payload[off]
            if ftype == F_PADDING:
                off += 1
                continue
            if ftype == F_PING:
                off += 1
                ack_eliciting = True
                continue
            if ftype in (F_ACK, F_ACK + 1):
                off = self._on_ack(epoch, payload, off)
                continue
            if ftype == F_CRYPTO:
                coff, off = dec_varint(payload, off + 1)
                clen, off = dec_varint(payload, off)
                self._on_crypto(epoch, coff,
                                payload[off:off + clen])
                off += clen
                ack_eliciting = True
                continue
            if F_STREAM_BASE <= ftype <= F_STREAM_BASE + 7:
                off = self._on_stream(ftype, payload, off)
                ack_eliciting = True
                continue
            if ftype == F_DONE:
                off += 1
                self._handshake_confirmed = True
                ack_eliciting = True
                continue
            if ftype in (F_CLOSE, F_CLOSE_APP):
                code, off2 = dec_varint(payload, off + 1)
                if ftype == F_CLOSE:
                    _ft, off2 = dec_varint(payload, off2)
                rlen, off2 = dec_varint(payload, off2)
                off = off2 + rlen
                self.closed = True
                self.close_code = code
                self._events.append(("closed", code))
                continue
            # MAX_DATA / MAX_STREAM_DATA / NEW_CONNECTION_ID /
            # STREAMS limits: skip with correct varint structure
            if ftype in (0x10, 0x11, 0x12, 0x13, 0x14, 0x16, 0x17):
                _v, off = dec_varint(payload, off + 1)
                if ftype in (0x11,):
                    _v, off = dec_varint(payload, off)
                continue
            if ftype == 0x18:  # NEW_CONNECTION_ID
                _seq, off = dec_varint(payload, off + 1)
                _rpt, off = dec_varint(payload, off)
                cl = payload[off]
                off += 1 + cl + 16
                continue
            # unknown frame: stop parsing this packet
            break
        if ack_eliciting:
            self._ack_pending[epoch] += 1
            if self._ack_pending[epoch] >= self._ack_every:
                self._ack_due[epoch] = True

    def _on_crypto(self, epoch: int, coff: int, data: bytes) -> None:
        if self.tls is None:
            return  # PSK profile: no handshake stream exists
        chunks = self._crypto_chunks[epoch]
        chunks[coff] = data
        advanced = True
        while advanced:
            advanced = False
            cur = self._crypto_recv_off[epoch]
            for o in sorted(chunks):
                if o <= cur < o + len(chunks[o]):
                    piece = chunks.pop(o)[cur - o:]
                    try:
                        self.tls.feed(epoch, piece)
                    except HandshakeError as exc:
                        self._events.append(("error", str(exc)))
                        self.close(0x128)
                        return
                    self._crypto_recv_off[epoch] = cur + len(piece)
                    advanced = True
                    break
                if o + len(chunks[o]) <= cur:
                    chunks.pop(o)
                    advanced = True
                    break
        self._after_tls()

    def _after_tls(self) -> None:
        if (self.tls.handshake_secrets
                and self._keys[EPOCH_HANDSHAKE][0] is None):
            c, s = self.tls.handshake_secrets
            ck, sk = Keys(c), Keys(s)
            self._keys[EPOCH_HANDSHAKE] = (
                (sk, ck) if self.is_server else (ck, sk)
            )
        if (self.tls.app_secrets
                and self._keys[EPOCH_APP][0] is None):
            c, s = self.tls.app_secrets
            ck, sk = Keys(c), Keys(s)
            self._keys[EPOCH_APP] = (
                (sk, ck) if self.is_server else (ck, sk)
            )
        if self.tls.complete and not self.handshake_complete:
            self.handshake_complete = True
            self._events.append(("handshake_complete",))

    def _on_stream(self, ftype: int, payload: bytes, off: int) -> int:
        has_off = bool(ftype & 0x04)
        has_len = bool(ftype & 0x02)
        fin = bool(ftype & 0x01)
        sid, off = dec_varint(payload, off + 1)
        soff = 0
        if has_off:
            soff, off = dec_varint(payload, off)
        if has_len:
            slen, off = dec_varint(payload, off)
        else:
            slen = len(payload) - off
        data = payload[off:off + slen]
        off += slen
        st = self._streams_in.setdefault(sid, _RecvStream())
        st.chunks[soff] = data
        if fin:
            st.fin_at = soff + slen
        # deliver the contiguous prefix
        out = b""
        advanced = True
        while advanced:
            advanced = False
            for o in sorted(st.chunks):
                chunk = st.chunks[o]
                if o <= st.delivered < o + len(chunk) or (
                    o == st.delivered and not chunk
                ):
                    piece = chunk[st.delivered - o:]
                    out += piece
                    st.delivered += len(piece)
                    st.chunks.pop(o)
                    advanced = True
                    break
                if o + len(chunk) <= st.delivered:
                    st.chunks.pop(o)
                    advanced = True
                    break
        fin_now = st.fin_at is not None and st.delivered >= st.fin_at
        if out or fin_now:
            self._events.append(("stream", sid, out, fin_now))
        return off

    def _on_ack(self, epoch: int, payload: bytes, off: int) -> int:
        ftype = payload[off]
        largest, off = dec_varint(payload, off + 1)
        _delay, off = dec_varint(payload, off)
        count, off = dec_varint(payload, off)
        first, off = dec_varint(payload, off)
        lo = largest - first
        self._on_acked_range(epoch, lo, largest)
        for _ in range(count):
            gap, off = dec_varint(payload, off)
            rng, off = dec_varint(payload, off)
            hi = lo - gap - 2
            lo = hi - rng
            self._on_acked_range(epoch, lo, hi)
        if ftype == F_ACK + 1:  # ECN counts
            for _ in range(3):
                _v, off = dec_varint(payload, off)
        # all ranges of this ACK processed: anything still in flight
        # PACKET_THRESHOLD below the largest acked pn was lost under
        # selective loss — queue its ranges for retransmission (the
        # ensuing _flush sends them)
        self._requeue_lost(epoch, self._spaces[epoch].detect_lost())
        return off

    def _on_acked_range(self, epoch: int, lo: int, hi: int) -> None:
        """Selective ack: advance EXACTLY the ranges the acked packet
        numbers carried (recovery.py records them per packet).  The
        old model treated an ack of the latest pn as cumulative — a
        lost earlier packet's bytes were never retransmitted and the
        receiver wedged until idle timeout."""
        touched = set()
        for pkt in self._spaces[epoch].on_ack_range(lo, hi):
            for sid, soff, send_ in pkt.streams:
                st = self._streams_out.get(sid)
                if st is not None:
                    st.acked_ranges.add(soff, send_)
                    touched.add(sid)
            for sid in pkt.fins:
                st = self._streams_out.get(sid)
                if st is not None:
                    st.fin_acked = True
        for sid in touched:
            st = self._streams_out[sid]
            new_acked = st.acked_ranges.contiguous_from(st.acked)
            if new_acked > st.acked:
                st.acked = new_acked
                if st.acked > st.base:
                    # drop the acked prefix: a long-lived subscriber
                    # must not retain every byte ever delivered to it
                    # (offsets stay absolute; only indexing into
                    # `data` rebases)
                    st.data = st.data[st.acked - st.base:]
                    st.base = st.acked
                st.acked_ranges.prune_below(st.acked)

    # -------------------------------------------------------- sending

    def _flush(self) -> None:
        if self.tls is not None:
            for epoch in (EPOCH_INITIAL, EPOCH_HANDSHAKE, EPOCH_APP):
                self._crypto_out[epoch] += self.tls.take_out(epoch)
        datagram = b""
        for epoch in (EPOCH_INITIAL, EPOCH_HANDSHAKE):
            pkt = self._build_crypto_packet(epoch)
            if pkt:
                datagram += pkt
        app = self._build_app_packet()
        if app:
            datagram += app
        if datagram:
            if self.tls is not None and not self.is_server \
                    and self._pn[EPOCH_HANDSHAKE] == 0 \
                    and len(datagram) < 1200:
                # a client Initial flight must fill 1200 bytes
                datagram += b"\x00" * (1200 - len(datagram))
            self._out_datagrams.append(datagram)

    def _build_crypto_packet(self, epoch: int) -> bytes:
        send, _recv = self._keys[epoch]
        if send is None:
            return b""
        space = self._spaces[epoch]
        frames = b""
        rec = SentPacket()
        if self._ack_due[epoch]:
            frames += self._ack_frame(epoch)
            self._ack_due[epoch] = False
            self._ack_pending[epoch] = 0
        # lost ranges first (exact retransmission), then the new tail
        for off, end in space.take_crypto_retx():
            data = self._crypto_out[epoch][off:end]
            if not data:
                continue
            frames += (bytes([F_CRYPTO]) + enc_varint(off)
                       + enc_varint(len(data)) + data)
            rec.crypto.append((off, off + len(data)))
        pending = self._crypto_out[epoch][self._crypto_sent[epoch]:]
        if pending:
            off = self._crypto_sent[epoch]
            frames += (bytes([F_CRYPTO]) + enc_varint(off)
                       + enc_varint(len(pending)) + pending)
            rec.crypto.append((off, off + len(pending)))
            self._crypto_sent[epoch] = len(self._crypto_out[epoch])
        if not frames:
            return b""
        pkt = self._build_packet(epoch, frames)
        if pkt:
            space.record(self._pn[epoch] - 1, rec)
        return pkt

    @staticmethod
    def _stream_frame(sid: int, off: int, chunk: bytes,
                      fin: bool) -> bytes:
        return (
            bytes([F_STREAM_BASE | 0x04 | 0x02 | (0x01 if fin else 0)])
            + enc_varint(sid) + enc_varint(off)
            + enc_varint(len(chunk)) + chunk
        )

    def _build_app_packet(self) -> bytes:
        send, _ = self._keys[EPOCH_APP]
        if send is None:
            return b""
        space = self._spaces[EPOCH_APP]
        frames = b""
        rec = SentPacket()
        if self._ack_due[EPOCH_APP]:
            frames += self._ack_frame(EPOCH_APP)
            self._ack_due[EPOCH_APP] = False
            self._ack_pending[EPOCH_APP] = 0
        if (self.is_server and self.handshake_complete
                and not self._handshake_done_sent):
            frames += bytes([F_DONE])
            self._handshake_done_sent = True

        def flush_packet() -> None:
            # split across datagrams, recording per-packet carriage
            nonlocal frames, rec
            pkt = self._build_packet(EPOCH_APP, frames)
            if pkt:
                space.record(self._pn[EPOCH_APP] - 1, rec)
                self._out_datagrams.append(pkt)
            frames = b""
            rec = SentPacket()

        if self.handshake_complete:
            max_chunk = self.max_stream_chunk
            for sid, st in self._streams_out.items():
                # 1) lost ranges (selective retransmission), re-checked
                #    against acks that landed after the loss call
                retx, st.retx = st.retx, []
                for lo, hi in retx:
                    for roff, rend in st.acked_ranges.missing_within(
                        lo, hi
                    ):
                        roff = max(roff, st.base)  # below base == acked
                        while roff < rend:
                            chunk = st.data[
                                roff - st.base:
                                min(rend, roff + max_chunk) - st.base
                            ]
                            if not chunk:
                                break
                            frames += self._stream_frame(
                                sid, roff, chunk, False
                            )
                            rec.streams.append(
                                (sid, roff, roff + len(chunk))
                            )
                            roff += len(chunk)
                            if len(frames) > max_chunk:
                                flush_packet()
                # 2) the new tail
                sent = self._streams_sent.get(sid, 0)
                pending = st.data[sent - st.base:]
                send_fin = st.fin and not st.fin_sent
                while pending or send_fin:
                    chunk = pending[:max_chunk]
                    pending = pending[len(chunk):]
                    fin_flag = st.fin and not pending
                    frames += self._stream_frame(
                        sid, sent, chunk, fin_flag
                    )
                    if chunk:
                        rec.streams.append(
                            (sid, sent, sent + len(chunk))
                        )
                    sent += len(chunk)
                    if fin_flag:
                        rec.fins.append(sid)
                        st.fin_sent = True
                        send_fin = False
                    if len(frames) > max_chunk:
                        flush_packet()
                self._streams_sent[sid] = sent
        if not frames:
            return b""
        pkt = self._build_packet(EPOCH_APP, frames)
        if pkt:
            space.record(self._pn[EPOCH_APP] - 1, rec)
        return pkt

    def _ack_frame(self, epoch: int) -> bytes:
        pns = sorted(self._recv_pns[epoch])
        if not pns:
            return b""
        # ranges from largest down
        ranges: List[Tuple[int, int]] = []
        lo = hi = pns[-1]
        for pn in reversed(pns[:-1]):
            if pn == lo - 1:
                lo = pn
            else:
                ranges.append((lo, hi))
                lo = hi = pn
        ranges.append((lo, hi))
        out = (bytes([F_ACK]) + enc_varint(ranges[0][1])
               + enc_varint(0)
               + enc_varint(len(ranges) - 1)
               + enc_varint(ranges[0][1] - ranges[0][0]))
        prev_lo = ranges[0][0]
        for lo, hi in ranges[1:]:
            out += enc_varint(prev_lo - hi - 2)
            out += enc_varint(hi - lo)
            prev_lo = lo
        return out

    def _build_packet(self, epoch: int, frames: bytes) -> bytes:
        send, _ = self._keys[epoch]
        if send is None:
            return b""
        # the header-protection sample starts 4 bytes past the pn
        # offset and needs 16 bytes of ciphertext: pad tiny frames
        # (bare ACK/DONE) with PADDING so every packet is sampleable
        if len(frames) < 4:
            frames = frames + b"\x00" * (4 - len(frames))
        pn = self._pn[epoch]
        self._pn[epoch] += 1
        pn_bytes = struct.pack(">H", pn & 0xFFFF)
        if epoch == EPOCH_APP:
            first = 0x41  # short, key phase 0, 2-byte pn
            header = bytes([first]) + self.dcid + pn_bytes
            pn_off = 1 + len(self.dcid)
        else:
            ptype = 0x00 if epoch == EPOCH_INITIAL else 0x02
            first = 0xC1 | (ptype << 4)  # long, fixed, 2-byte pn
            payload_len = len(frames) + 2 + 16  # pn + tag
            header = (
                bytes([first]) + struct.pack(">I", VERSION_1)
                + bytes([len(self.dcid)]) + self.dcid
                + bytes([len(self.scid)]) + self.scid
            )
            if epoch == EPOCH_INITIAL:
                header += enc_varint(0)  # empty token
            header += enc_varint(payload_len)
            pn_off = len(header)
            header += pn_bytes
        ct = send.aead.encrypt(send.nonce(pn), frames, header)
        pkt = bytearray(header + ct)
        sample = bytes(pkt[pn_off + 4:pn_off + 4 + 16])
        mask = send.hp_mask(sample)
        pkt[0] ^= mask[0] & (0x1F if epoch == EPOCH_APP else 0x0F)
        pkt[pn_off] ^= mask[1]
        pkt[pn_off + 1] ^= mask[2]
        return bytes(pkt)
