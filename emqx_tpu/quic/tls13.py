"""Minimal TLS 1.3 (RFC 8446) handshake core for QUIC.

QUIC embeds the TLS 1.3 handshake in CRYPTO frames and takes its
traffic secrets from the TLS key schedule (RFC 9001).  No Python ssl
integration exists for that (CPython's ssl cannot export handshake
secrets), so this module implements the handshake itself on
`cryptography` primitives, scoped to one ciphersuite and one curve:

  * TLS_AES_128_GCM_SHA256, key exchange x25519,
    signature ecdsa_secp256r1_sha256 (the server cert is an EC P-256
    key; tests mint self-signed certs);
  * full 1-RTT handshake: CH, SH, EE, Cert, CertVerify, Finished both
    ways; QUIC transport parameters ride their extension (0x39);
  * NOT implemented (explicit cuts): PSK/resumption/0-RTT, HRR,
    client certificates, key update, compatibility middlebox layers
    (QUIC forbids them anyway), and certificate-chain VALIDATION on
    the client (the in-repo test client pins by public key instead —
    a production client would verify the chain).

The class is sans-IO: feed handshake bytes per epoch, collect
outgoing handshake bytes per epoch plus the derived secrets; the QUIC
layer does all packetization."""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import os
import struct
from typing import Dict, List, Optional, Tuple

# optional: the TLS handshake needs `cryptography` primitives, but the
# hkdf helpers (pure hashlib) and HandshakeError are used by modules
# that can run without it (the cluster peer transport's PSK profile) —
# importing this module must not require the package
try:
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    HAVE_CRYPTO = True
except ImportError:  # pragma: no cover - environment-dependent
    hashes = serialization = ec = None  # type: ignore
    X25519PrivateKey = X25519PublicKey = None  # type: ignore
    HAVE_CRYPTO = False

# handshake message types
CH, SH, EE, CERT, CV, FIN = 1, 2, 8, 11, 15, 20

TLS_AES_128_GCM_SHA256 = 0x1301
X25519 = 0x001D
ECDSA_SECP256R1_SHA256 = 0x0403

EXT_SNI = 0
EXT_GROUPS = 10
EXT_SIGALGS = 13
EXT_ALPN = 16
EXT_VERSIONS = 43
EXT_KEYSHARE = 51
EXT_QUIC_TP = 0x39

HASHLEN = 32


# ------------------------------------------------------- key schedule

def hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac_mod.new(salt, ikm, hashlib.sha256).digest()


def hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    out = b""
    t = b""
    i = 1
    while len(out) < length:
        t = hmac_mod.new(prk, t + info + bytes([i]),
                         hashlib.sha256).digest()
        out += t
        i += 1
    return out[:length]


def hkdf_expand_label(secret: bytes, label: str, context: bytes,
                      length: int) -> bytes:
    lab = b"tls13 " + label.encode()
    info = (struct.pack(">H", length) + bytes([len(lab)]) + lab
            + bytes([len(context)]) + context)
    return hkdf_expand(secret, info, length)


def derive_secret(secret: bytes, label: str,
                  transcript_hash: bytes) -> bytes:
    return hkdf_expand_label(secret, label, transcript_hash, HASHLEN)


def _hash(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


# --------------------------------------------------------- TLS records

def _hs_msg(mtype: int, body: bytes) -> bytes:
    return bytes([mtype]) + len(body).to_bytes(3, "big") + body


def _ext(etype: int, body: bytes) -> bytes:
    return struct.pack(">HH", etype, len(body)) + body


def _parse_exts(data: bytes) -> Dict[int, bytes]:
    out: Dict[int, bytes] = {}
    off = 0
    while off + 4 <= len(data):
        et, ln = struct.unpack_from(">HH", data, off)
        off += 4
        out[et] = data[off:off + ln]
        off += ln
    return out


class HandshakeError(Exception):
    pass


class Tls13:
    """One endpoint's handshake state.  Epochs: 0=initial (cleartext
    CRYPTO), 2=handshake, 3=application — matching the QUIC packet
    number spaces that carry them."""

    def __init__(
        self,
        is_server: bool,
        alpn: str = "mqtt",
        quic_tp: bytes = b"",
        cert_der: Optional[bytes] = None,
        key=None,  # ec.EllipticCurvePrivateKey (server)
        server_name: str = "localhost",
    ) -> None:
        if not HAVE_CRYPTO:
            raise ImportError(
                "the TLS 1.3 handshake requires the `cryptography` "
                "package (the QUIC cluster transport's PSK profile "
                "does not)"
            )
        self.is_server = is_server
        self.alpn = alpn
        self.quic_tp = quic_tp
        self.cert_der = cert_der
        self.key = key
        self.server_name = server_name
        self.kx = X25519PrivateKey.generate()
        self.transcript = b""
        self.out: Dict[int, List[bytes]] = {0: [], 2: [], 3: []}
        self.handshake_secrets: Optional[Tuple[bytes, bytes]] = None
        self.app_secrets: Optional[Tuple[bytes, bytes]] = None
        self.peer_quic_tp: Optional[bytes] = None
        self.peer_cert_der: Optional[bytes] = None
        self.negotiated_alpn: Optional[str] = None
        self.complete = False
        self._buf: Dict[int, bytes] = {0: b"", 2: b"", 3: b""}
        self._master: Optional[bytes] = None
        self._client_hs_traffic: Optional[bytes] = None
        self._server_hs_traffic: Optional[bytes] = None

    # ------------------------------------------------------- client

    def client_hello(self) -> None:
        assert not self.is_server
        legacy_session = os.urandom(32)
        pub = self.kx.public_key().public_bytes(
            serialization.Encoding.Raw,
            serialization.PublicFormat.Raw,
        )
        sni = self.server_name.encode()
        exts = b"".join([
            _ext(EXT_SNI, struct.pack(
                ">HBH", len(sni) + 3, 0, len(sni)) + sni),
            _ext(EXT_VERSIONS, b"\x02\x03\x04"),
            _ext(EXT_GROUPS, struct.pack(">HH", 2, X25519)),
            _ext(EXT_SIGALGS, struct.pack(
                ">HH", 2, ECDSA_SECP256R1_SHA256)),
            _ext(EXT_ALPN, struct.pack(
                ">HB", len(self.alpn) + 1, len(self.alpn))
                + self.alpn.encode()),
            _ext(EXT_KEYSHARE, struct.pack(
                ">HHH", len(pub) + 4, X25519, len(pub)) + pub),
            _ext(EXT_QUIC_TP, self.quic_tp),
        ])
        body = (
            b"\x03\x03" + os.urandom(32)
            + bytes([len(legacy_session)]) + legacy_session
            + struct.pack(">H", 2)
            + struct.pack(">H", TLS_AES_128_GCM_SHA256)
            + b"\x01\x00"  # legacy compression: null
            + struct.pack(">H", len(exts)) + exts
        )
        msg = _hs_msg(CH, body)
        self.transcript += msg
        self.out[0].append(msg)

    # -------------------------------------------------------- feeding

    def feed(self, epoch: int, data: bytes) -> None:
        """Consume handshake bytes arriving at an epoch; drives the
        state machine and fills `out` / secrets."""
        self._buf[epoch] += data
        while True:
            buf = self._buf[epoch]
            if len(buf) < 4:
                return
            ln = int.from_bytes(buf[1:4], "big")
            if len(buf) < 4 + ln:
                return
            msg, self._buf[epoch] = buf[:4 + ln], buf[4 + ln:]
            self._on_message(epoch, msg[0], msg[4:], msg)

    # ------------------------------------------------- state machine

    def _on_message(self, epoch: int, mtype: int, body: bytes,
                    raw: bytes) -> None:
        if self.is_server:
            if mtype == CH and epoch == 0:
                self._server_on_client_hello(body, raw)
            elif mtype == FIN and epoch == 2:
                self._server_on_finished(body, raw)
            else:
                raise HandshakeError(
                    f"server: unexpected msg {mtype} at epoch {epoch}"
                )
            return
        if mtype == SH and epoch == 0:
            self._client_on_server_hello(body, raw)
        elif mtype == EE and epoch == 2:
            self.transcript += raw
            exts = _parse_exts(body[2:])
            self.peer_quic_tp = exts.get(EXT_QUIC_TP)
            if EXT_ALPN in exts:
                alpn = exts[EXT_ALPN]
                self.negotiated_alpn = alpn[3:].decode()
        elif mtype == CERT and epoch == 2:
            self.transcript += raw
            # certificate_request_context (1B len) + cert list
            off = 1 + body[0]
            off += 3  # list length
            cert_len = int.from_bytes(body[off:off + 3], "big")
            self.peer_cert_der = body[off + 3:off + 3 + cert_len]
        elif mtype == CV and epoch == 2:
            self._client_on_cert_verify(body, raw)
        elif mtype == FIN and epoch == 2:
            self._client_on_finished(body, raw)
        else:
            raise HandshakeError(
                f"client: unexpected msg {mtype} at epoch {epoch}"
            )

    # -------------------------------------------------- server flight

    def _server_on_client_hello(self, body: bytes, raw: bytes) -> None:
        self.transcript += raw
        off = 34  # legacy_version(2) + random(32)
        sess_len = body[off]
        off += 1 + sess_len
        (n_suites,) = struct.unpack_from(">H", body, off)
        suites = body[off + 2:off + 2 + n_suites]
        off += 2 + n_suites
        off += 1 + body[off]  # compression
        (ext_len,) = struct.unpack_from(">H", body, off)
        exts = _parse_exts(body[off + 2:off + 2 + ext_len])
        if struct.pack(">H", TLS_AES_128_GCM_SHA256) not in [
            suites[i:i + 2] for i in range(0, len(suites), 2)
        ]:
            raise HandshakeError("no common ciphersuite")
        ks = exts.get(EXT_KEYSHARE)
        if ks is None:
            raise HandshakeError("no key_share")
        # client shares: 2B list len, then (group, len, key)*
        koff = 2
        client_pub = None
        while koff + 4 <= len(ks):
            grp, kl = struct.unpack_from(">HH", ks, koff)
            if grp == X25519:
                client_pub = ks[koff + 4:koff + 4 + kl]
                break
            koff += 4 + kl
        if client_pub is None:
            raise HandshakeError("no x25519 share")
        if EXT_ALPN in exts:
            alpn = exts[EXT_ALPN]
            self.negotiated_alpn = alpn[3:].decode()
        self.peer_quic_tp = exts.get(EXT_QUIC_TP)
        shared = self.kx.exchange(
            X25519PublicKey.from_public_bytes(client_pub)
        )
        # ServerHello
        my_pub = self.kx.public_key().public_bytes(
            serialization.Encoding.Raw,
            serialization.PublicFormat.Raw,
        )
        sh_exts = b"".join([
            _ext(EXT_VERSIONS, b"\x03\x04"),
            _ext(EXT_KEYSHARE, struct.pack(
                ">HH", X25519, len(my_pub)) + my_pub),
        ])
        sh = _hs_msg(SH, (
            b"\x03\x03" + os.urandom(32)
            + bytes([sess_len]) + body[35:35 + sess_len]
            + struct.pack(">H", TLS_AES_128_GCM_SHA256)
            + b"\x00"
            + struct.pack(">H", len(sh_exts)) + sh_exts
        ))
        self.transcript += sh
        self.out[0].append(sh)
        self._derive_handshake(shared)
        # EncryptedExtensions
        ee_exts = _ext(EXT_QUIC_TP, self.quic_tp)
        if self.negotiated_alpn:
            a = self.negotiated_alpn.encode()
            ee_exts += _ext(EXT_ALPN, struct.pack(
                ">HB", len(a) + 1, len(a)) + a)
        ee = _hs_msg(EE, struct.pack(">H", len(ee_exts)) + ee_exts)
        self.transcript += ee
        self.out[2].append(ee)
        # Certificate
        cert_entry = (
            len(self.cert_der).to_bytes(3, "big") + self.cert_der
            + struct.pack(">H", 0)  # no per-cert extensions
        )
        cert = _hs_msg(CERT, (
            b"\x00" + len(cert_entry).to_bytes(3, "big") + cert_entry
        ))
        self.transcript += cert
        self.out[2].append(cert)
        # CertificateVerify
        to_sign = (b"\x20" * 64
                   + b"TLS 1.3, server CertificateVerify\x00"
                   + _hash(self.transcript))
        sig = self.key.sign(to_sign, ec.ECDSA(hashes.SHA256()))
        cv = _hs_msg(CV, struct.pack(
            ">HH", ECDSA_SECP256R1_SHA256, len(sig)) + sig)
        self.transcript += cv
        self.out[2].append(cv)
        # Finished
        fin_key = hkdf_expand_label(
            self._server_hs_traffic, "finished", b"", HASHLEN
        )
        verify = hmac_mod.new(
            fin_key, _hash(self.transcript), hashlib.sha256
        ).digest()
        fin = _hs_msg(FIN, verify)
        self.transcript += fin
        self.out[2].append(fin)
        self._derive_app()

    def _server_on_finished(self, body: bytes, raw: bytes) -> None:
        fin_key = hkdf_expand_label(
            self._client_hs_traffic, "finished", b"", HASHLEN
        )
        want = hmac_mod.new(
            fin_key, _hash(self.transcript), hashlib.sha256
        ).digest()
        if not hmac_mod.compare_digest(want, body):
            raise HandshakeError("client Finished mismatch")
        self.transcript += raw
        self.complete = True

    # -------------------------------------------------- client flight

    def _client_on_server_hello(self, body: bytes, raw: bytes) -> None:
        self.transcript += raw
        off = 34
        off += 1 + body[34]  # session id echo
        (suite,) = struct.unpack_from(">H", body, off)
        if suite != TLS_AES_128_GCM_SHA256:
            raise HandshakeError(f"suite {suite:#x}")
        off += 2 + 1  # compression
        (ext_len,) = struct.unpack_from(">H", body, off)
        exts = _parse_exts(body[off + 2:off + 2 + ext_len])
        ks = exts.get(EXT_KEYSHARE)
        if ks is None:
            raise HandshakeError("SH without key_share")
        grp, kl = struct.unpack_from(">HH", ks, 0)
        if grp != X25519:
            raise HandshakeError("SH group")
        server_pub = ks[4:4 + kl]
        shared = self.kx.exchange(
            X25519PublicKey.from_public_bytes(server_pub)
        )
        self._derive_handshake(shared)

    def _client_on_cert_verify(self, body: bytes, raw: bytes) -> None:
        (alg, slen) = struct.unpack_from(">HH", body, 0)
        sig = body[4:4 + slen]
        if alg != ECDSA_SECP256R1_SHA256:
            raise HandshakeError(f"sig alg {alg:#x}")
        to_sign = (b"\x20" * 64
                   + b"TLS 1.3, server CertificateVerify\x00"
                   + _hash(self.transcript))
        from cryptography import x509

        cert = x509.load_der_x509_certificate(self.peer_cert_der)
        cert.public_key().verify(
            sig, to_sign, ec.ECDSA(hashes.SHA256())
        )
        self.transcript += raw

    def _client_on_finished(self, body: bytes, raw: bytes) -> None:
        fin_key = hkdf_expand_label(
            self._server_hs_traffic, "finished", b"", HASHLEN
        )
        want = hmac_mod.new(
            fin_key, _hash(self.transcript), hashlib.sha256
        ).digest()
        if not hmac_mod.compare_digest(want, body):
            raise HandshakeError("server Finished mismatch")
        self.transcript += raw
        self._derive_app()
        # client Finished (epoch 2)
        my_fin_key = hkdf_expand_label(
            self._client_hs_traffic, "finished", b"", HASHLEN
        )
        verify = hmac_mod.new(
            my_fin_key, _hash(self.transcript), hashlib.sha256
        ).digest()
        fin = _hs_msg(FIN, verify)
        self.transcript += fin
        self.out[2].append(fin)
        self.complete = True

    # ------------------------------------------------------- schedule

    def _derive_handshake(self, shared: bytes) -> None:
        early = hkdf_extract(b"\x00" * HASHLEN, b"\x00" * HASHLEN)
        derived = derive_secret(early, "derived", _hash(b""))
        hs = hkdf_extract(derived, shared)
        th = _hash(self.transcript)
        self._client_hs_traffic = derive_secret(hs, "c hs traffic", th)
        self._server_hs_traffic = derive_secret(hs, "s hs traffic", th)
        self.handshake_secrets = (
            self._client_hs_traffic, self._server_hs_traffic
        )
        self._master = hkdf_extract(
            derive_secret(hs, "derived", _hash(b"")), b"\x00" * HASHLEN
        )

    def _derive_app(self) -> None:
        th = _hash(self.transcript)
        self.app_secrets = (
            derive_secret(self._master, "c ap traffic", th),
            derive_secret(self._master, "s ap traffic", th),
        )

    def take_out(self, epoch: int) -> bytes:
        msgs, self.out[epoch] = self.out[epoch], []
        return b"".join(msgs)
