"""Failpoint injection: deterministic, named fault seams.

The chaos-engineering counterpart of `tp.py`'s tracepoints (and the
role FreeBSD/TiKV ``fail::fail_point!`` macros play): production code
marks its real failure seams — cluster frame send/recv, raft RPCs,
replica-store writes, Kafka produce, resource buffer drains, exhook
verdict calls, the engine's device step — with a NAMED evaluation
point, and tests/operators arm those points with an action:

  * ``error``      raise (`FailpointError`, a ConnectionError — the
                   seams treat it exactly like a real transport fault)
  * ``delay``      sleep/await ``delay`` seconds, then proceed
  * ``drop``       the call site discards the unit of work silently
                   (a frame the network ate)
  * ``duplicate``  the call site performs the work twice (at-least-
                   once delivery duplication)
  * ``panic``      raise `FailpointPanic` (BaseException: flows
                   through ``except Exception`` recovery the way a
                   process death would)

Every point supports a firing probability with a SEEDED per-point RNG
(chaos runs reproduce bit-for-bit), hit-count windows (``after`` skips
the first N hits, ``times`` caps total fires), and an optional ``match``
substring filter against the call-site key (e.g. partition only the
traffic crossing ``"n0"``).

Zero-overhead when disabled: call sites guard with the module-level
``enabled`` bool (one attribute load per operation — the tp.py
philosophy), and `evaluate` itself short-circuits on the same flag, so
an unarmed broker's hot paths are behavior-identical with the
framework present or absent (tests/test_failpoints.py guards this).

Configuration surfaces:

  * env:   ``EMQX_FAILPOINTS="engine.device_step=error;
            cluster.transport.send=drop,prob=0.3,seed=7"``
            (parsed by `load_env`, called at BrokerServer.start)
  * REST:  ``GET/PUT/DELETE /api/v5/failpoints[/{name}]``
  * ctl:   ``python -m emqx_tpu.ctl failpoints list|set|clear``
"""

from __future__ import annotations

import asyncio
import os
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional

ACTIONS = ("error", "delay", "drop", "duplicate", "panic")

# the instrumented seams (kept in sync with the call sites; the guard
# test iterates this list to prove each is a no-op when disabled)
SEAMS = (
    "engine.device_step",
    "dispatch.decide.device",
    "dispatch.rules.device",
    "cluster.transport.send",
    "cluster.transport.recv",
    "cluster.raft.rpc",
    "ds.replication.store",
    "kafka.produce",
    "resource.buffer.query",
    "exhook.call",
    "ds.beamformer.poll",
    "cluster.link.forward",
    "s3.request",
    "ds.replay.read",
    "ds.store.append",
    "ds.store.sync",
    "ds.meta.write",
    "session.resume.commit",
    "cluster.quic.send",
    "cluster.quic.recv",
    "cluster.forward.ack",
    "olp.sample",
    "olp.shed",
    "ds.journal.append",
    "ds.gc.reclaim",
    "multicore.ring.submit",
    "multicore.ring.complete",
    "multicore.service.restart",
    "resource.batch.flush",
    "bridge.mqtt.send",
)

enabled = False  # fast-path gate: disabled brokers pay one bool check

# last fires (wall_ts, name, action, key): the lifecycle tracer reads
# this ring to attach in-window failpoint hits as span events (chaos
# attribution); deque.append is atomic, so no lock is needed
RECENT_FIRES: "deque" = deque(maxlen=256)


def fires_since(ts: float):
    """Fires strictly newer than ``ts``, oldest first — the flight
    recorder drains these at its 1 Hz tick so injected faults land in
    the black-box timeline next to their consequences."""
    return [f for f in list(RECENT_FIRES) if f[0] > ts]


class FailpointError(ConnectionError):
    """Injected failure.  Subclasses ConnectionError so transport-layer
    seams recover through their real ``except (ConnectionError, ...)``
    paths — the injection exercises production error handling, not a
    parallel test-only one."""

    def code(self) -> str:  # grpc.RpcError duck-typing (exhook seam)
        return "FAILPOINT"


class FailpointPanic(BaseException):
    """Injected process-death stand-in: BaseException, so ordinary
    ``except Exception`` recovery does NOT absorb it."""


class _Point:
    __slots__ = ("name", "action", "prob", "delay", "after", "times",
                 "match", "exc", "rng", "seed", "hits", "fires")

    def __init__(self, name: str, action: str, prob: float, delay: float,
                 after: int, times: Optional[int], match: Optional[str],
                 exc: Optional[BaseException], seed: Optional[int]):
        if action not in ACTIONS:
            raise ValueError(f"unknown failpoint action {action!r}")
        self.name = name
        self.action = action
        self.prob = float(prob)
        self.delay = float(delay)
        self.after = int(after)
        self.times = None if times is None else int(times)
        self.match = match
        self.exc = exc
        self.seed = seed
        self.rng = random.Random(seed)
        self.hits = 0
        self.fires = 0

    def info(self) -> Dict:
        return {
            "name": self.name,
            "action": self.action,
            "prob": self.prob,
            "delay": self.delay,
            "after": self.after,
            "times": self.times,
            "match": self.match,
            "seed": self.seed,
            "hits": self.hits,
            "fires": self.fires,
        }


class FailpointRegistry:
    """Named injection points; one process-wide instance below."""

    def __init__(self) -> None:
        self._points: Dict[str, _Point] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------ configure

    def configure(
        self,
        name: str,
        action: str,
        prob: float = 1.0,
        delay: float = 0.05,
        after: int = 0,
        times: Optional[int] = None,
        match: Optional[str] = None,
        exc: Optional[BaseException] = None,
        seed: Optional[int] = None,
    ) -> Dict:
        """Arm (or re-arm, resetting counters) one failpoint."""
        point = _Point(name, action, prob, delay, after, times, match,
                       exc, seed)
        with self._lock:
            self._points[name] = point
            self._sync_enabled()
        return point.info()

    def clear(self, name: Optional[str] = None) -> bool:
        with self._lock:
            if name is None:
                had = bool(self._points)
                self._points.clear()
            else:
                had = self._points.pop(name, None) is not None
            self._sync_enabled()
        return had

    def _sync_enabled(self) -> None:
        global enabled
        enabled = bool(self._points)

    def list(self) -> List[Dict]:
        with self._lock:
            return [p.info() for p in self._points.values()]

    # ------------------------------------------------------- evaluate

    def _decide(self, name: str, key: Optional[str]):
        """Count the hit and pick the action tuple (or None) under the
        lock; the sleep/raise happens in the caller, outside it."""
        with self._lock:
            p = self._points.get(name)
            if p is None:
                return None
            if p.match is not None and (
                key is None or p.match not in str(key)
            ):
                return None
            p.hits += 1
            if p.hits <= p.after:
                return None
            if p.times is not None and p.fires >= p.times:
                return None
            if p.prob < 1.0 and p.rng.random() >= p.prob:
                return None
            p.fires += 1
            if p.action == "delay":
                return ("delay", p.delay)
            if p.action == "error":
                return ("error", p.exc or FailpointError(
                    f"failpoint {name}"
                ))
            if p.action == "panic":
                return ("panic",)
            return (p.action,)  # drop / duplicate

    def evaluate(self, name: str, key: Optional[str] = None):
        """Sync seam entry: returns None (proceed), ``"drop"`` or
        ``"duplicate"`` (the call site implements those), sleeps
        through a delay, raises on error/panic."""
        if not enabled:
            return None
        d = self._decide(name, key)
        if d is None:
            return None
        RECENT_FIRES.append((time.time(), name, d[0], key))
        if d[0] == "delay":
            time.sleep(d[1])
            return None
        if d[0] == "error":
            raise d[1]
        if d[0] == "panic":
            raise FailpointPanic(name)
        return d[0]

    async def evaluate_async(self, name: str, key: Optional[str] = None):
        """`evaluate` for coroutine seams: delays await instead of
        blocking the event loop."""
        if not enabled:
            return None
        d = self._decide(name, key)
        if d is None:
            return None
        RECENT_FIRES.append((time.time(), name, d[0], key))
        if d[0] == "delay":
            await asyncio.sleep(d[1])
            return None
        if d[0] == "error":
            raise d[1]
        if d[0] == "panic":
            raise FailpointPanic(name)
        return d[0]


_REG = FailpointRegistry()

configure = _REG.configure
clear = _REG.clear
evaluate = _REG.evaluate
evaluate_async = _REG.evaluate_async
list_points = _REG.list


# ------------------------------------------------------------------ env

def parse_spec(spec: str) -> List[Dict]:
    """``name=action[,k=v...]`` entries separated by ``;``.  Keys:
    prob, delay (floats), after, times, seed (ints), match (string)."""
    out: List[Dict] = []
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        head, _, tail = entry.partition("=")
        name = head.strip()
        parts = [s.strip() for s in tail.split(",") if s.strip()]
        if not name or not parts:
            raise ValueError(f"bad failpoint spec entry: {entry!r}")
        kw: Dict = {"name": name, "action": parts[0]}
        for kv in parts[1:]:
            k, _, v = kv.partition("=")
            k = k.strip()
            v = v.strip()
            if k in ("prob", "delay"):
                kw[k] = float(v)
            elif k in ("after", "times", "seed"):
                kw[k] = int(v)
            elif k == "match":
                kw[k] = v
            else:
                raise ValueError(f"unknown failpoint option {k!r}")
        out.append(kw)
    return out


def load_env(env: Optional[str] = None) -> int:
    """Arm failpoints from ``EMQX_FAILPOINTS`` (or an explicit spec);
    returns how many were configured.  Unset/empty is a no-op, so
    production boots stay untouched."""
    spec = os.environ.get("EMQX_FAILPOINTS", "") if env is None else env
    if not spec:
        return 0
    n = 0
    for kw in parse_spec(spec):
        configure(**kw)
        n += 1
    return n
