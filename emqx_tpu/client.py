"""Async MQTT client — the `emqtt` analogue, on this package's codec.

Used by the MQTT bridge (and available standalone): connect with
auto-reconnect + resubscribe, QoS 0/1/2 publish with pipelined acks,
subscription callbacks, keepalive pings.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Awaitable, Callable, Dict, List, Optional, Tuple

from .aio import cancel_and_wait
from .codec import mqtt as C
from .message import Message

log = logging.getLogger("emqx_tpu.client")

OnMessage = Callable[[Message], Optional[Awaitable[None]]]


class MqttClient:
    def __init__(
        self,
        host: str,
        port: int,
        client_id: str,
        username: Optional[str] = None,
        password: Optional[bytes] = None,
        keepalive: int = 60,
        clean_start: bool = True,
        reconnect_min: float = 0.2,
        reconnect_max: float = 10.0,
        version: int = C.MQTT_V5,
    ) -> None:
        self.host, self.port = host, port
        self.client_id = client_id
        self.username = username
        self.password = password
        self.keepalive = keepalive
        self.clean_start = clean_start
        self.reconnect_min = reconnect_min
        self.reconnect_max = reconnect_max
        self.version = version
        self.on_message: Optional[OnMessage] = None
        # fired after every successful CONNACK + resubscribe (link
        # agents push a full state resync here)
        self.on_connect = None
        self.connected = asyncio.Event()

        self._subs: Dict[str, int] = {}  # filter -> qos (for resubscribe)
        self._pids = itertools.count(1)
        self._acks: Dict[int, asyncio.Future] = {}
        self._writer: Optional[asyncio.StreamWriter] = None
        self._task: Optional[asyncio.Task] = None
        self._stopping = False

    # ------------------------------------------------------- lifecycle

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stopping = True
        if self._writer is not None and not self._writer.is_closing():
            try:
                self._writer.write(C.serialize(C.Disconnect(), self.version))
                await self._writer.drain()
            except ConnectionError:
                pass
            self._writer.close()
        if self._task is not None:
            await cancel_and_wait(self._task)
            self._task = None

    # ------------------------------------------------------- main loop

    async def _run(self) -> None:
        backoff = self.reconnect_min
        while not self._stopping:
            try:
                await self._session()
                backoff = self.reconnect_min
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                log.debug("mqtt client %s: %s", self.client_id, exc)
            self.connected.clear()
            for fut in self._acks.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("link lost"))
            self._acks.clear()
            if self._stopping:
                return
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, self.reconnect_max)

    async def _session(self) -> None:
        reader, writer = await asyncio.open_connection(self.host, self.port)
        self._writer = writer
        parser = C.StreamParser(version=self.version)
        writer.write(
            C.serialize(
                C.Connect(
                    client_id=self.client_id,
                    proto_ver=self.version,
                    clean_start=self.clean_start,
                    keepalive=self.keepalive,
                    username=self.username,
                    password=self.password,
                ),
                self.version,
            )
        )
        await writer.drain()
        ping_task: Optional[asyncio.Task] = None
        try:
            while True:
                data = await reader.read(1 << 16)
                if not data:
                    raise ConnectionError("server closed")
                for pkt in parser.feed(data):
                    if pkt.type == C.CONNACK:
                        if pkt.reason_code != 0:
                            raise ConnectionError(
                                f"connect refused rc={pkt.reason_code:#x}"
                            )
                        self.connected.set()
                        ping_task = asyncio.get_running_loop().create_task(
                            self._pinger(writer)
                        )
                        await self._resubscribe(writer)
                        if self.on_connect is not None:
                            try:
                                self.on_connect()
                            except Exception:
                                log.exception("on_connect callback failed")
                    elif pkt.type == C.PUBLISH:
                        await self._incoming(pkt, writer)
                    elif pkt.type in (C.PUBACK, C.SUBACK, C.UNSUBACK,
                                      C.PUBCOMP):
                        fut = self._acks.pop(pkt.packet_id, None)
                        if fut is not None and not fut.done():
                            fut.set_result(pkt)
                    elif pkt.type == C.PUBREC:
                        writer.write(
                            C.serialize(
                                C.Pubrel(packet_id=pkt.packet_id),
                                self.version,
                            )
                        )
                        await writer.drain()
                    elif pkt.type == C.PUBREL:
                        # inbound QoS2 completion (receiver side)
                        writer.write(
                            C.serialize(
                                C.Pubcomp(packet_id=pkt.packet_id),
                                self.version,
                            )
                        )
                        await writer.drain()
                    elif pkt.type == C.DISCONNECT:
                        raise ConnectionError("server disconnect")
                await writer.drain()
        finally:
            if ping_task is not None:
                ping_task.cancel()
            if not writer.is_closing():
                writer.close()
            self._writer = None

    async def _pinger(self, writer: asyncio.StreamWriter) -> None:
        interval = max(self.keepalive * 0.5, 1.0)
        while True:
            await asyncio.sleep(interval)
            if writer.is_closing():
                return
            writer.write(C.serialize(C.Pingreq(), self.version))
            await writer.drain()

    async def _incoming(
        self, pkt: "C.Publish", writer: asyncio.StreamWriter
    ) -> None:
        if pkt.qos == 1:
            writer.write(
                C.serialize(C.Puback(packet_id=pkt.packet_id), self.version)
            )
        elif pkt.qos == 2:
            writer.write(
                C.serialize(C.Pubrec(packet_id=pkt.packet_id), self.version)
            )
        if self.on_message is not None:
            msg = Message(
                topic=pkt.topic,
                payload=pkt.payload,
                qos=pkt.qos,
                retain=pkt.retain,
                properties=dict(pkt.properties),
            )
            out = self.on_message(msg)
            if asyncio.iscoroutine(out):
                await out

    async def _resubscribe(self, writer: asyncio.StreamWriter) -> None:
        if not self._subs:
            return
        pid = next(self._pids) % 65535 or 1
        subs = [
            C.Subscription(topic_filter=f, qos=q)
            for f, q in self._subs.items()
        ]
        writer.write(
            C.serialize(
                C.Subscribe(packet_id=pid, subscriptions=subs), self.version
            )
        )
        await writer.drain()

    # ------------------------------------------------------------- api

    async def _request(self, make_packet, timeout: float = 10.0) -> None:
        """Allocate a packet id, send ``make_packet(pid)``, await the
        matching ack — the one place the ack protocol lives."""
        pid = next(self._pids) % 65535 or 1
        fut = asyncio.get_running_loop().create_future()
        self._acks[pid] = fut
        self._writer.write(C.serialize(make_packet(pid), self.version))
        await self._writer.drain()
        await asyncio.wait_for(fut, timeout)

    async def subscribe(self, flt: str, qos: int = 0) -> None:
        self._subs[flt] = qos
        if self.connected.is_set() and self._writer is not None:
            await self._request(
                lambda pid: C.Subscribe(
                    packet_id=pid,
                    subscriptions=[C.Subscription(topic_filter=flt, qos=qos)],
                )
            )

    async def unsubscribe(self, flt: str) -> None:
        self._subs.pop(flt, None)
        if self.connected.is_set() and self._writer is not None:
            await self._request(
                lambda pid: C.Unsubscribe(packet_id=pid, topic_filters=[flt])
            )

    async def publish(
        self,
        topic: str,
        payload: bytes,
        qos: int = 0,
        retain: bool = False,
        timeout: float = 10.0,
    ) -> None:
        """Publish; for QoS>0 waits for the final ack.  Raises
        ConnectionError when the link is down (callers buffer/retry —
        the bridge's BufferWorker does exactly that)."""
        if not self.connected.is_set() or self._writer is None:
            raise ConnectionError("not connected")
        if qos > 0:
            await self._request(
                lambda pid: C.Publish(
                    topic=topic, payload=payload, qos=qos,
                    retain=retain, packet_id=pid,
                ),
                timeout,
            )
            return
        self._writer.write(
            C.serialize(
                C.Publish(topic=topic, payload=payload, qos=0, retain=retain),
                self.version,
            )
        )
        await self._writer.drain()
