"""Additional authentication backends: pbkdf2 store, JWT, HTTP.

The per-backend provider apps of the reference
(/root/reference/apps/emqx_auth_jwt, emqx_auth_http;
password hashing per apps/emqx_auth/src/emqx_authn/ hash options):

  * ``Pbkdf2Authenticator`` — username/password with PBKDF2-HMAC
    (stdlib ``hashlib.pbkdf2_hmac``; bcrypt has no NIF here, pbkdf2 is
    the supported strong hash).
  * ``JwtAuthenticator`` — HS256 JWTs carried in the password field,
    verified with stdlib hmac (no external jwt lib in this
    environment); checks exp/nbf and optional required claims, honors
    an ``is_superuser`` claim.
  * ``HttpAuthenticator`` — asks an external HTTP service; asynchronous
    (aiohttp) and therefore only usable on the deferred connect path
    (`AccessControl.authenticate_async`), never blocking the loop.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import time
from typing import Any, Dict, Optional, Tuple

from .access import ALLOW, DENY, IGNORE, Authenticator, ClientInfo


class Pbkdf2Authenticator(Authenticator):
    """Username/password store hashed with PBKDF2-HMAC-SHA256."""

    def __init__(self, iterations: int = 50_000) -> None:
        self.iterations = iterations
        self._users: Dict[str, Tuple[bytes, bytes, bool]] = {}

    def add_user(
        self, username: str, password: str, is_superuser: bool = False
    ) -> None:
        salt = os.urandom(16)
        digest = hashlib.pbkdf2_hmac(
            "sha256", password.encode(), salt, self.iterations
        )
        self._users[username] = (salt, digest, is_superuser)

    def authenticate(self, client: ClientInfo):
        if client.username is None:
            return IGNORE, {}
        entry = self._users.get(client.username)
        if entry is None:
            return IGNORE, {}
        salt, digest, is_superuser = entry
        given = hashlib.pbkdf2_hmac(
            "sha256", client.password or b"", salt, self.iterations
        )
        if hmac.compare_digest(given, digest):
            return ALLOW, {"is_superuser": is_superuser}
        return DENY, {}


def _b64url_decode(part: str) -> bytes:
    return base64.urlsafe_b64decode(part + "=" * (-len(part) % 4))


class JwtAuthenticator(Authenticator):
    """HS256 JWT in the password field (the emqx_auth_jwt core mode)."""

    def __init__(
        self,
        secret: bytes,
        required_claims: Optional[Dict[str, Any]] = None,
        leeway: float = 5.0,
    ) -> None:
        self.secret = secret
        self.required_claims = dict(required_claims or {})
        self.leeway = leeway

    def _verify(self, token: str) -> Optional[Dict[str, Any]]:
        try:
            head_b64, body_b64, sig_b64 = token.split(".")
            header = json.loads(_b64url_decode(head_b64))
            if header.get("alg") != "HS256":
                return None
            expect = hmac.new(
                self.secret,
                f"{head_b64}.{body_b64}".encode(),
                hashlib.sha256,
            ).digest()
            if not hmac.compare_digest(expect, _b64url_decode(sig_b64)):
                return None
            return json.loads(_b64url_decode(body_b64))
        except (ValueError, json.JSONDecodeError):
            return None

    def authenticate(self, client: ClientInfo):
        if not client.password:
            return IGNORE, {}
        claims = self._verify(client.password.decode("utf-8", "replace"))
        if claims is None:
            return IGNORE, {}  # not a (valid) JWT: let other providers try
        now = time.time()
        exp = claims.get("exp")
        if exp is not None and now > float(exp) + self.leeway:
            return DENY, {}
        nbf = claims.get("nbf")
        if nbf is not None and now < float(nbf) - self.leeway:
            return DENY, {}
        for k, want in self.required_claims.items():
            have = claims.get(k)
            # %c / %u placeholder matching as in the reference verify
            if want == "%c":
                want = client.clientid
            elif want == "%u":
                want = client.username
            if have != want:
                return DENY, {}
        return ALLOW, {"is_superuser": bool(claims.get("is_superuser"))}


def make_jwt(secret: bytes, claims: Dict[str, Any]) -> str:
    """Mint an HS256 JWT (test/tooling helper)."""

    def enc(obj) -> str:
        return (
            base64.urlsafe_b64encode(
                json.dumps(obj, separators=(",", ":")).encode()
            )
            .rstrip(b"=")
            .decode()
        )

    head, body = enc({"alg": "HS256", "typ": "JWT"}), enc(claims)
    sig = (
        base64.urlsafe_b64encode(
            hmac.new(secret, f"{head}.{body}".encode(), hashlib.sha256).digest()
        )
        .rstrip(b"=")
        .decode()
    )
    return f"{head}.{body}.{sig}"


class HttpAuthenticator(Authenticator):
    """POSTs credentials to an HTTP service (emqx_auth_http).  Response:
    200 with {"result": "allow"|"deny"|"ignore", "is_superuser": bool};
    any error => ignore (fall through the chain).  Async-only."""

    is_async = True

    def __init__(
        self, url: str, timeout: float = 5.0, method: str = "POST"
    ) -> None:
        self.url = url
        self.timeout = timeout
        self.method = method
        self._session = None

    def authenticate(self, client: ClientInfo):
        # sync chains skip async providers; the deferred connect path
        # (AccessControl.authenticate_async) awaits authenticate_async
        return IGNORE, {}

    async def authenticate_async(self, client: ClientInfo):
        import aiohttp

        if self._session is None:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout)
            )
        try:
            async with self._session.request(
                self.method,
                self.url,
                json={
                    "clientid": client.clientid,
                    "username": client.username,
                    "password": (client.password or b"").decode(
                        "utf-8", "replace"
                    ),
                    "peerhost": client.peerhost,
                },
            ) as resp:
                if resp.status != 200:
                    return IGNORE, {}
                body = await resp.json()
        except Exception:
            return IGNORE, {}
        result = body.get("result", "ignore")
        if result == ALLOW:
            return ALLOW, {
                "is_superuser": bool(body.get("is_superuser"))
            }
        if result == DENY:
            return DENY, {}
        return IGNORE, {}

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None
