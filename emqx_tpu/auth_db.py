"""Database-backed authentication/authorization providers.

The `emqx_auth_mysql` / `emqx_auth_postgresql` / `emqx_auth_redis`
role (/root/reference/apps/emqx_auth_mysql/src/emqx_authn_mysql.erl,
emqx_authz_mysql.erl and siblings): credentials and ACL rules live in
an operator database, queried per client with placeholder templates
and verified against the reference's full password-hashing suite
(/root/reference/apps/emqx_auth/src/emqx_authn/
emqx_authn_password_hashing.erl — plain/md5/sha/sha256/sha512 with
salt prefix/suffix, pbkdf2, bcrypt).

Three layers:
  * hashing   — `verify_password` implements the suite; bcrypt rides
    the system libxcrypt ($2b$) since no bcrypt NIF exists here.
  * templating — `compile_query` turns ``${username}``-style
    placeholders (and legacy ``%u``/``%c``) into PREPARED-STATEMENT
    parameters, the reference's injection-safety approach
    (emqx_auth_template.erl): client values never splice into SQL.
  * providers — `SqlAuthenticator`/`SqlAuthorizer` and
    `RedisAuthenticator`/`RedisAuthorizer` speak to a minimal
    connector interface (`SqlConnector.query` / `RedisConnector.cmd`,
    the ecpool role); concrete aiomysql/asyncpg/redis connectors are
    gated on their drivers being installed, and tests drive the
    providers through fakes.
"""

from __future__ import annotations

import hashlib
import hmac
import logging
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import topic as T
from .access import ALLOW, DENY, IGNORE, Authenticator, ClientInfo

log = logging.getLogger("emqx_tpu.auth_db")


# --------------------------------------------------------------- hashing

def _crypt():
    """The stdlib crypt module (deprecated, removed in 3.13 — by then
    switch to the `bcrypt` wheel or a ctypes libxcrypt binding)."""
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        import crypt

    return crypt


def _bcrypt_verify(password: str, stored: str) -> bool:
    """bcrypt via the platform libxcrypt ($2b$/$2a$/$2y$): the
    reference links a bcrypt NIF; this environment's crypt(3) supports
    the same modular format."""
    try:
        out = _crypt().crypt(password, stored)
        return out is not None and hmac.compare_digest(out, stored)
    except Exception:
        log.warning("bcrypt unavailable on this platform")
        return False


_bcrypt_ok: Optional[bool] = None


def bcrypt_supported() -> bool:
    """One-time platform probe: round-trip a known password through
    crypt(3) $2b$.  Used at authenticator CONSTRUCTION so an
    algorithm=bcrypt config on a platform without libxcrypt (or on
    Python >= 3.13, where stdlib crypt is gone) fails loudly at boot
    instead of silently DENYing every bcrypt credential at runtime."""
    global _bcrypt_ok
    if _bcrypt_ok is None:
        try:
            crypt = _crypt()
            salt = crypt.mksalt(crypt.METHOD_BLOWFISH)
            probe = crypt.crypt("probe", salt)
            _bcrypt_ok = bool(probe) and crypt.crypt("probe", probe) == probe
        except Exception:
            _bcrypt_ok = False
    return _bcrypt_ok


def check_algorithm_supported(algorithm: str) -> None:
    """Raise at construction time for algorithms this platform cannot
    verify (currently: bcrypt without a working crypt(3))."""
    if algorithm == "bcrypt" and not bcrypt_supported():
        raise RuntimeError(
            "password_hash algorithm 'bcrypt' is not supported on this "
            "platform (no stdlib crypt module or crypt(3) lacks $2b$); "
            "every bcrypt credential would silently fail closed"
        )


_SIMPLE = {
    "plain": None,
    "md5": hashlib.md5,
    "sha": hashlib.sha1,
    "sha256": hashlib.sha256,
    "sha512": hashlib.sha512,
}


def hash_password(
    password: str,
    algorithm: str = "sha256",
    salt: str = "",
    salt_position: str = "prefix",
    iterations: int = 50_000,
) -> str:
    """Produce a stored hash (tooling/tests; the verify twin below)."""
    if algorithm == "bcrypt":
        crypt = _crypt()
        stored_salt = salt or crypt.mksalt(crypt.METHOD_BLOWFISH)
        return crypt.crypt(password, stored_salt)
    if algorithm == "pbkdf2":
        return hashlib.pbkdf2_hmac(
            "sha256", password.encode(), salt.encode(), iterations
        ).hex()
    fn = _SIMPLE[algorithm]
    if fn is None:
        return password
    data = (salt + password) if salt_position == "prefix" \
        else (password + salt)
    return fn(data.encode()).hexdigest()


def verify_password(
    password: Optional[bytes],
    stored_hash: str,
    algorithm: str = "sha256",
    salt: str = "",
    salt_position: str = "prefix",
    iterations: int = 50_000,
) -> bool:
    """The reference's hashing suite
    (emqx_authn_password_hashing.erl): simple algorithms concatenate
    the salt before/after the password; pbkdf2 uses it as the HMAC
    salt; bcrypt embeds it in the stored hash."""
    if password is None:
        return False
    pw = password.decode("utf-8", "replace")
    if algorithm == "bcrypt":
        return _bcrypt_verify(pw, stored_hash)
    got = hash_password(pw, algorithm, salt, salt_position, iterations)
    return hmac.compare_digest(got, stored_hash)


# ------------------------------------------------------------ templating

def _peer_ip(c) -> str:
    # peerhost is "host:port"; rsplit keeps IPv6 colons intact (same
    # rule as broker/channel.py's peer formatting)
    return (c.peerhost or "").rsplit(":", 1)[0]


_PLACEHOLDERS = {
    "${username}": lambda c: c.username or "",
    "${clientid}": lambda c: c.clientid or "",
    "${peerhost}": _peer_ip,
    "${password}": lambda c: (c.password or b"").decode("utf-8",
                                                        "replace"),
    # legacy 4.x placeholders, still widely deployed
    "%u": lambda c: c.username or "",
    "%c": lambda c: c.clientid or "",
    "%a": _peer_ip,
    "%P": lambda c: (c.password or b"").decode("utf-8", "replace"),
}


def compile_query(
    template: str, paramstyle: str = "format"
) -> Tuple[str, List[Callable[[ClientInfo], str]]]:
    """Compile a placeholder template into (sql, param extractors):
    each placeholder becomes a bind parameter (``%s`` for MySQL-style,
    ``$1..$n`` for PostgreSQL), so client-controlled values never
    splice into SQL text (emqx_auth_template.erl's prepared-statement
    rendering)."""
    out: List[str] = []
    getters: List[Callable[[ClientInfo], str]] = []
    i = 0
    n = len(template)
    while i < n:
        for ph, getter in _PLACEHOLDERS.items():
            if template.startswith(ph, i):
                getters.append(getter)
                if paramstyle == "numeric":
                    out.append(f"${len(getters)}")
                else:
                    out.append("%s")
                i += len(ph)
                break
        else:
            ch = template[i]
            if ch == "%" and paramstyle == "format":
                # literal % (e.g. SQL LIKE 'x/%') must not read as a
                # driver format directive
                out.append("%%")
            else:
                out.append(ch)
            i += 1
    return "".join(out), getters


def render_params(
    getters: Sequence[Callable[[ClientInfo], str]], client: ClientInfo
) -> Tuple[str, ...]:
    return tuple(g(client) for g in getters)


def render_topic(pattern: str, client: ClientInfo) -> str:
    """ACL rows may embed placeholders inside topic patterns
    (emqx_authz rule rendering): literal substitution is correct here
    — topics are data, not SQL."""
    for ph, getter in _PLACEHOLDERS.items():
        if ph in pattern:
            pattern = pattern.replace(ph, getter(client))
    return pattern


# ------------------------------------------------------------ connectors

class SqlConnector:
    """Minimal async SQL interface (the ecpool role): ``query`` returns
    rows as dicts.  Concrete drivers below; tests use fakes."""

    paramstyle = "format"

    async def query(self, sql: str, params: Sequence) -> List[Dict]:
        raise NotImplementedError

    async def close(self) -> None:
        pass


class MysqlConnector(SqlConnector):
    """aiomysql-backed pool (gated: the driver is not bundled in this
    image; constructing without it raises with a clear message)."""

    paramstyle = "format"

    def __init__(self, host="127.0.0.1", port=3306, user="root",
                 password="", db="mqtt", pool_size=8):
        try:
            import aiomysql  # noqa: F401
        except ImportError as exc:
            raise RuntimeError(
                "MysqlConnector requires the 'aiomysql' driver"
            ) from exc
        self._cfg = dict(host=host, port=port, user=user,
                         password=password, db=db,
                         maxsize=pool_size, autocommit=True)
        self._pool = None

    async def _ensure(self):
        if self._pool is None:
            import aiomysql

            self._pool = await aiomysql.create_pool(**self._cfg)
        return self._pool

    async def query(self, sql: str, params: Sequence) -> List[Dict]:
        import aiomysql

        pool = await self._ensure()
        async with pool.acquire() as conn:
            async with conn.cursor(aiomysql.DictCursor) as cur:
                await cur.execute(sql, tuple(params))
                return list(await cur.fetchall())

    async def close(self) -> None:
        if self._pool is not None:
            self._pool.close()
            await self._pool.wait_closed()
            self._pool = None


class PostgresConnector(SqlConnector):
    """asyncpg-backed pool (gated like MysqlConnector)."""

    paramstyle = "numeric"

    def __init__(self, dsn="postgresql://localhost/mqtt", pool_size=8):
        try:
            import asyncpg  # noqa: F401
        except ImportError as exc:
            raise RuntimeError(
                "PostgresConnector requires the 'asyncpg' driver"
            ) from exc
        self._dsn = dsn
        self._size = pool_size
        self._pool = None

    async def _ensure(self):
        if self._pool is None:
            import asyncpg

            self._pool = await asyncpg.create_pool(
                self._dsn, max_size=self._size
            )
        return self._pool

    async def query(self, sql: str, params: Sequence) -> List[Dict]:
        pool = await self._ensure()
        rows = await pool.fetch(sql, *params)
        return [dict(r) for r in rows]

    async def close(self) -> None:
        if self._pool is not None:
            await self._pool.close()
            self._pool = None


class RedisConnector:
    """Minimal async Redis interface: ``cmd('HMGET', key, f1, f2)``."""

    def __init__(self, host="127.0.0.1", port=6379, db=0):
        try:
            import redis.asyncio  # noqa: F401
        except ImportError as exc:
            raise RuntimeError(
                "RedisConnector requires the 'redis' driver"
            ) from exc
        self._cfg = dict(host=host, port=port, db=db)
        self._client = None

    async def cmd(self, *args) -> Any:
        if self._client is None:
            import redis.asyncio as aredis

            self._client = aredis.Redis(
                **self._cfg, decode_responses=True
            )
        return await self._client.execute_command(*args)

    async def close(self) -> None:
        if self._client is not None:
            await self._client.aclose()
            self._client = None


# -------------------------------------------------------------- providers

class SqlAuthenticator(Authenticator):
    """SELECT-based authn (emqx_authn_mysql/postgresql): the query
    must yield at most one row with a ``password_hash`` column and
    optional ``salt`` / ``is_superuser``.  No row -> ignore (fall
    through the chain); wrong password -> deny."""

    is_async = True

    def __init__(
        self,
        connector: SqlConnector,
        query: str = (
            "SELECT password_hash, salt, is_superuser FROM mqtt_user "
            "WHERE username = ${username} LIMIT 1"
        ),
        algorithm: str = "sha256",
        salt_position: str = "prefix",
        iterations: int = 50_000,
    ) -> None:
        check_algorithm_supported(algorithm)
        self.connector = connector
        self.sql, self._getters = compile_query(
            query, connector.paramstyle
        )
        self.algorithm = algorithm
        self.salt_position = salt_position
        self.iterations = iterations

    def authenticate(self, client: ClientInfo):
        return IGNORE, {}  # async-only provider

    async def authenticate_async(self, client: ClientInfo):
        try:
            rows = await self.connector.query(
                self.sql, render_params(self._getters, client)
            )
        except Exception:
            log.exception("sql authn query failed")
            return IGNORE, {}  # DB down: fall through the chain
        if not rows:
            return IGNORE, {}
        row = rows[0]
        ok = verify_password(
            client.password,
            str(row.get("password_hash", "")),
            algorithm=self.algorithm,
            salt=str(row.get("salt") or ""),
            salt_position=self.salt_position,
            iterations=self.iterations,
        )
        if not ok:
            return DENY, {}
        return ALLOW, {
            "is_superuser": bool(row.get("is_superuser") or False)
        }

    async def close(self) -> None:
        await self.connector.close()


class SqlAuthorizer:
    """SELECT-based authz source (emqx_authz_mysql/postgresql): rows
    ``(permission, action, topic)`` evaluated in order; topics may
    embed placeholders and the reference's ``eq_`` prefix pins a
    literal topic (no wildcard expansion)."""

    def __init__(
        self,
        connector: SqlConnector,
        query: str = (
            "SELECT permission, action, topic FROM mqtt_acl "
            "WHERE username = ${username}"
        ),
    ) -> None:
        self.connector = connector
        self.sql, self._getters = compile_query(
            query, connector.paramstyle
        )

    async def fetch_rows(self, client: ClientInfo) -> List[Dict]:
        """All ACL rows for this client (prefetched at CONNECT into
        AccessControl's per-client cache — the emqx_authz_cache
        role)."""
        return await self.connector.query(
            self.sql, render_params(self._getters, client)
        )

    async def authorize_async(
        self, client: ClientInfo, action: str, topic: str
    ) -> str:
        try:
            rows = await self.fetch_rows(client)
        except Exception:
            log.exception("sql authz query failed")
            return IGNORE
        return evaluate_acl_rows(rows, client, action, topic)

    def authorize(self, client: ClientInfo, action: str, topic: str):
        return IGNORE  # async-only source

    async def close(self) -> None:
        await self.connector.close()


def evaluate_acl_rows(
    rows: Sequence[Dict], client: ClientInfo, action: str, topic: str
) -> str:
    """First matching row decides (emqx_authz_rule semantics):
    ``action`` of a row may be publish/subscribe/all; ``topic``
    matches as an MQTT filter unless prefixed ``eq_``/``eq `` (exact
    literal, the reference's <<"eq ...">> form)."""
    for row in rows:
        r_action = str(row.get("action", "all")).lower()
        if r_action not in ("all", action):
            continue
        pattern = render_topic(str(row.get("topic", "")), client)
        if pattern.startswith("eq "):
            hit = topic == pattern[3:]
        elif pattern.startswith("eq_"):
            hit = topic == pattern[3:]
        else:
            try:
                hit = T.match(topic, pattern)
            except ValueError:
                continue
        if hit:
            perm = str(row.get("permission", "allow")).lower()
            return ALLOW if perm == "allow" else DENY
    return IGNORE


class RedisAuthenticator(Authenticator):
    """HMGET-based authn (emqx_authn_redis): the command template
    names a key with placeholders and the fields to fetch, e.g.
    ``HMGET mqtt_user:${username} password_hash salt is_superuser``."""

    is_async = True

    def __init__(
        self,
        connector: RedisConnector,
        cmd: str = ("HMGET mqtt_user:${username} password_hash salt "
                    "is_superuser"),
        algorithm: str = "sha256",
        salt_position: str = "prefix",
        iterations: int = 50_000,
    ) -> None:
        check_algorithm_supported(algorithm)
        self.connector = connector
        parts = cmd.split()
        if not parts or parts[0].upper() != "HMGET" or len(parts) < 3:
            raise ValueError(
                "redis authn cmd must be 'HMGET <key> <field>...'"
            )
        self._key_tpl = parts[1]
        self.fields = parts[2:]
        self.algorithm = algorithm
        self.salt_position = salt_position
        self.iterations = iterations

    def authenticate(self, client: ClientInfo):
        return IGNORE, {}

    async def authenticate_async(self, client: ClientInfo):
        key = render_topic(self._key_tpl, client)
        try:
            vals = await self.connector.cmd("HMGET", key, *self.fields)
        except Exception:
            log.exception("redis authn failed")
            return IGNORE, {}
        row = dict(zip(self.fields, vals or ()))
        if not row.get("password_hash"):
            return IGNORE, {}
        ok = verify_password(
            client.password,
            str(row["password_hash"]),
            algorithm=self.algorithm,
            salt=str(row.get("salt") or ""),
            salt_position=self.salt_position,
            iterations=self.iterations,
        )
        if not ok:
            return DENY, {}
        return ALLOW, {
            "is_superuser": str(row.get("is_superuser") or "")
            in ("1", "true", "True")
        }

    async def close(self) -> None:
        await self.connector.close()


class RedisAuthorizer:
    """HGETALL-based authz (emqx_authz_redis): the hash at
    ``mqtt_acl:${username}`` maps topic filter -> action
    (publish|subscribe|all); present = allow (the reference's Redis
    source is allow-only; denial comes from the chain default)."""

    def __init__(
        self,
        connector: RedisConnector,
        cmd: str = "HGETALL mqtt_acl:${username}",
    ) -> None:
        self.connector = connector
        parts = cmd.split()
        if len(parts) != 2 or parts[0].upper() != "HGETALL":
            raise ValueError("redis authz cmd must be 'HGETALL <key>'")
        self._key_tpl = parts[1]

    def authorize(self, client: ClientInfo, action: str, topic: str):
        return IGNORE  # async-only source

    async def fetch_rows(self, client: ClientInfo) -> List[Dict]:
        key = render_topic(self._key_tpl, client)
        table = await self.connector.cmd("HGETALL", key)
        if isinstance(table, dict):
            items = table.items()
        else:  # flat [k, v, k, v] reply shape
            items = zip(table[::2], table[1::2])
        return [
            {"permission": "allow", "action": v, "topic": k}
            for k, v in items
        ]

    async def authorize_async(
        self, client: ClientInfo, action: str, topic: str
    ) -> str:
        try:
            rows = await self.fetch_rows(client)
        except Exception:
            log.exception("redis authz failed")
            return IGNORE
        return evaluate_acl_rows(rows, client, action, topic)

    async def close(self) -> None:
        await self.connector.close()
