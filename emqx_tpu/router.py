"""Router: subscription registry over the TPU match engine.

The layer the reference splits across `emqx_broker` subscriber tables +
`emqx_router` route table (/root/reference/apps/emqx/src/
emqx_broker.erl:119-132 ETS tables; emqx_router.erl:476-525 v2 route
schema).  Here one object owns both because a single host is one
"node": the `MatchEngine` indexes each distinct real filter once
(fid = the filter string), and per-filter subscriber maps carry
(clientid -> SubOpts) fan-out, CSR-expanded at dispatch time.

Shared subscriptions route through the same engine entry for the real
filter; group membership and per-message picks live in
`SharedSubManager`.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import topic as T
from .broker.session import SubOpts
from .broker.shared import SharedSubManager
from .engine import MatchEngine


class Router:
    def __init__(
        self,
        engine: Optional[MatchEngine] = None,
        shared: Optional[SharedSubManager] = None,
    ) -> None:
        # `engine or MatchEngine()` would DISCARD a configured empty
        # engine: MatchEngine defines __len__, so a fresh one is falsy
        self.engine = engine if engine is not None else MatchEngine()
        self.shared = shared if shared is not None else SharedSubManager()
        # cluster hooks: fired when a real filter gains its first local
        # subscriber / loses its last one (the sync_route add/delete
        # points, emqx_broker.erl:691-721) — ClusterNode broadcasts them
        self.on_route_added = None
        self.on_route_removed = None
        # real filter -> {clientid -> SubOpts} (direct, non-shared)
        self._subs: Dict[str, Dict[str, SubOpts]] = {}
        # real filter -> {(group, clientid) -> SubOpts} (shared)
        self._shared_opts: Dict[str, Dict[Tuple[str, str], SubOpts]] = {}
        # clientid -> set of full filter strings (incl. $share prefix)
        self._by_client: Dict[str, Set[str]] = {}

    # ------------------------------------------------------- mutation

    def subscribe(self, clientid: str, flt: str, opts: SubOpts) -> None:
        """Register `clientid`'s subscription to `flt` (which may be a
        `$share/...` filter).  Mirrors emqx_broker:subscribe/3 +
        route-add (emqx_broker.erl:151-190, 691-721)."""
        shared = T.parse_share(flt)
        if shared is not None:
            real = shared.topic
            opts.share_group = shared.group
            need_route = self.shared.join(shared.group, real, clientid)
            self._shared_opts.setdefault(real, {})[
                (shared.group, clientid)
            ] = opts
            if need_route and real not in self._subs:
                self.engine.insert(real, real)
                if self.on_route_added is not None:
                    self.on_route_added(real)
        else:
            real = flt
            subs = self._subs.get(real)
            if subs is None:
                subs = self._subs[real] = {}
                if real not in self._shared_opts or not self._shared_opts[real]:
                    self.engine.insert(real, real)
                    if self.on_route_added is not None:
                        self.on_route_added(real)
            subs[clientid] = opts
        self._by_client.setdefault(clientid, set()).add(flt)

    def unsubscribe(self, clientid: str, flt: str) -> bool:
        shared = T.parse_share(flt)
        if shared is not None:
            real = shared.topic
            emptied = self.shared.leave(shared.group, real, clientid)
            opts_map = self._shared_opts.get(real)
            if opts_map is not None:
                opts_map.pop((shared.group, clientid), None)
                if not opts_map:
                    del self._shared_opts[real]
            removed = True
        else:
            real = flt
            subs = self._subs.get(real)
            if subs is None or clientid not in subs:
                removed = False
            else:
                del subs[clientid]
                if not subs:
                    del self._subs[real]
                removed = True
        self._maybe_drop_route(real)
        filters = self._by_client.get(clientid)
        if filters is not None:
            filters.discard(flt)
            if not filters:
                del self._by_client[clientid]
        return removed

    def _maybe_drop_route(self, real: str) -> None:
        if real not in self._subs and real not in self._shared_opts:
            if self.engine.delete(real) and self.on_route_removed is not None:
                self.on_route_removed(real)

    def cleanup_client(self, clientid: str) -> None:
        """Drop every subscription of a dead client (the
        `subscriber_down` path, emqx_broker.erl:448-462)."""
        for flt in list(self._by_client.get(clientid, ())):
            self.unsubscribe(clientid, flt)

    def subscriptions_of(self, clientid: str) -> Set[str]:
        return set(self._by_client.get(clientid, ()))

    def topics(self) -> List[str]:
        """All indexed real filters (the route-table dump used by the
        mgmt API's /topics)."""
        return list(self._subs.keys() | self._shared_opts.keys())

    def subscription_count(self) -> int:
        """Total (client, filter) subscription pairs — the
        'subscriptions.count' stat (rule fids excluded)."""
        return sum(len(v) for v in self._subs.values()) + sum(
            len(v) for v in self._shared_opts.values()
        )

    # --------------------------------------------------------- match

    def match_batch(
        self, topics: Sequence[str], congested: bool = False
    ) -> List[Set[str]]:
        """Real filters matching each topic (batched on device).  The
        ``congested`` hint flips the engine's auto policy into
        throughput mode (compare host CPU, not wall time)."""
        return self.engine.match_batch(topics, congested=congested)

    def subscribers(
        self, real: str
    ) -> List[Tuple[str, SubOpts]]:
        """Direct (non-shared) subscribers of a matched filter."""
        return list(self._subs.get(real, {}).items())

    def shared_opts(
        self, real: str, group: str, clientid: str
    ) -> Optional[SubOpts]:
        m = self._shared_opts.get(real)
        return None if m is None else m.get((group, clientid))
