"""Router: subscription registry over the TPU match engine.

The layer the reference splits across `emqx_broker` subscriber tables +
`emqx_router` route table (/root/reference/apps/emqx/src/
emqx_broker.erl:119-132 ETS tables; emqx_router.erl:476-525 v2 route
schema).  Here one object owns both because a single host is one
"node": the `MatchEngine` indexes each distinct real filter once
(fid = the filter string), and per-filter subscriber maps carry
(clientid -> SubOpts) fan-out, CSR-expanded at dispatch time.

Fan-out expansion is vectorized: client ids intern to integer rows and
each SubOpts to a table slot, and each filter keeps an incrementally
maintained CSR column of (client_row, opts_row) pairs.  A window's
matched fid sets expand to flat ``(msg_idx, client_row, opts_row)``
arrays in one pass (`expand_window`) instead of per-filter dict churn —
rule fids and shared-group fids split off as distinct columns feeding
the rule sink and the shared-pick path.

Shared subscriptions route through the same engine entry for the real
filter; group membership and per-message picks live in
`SharedSubManager`.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from . import topic as T
from .broker.session import SubOpts
from .broker.shared import SharedSubManager
from .engine import MatchEngine

_EMPTY_I64 = np.empty(0, dtype=np.int64)

# initial capacity of the parallel SubOpts attribute columns; grown by
# doubling so the device decide path sees few distinct table shapes
_OPTS_CAP0 = 64


class _CsrBucket:
    """One filter's subscriber column: parallel (client_row, opts_row)
    lists with O(1) append and swap-remove, plus lazily rebuilt numpy
    views so a window expansion is array concatenation, not dict
    iteration."""

    __slots__ = ("rows", "opts_rows", "pos", "_arr")

    def __init__(self) -> None:
        self.rows: List[int] = []
        self.opts_rows: List[int] = []
        self.pos: Dict[int, int] = {}  # client_row -> index
        self._arr: Optional[Tuple[np.ndarray, np.ndarray]] = None

    def add(self, client_row: int, opts_row: int) -> None:
        self.pos[client_row] = len(self.rows)
        self.rows.append(client_row)
        self.opts_rows.append(opts_row)
        self._arr = None

    def opts_row_of(self, client_row: int) -> Optional[int]:
        i = self.pos.get(client_row)
        return None if i is None else self.opts_rows[i]

    def remove(self, client_row: int) -> Optional[int]:
        """Swap-remove; returns the freed opts row (None if absent)."""
        i = self.pos.pop(client_row, None)
        if i is None:
            return None
        freed = self.opts_rows[i]
        last_row = self.rows[-1]
        last_opts = self.opts_rows[-1]
        self.rows.pop()
        self.opts_rows.pop()
        if i < len(self.rows):
            self.rows[i] = last_row
            self.opts_rows[i] = last_opts
            self.pos[last_row] = i
        self._arr = None
        return freed

    def arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        a = self._arr
        if a is None:
            a = self._arr = (
                np.asarray(self.rows, dtype=np.int64),
                np.asarray(self.opts_rows, dtype=np.int64),
            )
        return a


class Router:
    def __init__(
        self,
        engine: Optional[MatchEngine] = None,
        shared: Optional[SharedSubManager] = None,
    ) -> None:
        # `engine or MatchEngine()` would DISCARD a configured empty
        # engine: MatchEngine defines __len__, so a fresh one is falsy
        self.engine = engine if engine is not None else MatchEngine()
        self.shared = shared if shared is not None else SharedSubManager()
        # cluster hooks: fired when a real filter gains its first local
        # subscriber / loses its last one (the sync_route add/delete
        # points, emqx_broker.erl:691-721) — ClusterNode broadcasts them
        self.on_route_added = None
        self.on_route_removed = None
        # real filter -> {clientid -> SubOpts} (direct, non-shared).
        # Stays the source of truth (mgmt dumps, counts, the legacy
        # walk the CSR property test checks against).
        self._subs: Dict[str, Dict[str, SubOpts]] = {}
        # real filter -> {(group, clientid) -> SubOpts} (shared)
        self._shared_opts: Dict[str, Dict[Tuple[str, str], SubOpts]] = {}
        # (real, group, clientid) -> opts table slot: shared-sub opts
        # intern into the SAME table as direct ones, so a window's
        # shared picks ride the decision columns like any delivery
        self._shared_slot: Dict[Tuple[str, str, str], int] = {}
        # clientid -> set of full filter strings (incl. $share prefix)
        self._by_client: Dict[str, Set[str]] = {}
        # --- interning tables + CSR fan-out index -------------------
        self._client_rows: Dict[str, int] = {}   # clientid -> row
        self._row_clients: List[str] = []        # row -> clientid
        self._row_free: List[int] = []
        self._opts_table: List[Optional[SubOpts]] = []
        self._opts_free: List[int] = []
        self._csr: Dict[str, _CsrBucket] = {}
        # --- parallel SubOpts attribute columns ---------------------
        # numpy twins of `_opts_table`, maintained on every alloc/free/
        # refresh, so a window's per-delivery decisions (effective QoS,
        # no-local drop, RAP retain, subid presence) come from ONE
        # vectorized gather instead of a Python attribute read per
        # delivery.  `opts_rev` bumps on every write so the engine's
        # device decide path can cache its uploaded copies.
        self._oa_qos = np.zeros(_OPTS_CAP0, dtype=np.int8)
        self._oa_nl = np.zeros(_OPTS_CAP0, dtype=bool)
        self._oa_rap = np.zeros(_OPTS_CAP0, dtype=bool)
        self._oa_subid = np.zeros(_OPTS_CAP0, dtype=bool)
        self.opts_rev = 0

    # ---------------------------------------------------- interning

    def _intern(self, clientid: str) -> int:
        row = self._client_rows.get(clientid)
        if row is None:
            if self._row_free:
                row = self._row_free.pop()
                self._row_clients[row] = clientid
            else:
                row = len(self._row_clients)
                self._row_clients.append(clientid)
            self._client_rows[clientid] = row
        return row

    def _release_client(self, clientid: str) -> None:
        row = self._client_rows.pop(clientid, None)
        if row is not None:
            self._row_clients[row] = ""
            self._row_free.append(row)

    def _alloc_opts(self, opts: SubOpts) -> int:
        if self._opts_free:
            slot = self._opts_free.pop()
            self._opts_table[slot] = opts
        else:
            slot = len(self._opts_table)
            self._opts_table.append(opts)
            if slot >= len(self._oa_qos):
                # double the attribute columns: few distinct shapes
                # keep the device decide path's recompiles bounded
                cap = 2 * len(self._oa_qos)
                for name in ("_oa_qos", "_oa_nl", "_oa_rap",
                             "_oa_subid"):
                    old = getattr(self, name)
                    new = np.zeros(cap, dtype=old.dtype)
                    new[: len(old)] = old
                    setattr(self, name, new)
        self._set_opts_attrs(slot, opts)
        return slot

    def _set_opts_attrs(self, slot: int, opts: SubOpts) -> None:
        """Mirror one SubOpts into the attribute columns (alloc AND
        options-refresh paths — the columns must never go stale, they
        are what the window decisions read)."""
        self._oa_qos[slot] = opts.qos
        self._oa_nl[slot] = opts.no_local
        self._oa_rap[slot] = opts.retain_as_published
        self._oa_subid[slot] = opts.subid is not None
        self.opts_rev += 1

    def _free_opts(self, slot: int) -> None:
        self._opts_table[slot] = None
        self._oa_qos[slot] = 0
        self._oa_nl[slot] = False
        self._oa_rap[slot] = False
        self._oa_subid[slot] = False
        self.opts_rev += 1
        self._opts_free.append(slot)

    def opts_columns(self) -> Tuple[np.ndarray, np.ndarray,
                                    np.ndarray, np.ndarray]:
        """(qos, no_local, retain_as_published, has_subid) attribute
        columns, indexed by opts row — the vectorized read side of the
        table `_set_opts_attrs` maintains."""
        return self._oa_qos, self._oa_nl, self._oa_rap, self._oa_subid

    def client_of_row(self, row: int) -> str:
        return self._row_clients[row]

    def row_of_client(self, clientid: str) -> Optional[int]:
        return self._client_rows.get(clientid)

    def opts_at(self, slot: int) -> SubOpts:
        return self._opts_table[slot]  # type: ignore[return-value]

    # ------------------------------------------------------- mutation

    def subscribe(self, clientid: str, flt: str, opts: SubOpts) -> None:
        """Register `clientid`'s subscription to `flt` (which may be a
        `$share/...` filter).  Mirrors emqx_broker:subscribe/3 +
        route-add (emqx_broker.erl:151-190, 691-721)."""
        shared = T.parse_share(flt)
        if shared is not None:
            real = shared.topic
            opts.share_group = shared.group
            self._intern(clientid)  # picks resolve to rows at dispatch
            need_route = self.shared.join(shared.group, real, clientid)
            self._shared_opts.setdefault(real, {})[
                (shared.group, clientid)
            ] = opts
            skey = (real, shared.group, clientid)
            sslot = self._shared_slot.get(skey)
            if sslot is None:
                self._shared_slot[skey] = self._alloc_opts(opts)
            else:  # options refresh of an existing shared subscription
                self._opts_table[sslot] = opts
                self._set_opts_attrs(sslot, opts)
            if need_route and real not in self._subs:
                self.engine.insert(real, real)
                if self.on_route_added is not None:
                    self.on_route_added(real)
        else:
            real = flt
            subs = self._subs.get(real)
            if subs is None:
                subs = self._subs[real] = {}
                if real not in self._shared_opts or not self._shared_opts[real]:
                    self.engine.insert(real, real)
                    if self.on_route_added is not None:
                        self.on_route_added(real)
            subs[clientid] = opts
            row = self._intern(clientid)
            bucket = self._csr.get(real)
            if bucket is None:
                bucket = self._csr[real] = _CsrBucket()
            slot = bucket.opts_row_of(row)
            if slot is None:
                bucket.add(row, self._alloc_opts(opts))
            else:  # options refresh of an existing subscription
                self._opts_table[slot] = opts
                self._set_opts_attrs(slot, opts)
        self._by_client.setdefault(clientid, set()).add(flt)

    def unsubscribe(self, clientid: str, flt: str) -> bool:
        shared = T.parse_share(flt)
        if shared is not None:
            real = shared.topic
            emptied = self.shared.leave(shared.group, real, clientid)
            opts_map = self._shared_opts.get(real)
            if opts_map is not None:
                opts_map.pop((shared.group, clientid), None)
                if not opts_map:
                    del self._shared_opts[real]
            sslot = self._shared_slot.pop(
                (real, shared.group, clientid), None
            )
            if sslot is not None:
                self._free_opts(sslot)
            removed = True
        else:
            real = flt
            subs = self._subs.get(real)
            if subs is None or clientid not in subs:
                removed = False
            else:
                del subs[clientid]
                if not subs:
                    del self._subs[real]
                bucket = self._csr.get(real)
                row = self._client_rows.get(clientid)
                if bucket is not None and row is not None:
                    freed = bucket.remove(row)
                    if freed is not None:
                        self._free_opts(freed)
                    if not bucket.rows:
                        del self._csr[real]
                removed = True
        self._maybe_drop_route(real)
        filters = self._by_client.get(clientid)
        if filters is not None:
            filters.discard(flt)
            if not filters:
                del self._by_client[clientid]
                self._release_client(clientid)
        return removed

    def _maybe_drop_route(self, real: str) -> None:
        if real not in self._subs and real not in self._shared_opts:
            if self.engine.delete(real) and self.on_route_removed is not None:
                self.on_route_removed(real)

    def cleanup_client(self, clientid: str) -> None:
        """Drop every subscription of a dead client (the
        `subscriber_down` path, emqx_broker.erl:448-462)."""
        for flt in list(self._by_client.get(clientid, ())):
            self.unsubscribe(clientid, flt)

    def subscriptions_of(self, clientid: str) -> Set[str]:
        return set(self._by_client.get(clientid, ()))

    def topics(self) -> List[str]:
        """All indexed real filters (the route-table dump used by the
        mgmt API's /topics)."""
        return list(self._subs.keys() | self._shared_opts.keys())

    def subscription_count(self) -> int:
        """Total (client, filter) subscription pairs — the
        'subscriptions.count' stat (rule fids excluded)."""
        return sum(len(v) for v in self._subs.values()) + sum(
            len(v) for v in self._shared_opts.values()
        )

    # --------------------------------------------------------- match

    def match_batch(
        self, topics: Sequence[str], congested: bool = False
    ) -> List[Set[str]]:
        """Real filters matching each topic (batched on device).  The
        ``congested`` hint flips the engine's auto policy into
        throughput mode (compare host CPU, not wall time)."""
        return self.engine.match_batch(topics, congested=congested)

    def subscribers(
        self, real: str
    ) -> List[Tuple[str, SubOpts]]:
        """Direct (non-shared) subscribers of a matched filter (the
        legacy per-filter walk; `expand_window` is the batched path)."""
        return list(self._subs.get(real, {}).items())

    def shared_opts(
        self, real: str, group: str, clientid: str
    ) -> Optional[SubOpts]:
        m = self._shared_opts.get(real)
        return None if m is None else m.get((group, clientid))

    def shared_slot_of(
        self, real: str, group: str, clientid: str
    ) -> Optional[int]:
        """Opts-table slot of one shared subscription (the row a
        window's shared pick contributes to the decision columns)."""
        return self._shared_slot.get((real, group, clientid))

    def opts_slot_of(self, clientid: str, flt: str) -> Optional[int]:
        """Opts-table slot of one client's subscription to ``flt``
        (``$share`` filters included) — how the durable-replay window
        builder resolves each (client, filter) backlog entry to the
        decision-column row its live deliveries already ride."""
        share = T.parse_share(flt)
        if share is not None:
            return self._shared_slot.get(
                (share.topic, share.group, clientid)
            )
        bucket = self._csr.get(flt)
        if bucket is None:
            return None
        row = self._client_rows.get(clientid)
        if row is None:
            return None
        return bucket.opts_row_of(row)

    # ----------------------------------------------- window expansion

    def expand_window(
        self, matched: Sequence[Set]
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
               List[Tuple[int, List[str]]],
               List[Tuple[int, str, str]]]:
        """CSR-expand one window's matched fid sets to flat delivery
        columns.

        Returns ``(msg_idx, client_rows, opts_rows, rules, shared)``:
        the three aligned int64 arrays cover every DIRECT (non-shared)
        delivery in the window — one vectorized concatenation over the
        per-filter CSR columns — while rule fids come back grouped
        per message as ``(msg_idx, [rule_id, ...])`` (RAW: unsorted,
        a multi-filter rule may repeat; the rule engine's flatten
        cache dedups vectorized) and shared-group fids as
        ``(msg_idx, real_filter, group)`` for the rule-sink and
        shared-pick paths.  Fids with no local state (e.g. raw engine
        fids preloaded by benchmarks) cost one dict miss each."""
        seg_rows: List[np.ndarray] = []
        seg_opts: List[np.ndarray] = []
        seg_msg: List[int] = []
        seg_len: List[int] = []
        rules: List[Tuple[int, List[str]]] = []
        shared: List[Tuple[int, str, str]] = []
        csr = self._csr
        groups_for = self.shared.groups_for
        rule_i = -1
        rule_ids: List[str] = []
        for i, fids in enumerate(matched):
            for fid in fids:
                if type(fid) is tuple:  # ("rule", rule_id, i)
                    if rule_i != i:
                        rule_i = i
                        rule_ids = []
                        rules.append((i, rule_ids))
                    rule_ids.append(fid[1])
                    continue
                bucket = csr.get(fid)
                if bucket is not None and bucket.rows:
                    r, o = bucket.arrays()
                    seg_rows.append(r)
                    seg_opts.append(o)
                    seg_msg.append(i)
                    seg_len.append(len(r))
                for group in groups_for(fid):
                    shared.append((i, fid, group))
        if not seg_rows:
            return _EMPTY_I64, _EMPTY_I64, _EMPTY_I64, rules, shared
        if len(seg_rows) == 1:
            client_rows, opts_rows = seg_rows[0], seg_opts[0]
            msg_idx = np.full(seg_len[0], seg_msg[0], dtype=np.int64)
        else:
            client_rows = np.concatenate(seg_rows)
            opts_rows = np.concatenate(seg_opts)
            msg_idx = np.repeat(
                np.asarray(seg_msg, dtype=np.int64), seg_len
            )
        return msg_idx, client_rows, opts_rows, rules, shared
