"""Client/topic tracing to per-trace log files.

The `emqx_trace` role (/root/reference/apps/emqx/src/emqx_trace/
emqx_trace.erl:82-94 taps, managed over REST by emqx_mgmt_api_trace):
operators start named traces filtered by clientid, topic filter, or
peer IP; matching broker events (connect/disconnect/subscribe/
unsubscribe/publish/deliver) append formatted lines to the trace's
file until it is stopped or its window ends.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from . import topic as T


@dataclass
class TraceRule:
    name: str
    kind: str  # "clientid" | "topic" | "ip"
    match: str
    path: str
    started_at: float = field(default_factory=time.time)
    ends_at: Optional[float] = None
    hits: int = 0

    def matches(
        self, clientid: Optional[str], topic: Optional[str], ip: Optional[str]
    ) -> bool:
        if self.kind == "clientid":
            return clientid == self.match
        if self.kind == "topic":
            return topic is not None and T.match(topic, self.match)
        if self.kind == "ip":
            return ip is not None and ip.split(":", 1)[0] == self.match
        return False


class TraceManager:
    """Attaches to the broker's hookpoints and fans matching events to
    per-trace files."""

    EVENTS = (
        "client.connected",
        "client.disconnected",
        "session.subscribed",
        "session.unsubscribed",
        "message.publish",
        "message.delivered",
    )

    def __init__(self, broker, directory: str = "data/trace") -> None:
        self.broker = broker
        self.directory = directory
        self._rules: Dict[str, TraceRule] = {}
        self._files: Dict[str, object] = {}
        hooks = broker.hooks
        hooks.add("client.connected", self._on_connected, priority=-100)
        hooks.add("client.disconnected", self._on_disconnected, priority=-100)
        hooks.add("session.subscribed", self._on_subscribed, priority=-100)
        hooks.add(
            "session.unsubscribed", self._on_unsubscribed, priority=-100
        )
        hooks.add("message.publish", self._on_publish, priority=-200)
        # the delivered tap registers lazily with the FIRST rule (and
        # unregisters with the last): an idle TraceManager must leave
        # the hookpoint EMPTY so the dispatch window skips the hook
        # walk and the per-run delivery-list materialization entirely
        self._delivered_cb = None

    # ------------------------------------------------------ management

    def start(
        self,
        name: str,
        kind: str,
        match: str,
        duration: Optional[float] = None,
    ) -> TraceRule:
        import re as _re

        if not _re.fullmatch(r"[A-Za-z0-9_-]{1,64}", name):
            # the name lands in a file path: no traversal characters
            raise ValueError(f"invalid trace name {name!r}")
        if kind not in ("clientid", "topic", "ip"):
            raise ValueError(f"unknown trace kind {kind!r}")
        if name in self._rules:
            raise ValueError(f"trace {name!r} already running")
        os.makedirs(self.directory, exist_ok=True)
        path = os.path.join(self.directory, f"{name}.log")
        rule = TraceRule(
            name=name,
            kind=kind,
            match=match,
            path=path,
            ends_at=None if duration is None else time.time() + duration,
        )
        self._rules[name] = rule
        self._files[name] = open(path, "a", buffering=1)
        if self._delivered_cb is None:
            self._delivered_cb = self.broker.hooks.add(
                "message.delivered", self._on_delivered, priority=-100
            )
        return rule

    def stop(self, name: str) -> bool:
        rule = self._rules.pop(name, None)
        f = self._files.pop(name, None)
        if f is not None:
            f.close()
        if not self._rules and self._delivered_cb is not None:
            self.broker.hooks.delete(
                "message.delivered", self._delivered_cb
            )
            self._delivered_cb = None
        return rule is not None

    def list(self) -> List[Dict]:
        return [
            {
                "name": r.name,
                "type": r.kind,
                "match": r.match,
                "file": r.path,
                "hits": r.hits,
                "started_at": r.started_at,
            }
            for r in self._rules.values()
        ]

    def stop_all(self) -> None:
        for name in list(self._rules):
            self.stop(name)

    # ---------------------------------------------------------- taps

    def _emit(
        self,
        event: str,
        clientid: Optional[str],
        topic: Optional[str],
        detail: str = "",
        ip: Optional[str] = None,
    ) -> None:
        if not self._rules:
            return
        now = time.time()
        line = None
        for name, rule in list(self._rules.items()):
            if rule.ends_at is not None and now > rule.ends_at:
                self.stop(name)
                continue
            if not rule.matches(clientid, topic, ip):
                continue
            if line is None:
                stamp = time.strftime(
                    "%Y-%m-%dT%H:%M:%S", time.localtime(now)
                )
                line = (
                    f"{stamp} [{event}] clientid={clientid or '-'} "
                    f"topic={topic or '-'} {detail}\n"
                )
            rule.hits += 1
            self._files[name].write(line)

    def _on_connected(self, client) -> None:
        self._emit(
            "client.connected",
            client.clientid,
            None,
            ip=getattr(client, "peerhost", None),
        )

    def _on_disconnected(self, client, reason) -> None:
        self._emit(
            "client.disconnected",
            client.clientid,
            None,
            f"reason={reason}",
            ip=getattr(client, "peerhost", None),
        )

    def _on_subscribed(self, clientid, flt, *rest) -> None:
        self._emit("session.subscribed", clientid, flt)

    def _on_unsubscribed(self, clientid, flt, *rest) -> None:
        self._emit("session.unsubscribed", clientid, flt)

    @staticmethod
    def _trace_tag(msg) -> str:
        """``trace=<id>`` for a lifecycle-sampled message: the line in
        the operator's per-trace log file links straight to the full
        distributed trace (``ctl tracing show <id>``)."""
        ctx = getattr(msg, "_trace_ctx", None)
        return f" trace={ctx.trace_id}" if ctx is not None else ""

    def _on_publish(self, msg):
        if not self._rules:
            return None  # no active traces: skip the format work
        self._emit(
            "message.publish",
            msg.from_client or None,
            msg.topic,
            f"qos={msg.qos} len={len(msg.payload)}"
            f"{self._trace_tag(msg)}",
        )
        return None  # never alters the fold accumulator

    def _on_delivered(self, clientid, deliveries) -> None:
        if not self._rules:
            return  # no active traces: stay off the fan-out hot path
        for msg, _opts in deliveries:
            self._emit(
                "message.delivered", clientid, msg.topic,
                f"qos={msg.qos}{self._trace_tag(msg)}",
            )
