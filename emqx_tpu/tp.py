"""Tracepoints + causal trace assertions + fault injection.

The snabbkaffe role (SURVEY §5.2: the reference's dev tracepoints
``?tp(...)`` double as test hooks, with ``?force_ordering`` for
deterministic race reproduction and trace specs asserted after the
run — /root/reference/apps/emqx uses this in nearly every concurrency
suite).  Production cost is one module-level bool check per
tracepoint; everything else exists only while a test collector is
installed.

Usage (tests):

    with tp.collect() as trace:
        ... run concurrent code containing tp.tp("fold_adopt", gen=3) ...
    tp.assert_order(trace, "fold_capture", "fold_adopt")

Deterministic interleaving:

    with tp.collect() as trace, tp.force_ordering(
        after="match_snapshot", block="fold_adopt"
    ):
        ...  # every fold_adopt now waits until a match_snapshot fired

Fault injection:

    with tp.inject("fold_assemble", RuntimeError("boom")):
        ...  # the traced code raises at that point
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

enabled = False  # fast-path gate: production pays one bool check

_lock = threading.Lock()
_events: Optional[List[Dict[str, Any]]] = None
_orderings: List[Tuple[str, str, threading.Event]] = []
_injections: Dict[str, BaseException] = {}


def tp(point: str, **fields) -> None:
    """Record a tracepoint (no-op unless a collector is active)."""
    if not enabled:
        return
    _fire(point, fields)


def _fire(point: str, fields: Dict[str, Any]) -> None:
    waiters = []
    with _lock:
        if _events is not None:
            _events.append({
                "tp": point,
                "ts": time.monotonic(),
                "thread": threading.current_thread().name,
                **fields,
            })
        exc = _injections.get(point)
        for after, block, evt in _orderings:
            if point == after:
                evt.set()
            elif point == block and not evt.is_set():
                waiters.append(evt)
    for evt in waiters:  # wait OUTSIDE the lock (the releaser needs it)
        if not evt.wait(30.0):
            raise TimeoutError(
                f"force_ordering: {point!r} waited 30s for its trigger"
            )
    if exc is not None:
        raise exc


@contextmanager
def collect():
    """Install a trace collector; yields the (live) event list."""
    global enabled, _events
    with _lock:
        prev = _events
        _events = events = []
        enabled = True
    try:
        yield events
    finally:
        with _lock:
            _events = prev
            enabled = bool(prev or _orderings or _injections)


@contextmanager
def force_ordering(after: str, block: str):
    """Until a tracepoint `after` has fired, any thread reaching
    tracepoint `block` waits (the ?force_ordering race pin)."""
    global enabled
    evt = threading.Event()
    entry = (after, block, evt)
    with _lock:
        _orderings.append(entry)
        enabled = True
    try:
        yield evt
    finally:
        evt.set()  # release any still-blocked thread
        with _lock:
            _orderings.remove(entry)
            enabled = bool(_events or _orderings or _injections)


@contextmanager
def inject(point: str, exc: BaseException):
    """Raise `exc` from inside the traced code at tracepoint `point`."""
    global enabled
    with _lock:
        _injections[point] = exc
        enabled = True
    try:
        yield
    finally:
        with _lock:
            _injections.pop(point, None)
            enabled = bool(_events or _orderings or _injections)


# ------------------------------------------------------------ asserts


def events_of(trace: List[Dict], point: str) -> List[Dict]:
    return [e for e in trace if e["tp"] == point]


def assert_present(trace: List[Dict], point: str, **match) -> Dict:
    for e in events_of(trace, point):
        if all(e.get(k) == v for k, v in match.items()):
            return e
    raise AssertionError(
        f"no {point!r} event matching {match} in "
        f"{[e['tp'] for e in trace]}"
    )


def assert_absent(trace: List[Dict], point: str, **match) -> None:
    for e in events_of(trace, point):
        if all(e.get(k) == v for k, v in match.items()):
            raise AssertionError(f"unexpected {point!r} event: {e}")


def assert_order(trace: List[Dict], first: str, then: str) -> None:
    """Every `then` event must be preceded by at least one `first`."""
    seen_first = False
    for e in trace:
        if e["tp"] == first:
            seen_first = True
        elif e["tp"] == then and not seen_first:
            raise AssertionError(
                f"{then!r} fired before any {first!r}: "
                f"{[e['tp'] for e in trace]}"
            )
    if not any(e["tp"] == then for e in trace):
        raise AssertionError(f"no {then!r} event in trace")
