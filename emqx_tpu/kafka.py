"""Kafka producer bridge — wire protocol, no client library.

The reference's flagship integration is emqx_bridge_kafka
(/root/reference/apps/emqx_bridge_kafka/src/emqx_bridge_kafka.erl,
with the wolff producer underneath): rule output → buffered, batched,
partitioned produce with health checks and retry/partial-failure
handling.  This module re-creates that producer path directly on the
Kafka wire protocol (KIP-98 record batches, Produce v3, Metadata v1):

  * `KafkaClient` — one asyncio connection per broker, correlation-id
    matched request/response framing;
  * record batches: magic-2 batches with CRC-32C, varint/zigzag record
    encoding — one batch per (topic, partition) per flush;
  * partitioning: murmur2 on the record key (Kafka's own default
    partitioner) or round-robin for keyless records;
  * `KafkaProducerResource` — a batching Resource on the buffer-worker
    path: `on_query_batch` groups queries by partition leader, sends
    one Produce per broker, REFRESHES METADATA and re-enqueues only
    the failed partitions' records on retriable errors (bounded
    attempts), and health-checks via Metadata.

Intentional scope: producer only (the reference bridge's primary
direction), acks=-1 by default, no compression, no idempotent
producer ids — each is an attributes/fields upgrade on the same batch
format.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import failpoints

log = logging.getLogger("emqx_tpu.kafka")

API_PRODUCE = 0
API_METADATA = 3

# Kafka error codes this producer understands (subset)
ERR_NONE = 0
RETRIABLE = {
    5,   # LEADER_NOT_AVAILABLE
    6,   # NOT_LEADER_FOR_PARTITION
    7,   # REQUEST_TIMED_OUT
    13,  # NETWORK_EXCEPTION
}


# ------------------------------------------------------------ crc32c

def _make_crc32c_table() -> List[int]:
    poly = 0x82F63B78
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return table


_CRC32C_TABLE = _make_crc32c_table()


def crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli), the checksum magic-2 record batches carry
    (plain crc32 covers only the old message sets)."""
    crc = 0xFFFFFFFF
    tab = _CRC32C_TABLE
    for b in data:
        crc = tab[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# ---------------------------------------------------------- primitives

def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _varint(n: int) -> bytes:
    """Signed varint (zigzag), the record-level integer encoding."""
    z = _zigzag(n)
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _string(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    b = s.encode()
    return struct.pack(">h", len(b)) + b


def _bytes32(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


def murmur2(data: bytes) -> int:
    """Kafka's DefaultPartitioner hash (murmur2, seed 0x9747b28c):
    byte-compatible so keyed records land on the same partitions a
    Java producer would pick."""
    length = len(data)
    seed = 0x9747B28C
    m = 0x5BD1E995
    mask = 0xFFFFFFFF
    h = (seed ^ length) & mask
    i = 0
    while length - i >= 4:
        k = int.from_bytes(data[i:i + 4], "little")
        k = (k * m) & mask
        k ^= k >> 24
        k = (k * m) & mask
        h = (h * m) & mask
        h ^= k
        i += 4
    rem = length - i
    if rem == 3:
        h ^= data[i + 2] << 16
    if rem >= 2:
        h ^= data[i + 1] << 8
    if rem >= 1:
        h ^= data[i]
        h = (h * m) & mask
    h ^= h >> 13
    h = (h * m) & mask
    h ^= h >> 15
    return h


def encode_record_batch(
    records: Sequence[Tuple[Optional[bytes], bytes]],
    timestamp_ms: Optional[int] = None,
) -> bytes:
    """One magic-2 RecordBatch for a (topic, partition)."""
    ts = timestamp_ms if timestamp_ms is not None else int(
        time.time() * 1000
    )
    recs = bytearray()
    for i, (key, value) in enumerate(records):
        body = bytearray()
        body += b"\x00"  # record attributes
        body += _varint(0)  # timestamp delta
        body += _varint(i)  # offset delta
        if key is None:
            body += _varint(-1)
        else:
            body += _varint(len(key)) + key
        body += _varint(len(value)) + value
        body += _varint(0)  # header count
        recs += _varint(len(body)) + body
    # from attributes to the end — the crc's coverage
    tail = (
        struct.pack(">h", 0)                  # attributes
        + struct.pack(">i", len(records) - 1)  # lastOffsetDelta
        + struct.pack(">q", ts)               # firstTimestamp
        + struct.pack(">q", ts)               # maxTimestamp
        + struct.pack(">q", -1)               # producerId
        + struct.pack(">h", -1)               # producerEpoch
        + struct.pack(">i", -1)               # baseSequence
        + struct.pack(">i", len(records))
        + bytes(recs)
    )
    crc = crc32c(tail)
    inner = (
        struct.pack(">i", -1)  # partitionLeaderEpoch
        + b"\x02"              # magic
        + struct.pack(">I", crc)
        + tail
    )
    return struct.pack(">q", 0) + struct.pack(">i", len(inner)) + inner


def decode_batch_record_count(batch: bytes) -> int:
    """Record count of a magic-2 batch (used by the in-repo fake
    broker and by tests to verify what went over the wire)."""
    # baseOffset(8) batchLength(4) epoch(4) magic(1) crc(4) attr(2)
    # lastOffsetDelta(4) firstTs(8) maxTs(8) pid(8) pepoch(2) bseq(4)
    return struct.unpack_from(">i", batch, 8 + 4 + 4 + 1 + 4 + 2 + 4
                              + 8 + 8 + 8 + 2 + 4)[0]


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    """Signed (zigzag) varint at ``pos`` -> (value, next_pos)."""
    shift = 0
    z = 0
    while True:
        b = buf[pos]
        pos += 1
        z |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (z >> 1) ^ -(z & 1), pos


def decode_record_batch(
    batch: bytes,
) -> List[Tuple[Optional[bytes], bytes]]:
    """Full magic-2 RecordBatch decode -> ``[(key, value), ...]`` —
    the inverse of `encode_record_batch`, crc-verified.  Tests
    round-trip multi-record batches through it; the fake broker uses
    `decode_batch_record_count` on the hot path instead."""
    base = 8 + 4 + 4  # baseOffset, batchLength, partitionLeaderEpoch
    if batch[base:base + 1] != b"\x02":
        raise ValueError(f"not a magic-2 batch: {batch[base:base+1]!r}")
    (crc,) = struct.unpack_from(">I", batch, base + 1)
    tail = batch[base + 1 + 4:]
    actual = crc32c(tail)
    if actual != crc:
        raise ValueError(f"batch crc mismatch: {actual:#x} != {crc:#x}")
    (n_records,) = struct.unpack_from(
        ">i", tail, 2 + 4 + 8 + 8 + 8 + 2 + 4
    )
    pos = 2 + 4 + 8 + 8 + 8 + 2 + 4 + 4
    out: List[Tuple[Optional[bytes], bytes]] = []
    for _ in range(n_records):
        length, pos = _read_varint(tail, pos)
        end = pos + length
        pos += 1  # record attributes
        _, pos = _read_varint(tail, pos)  # timestamp delta
        _, pos = _read_varint(tail, pos)  # offset delta
        klen, pos = _read_varint(tail, pos)
        if klen < 0:
            key = None
        else:
            key = tail[pos:pos + klen]
            pos += klen
        vlen, pos = _read_varint(tail, pos)
        value = tail[pos:pos + vlen]
        pos += vlen
        n_headers, pos = _read_varint(tail, pos)
        for _h in range(n_headers):
            hklen, pos = _read_varint(tail, pos)
            pos += max(hklen, 0)
            hvlen, pos = _read_varint(tail, pos)
            pos += max(hvlen, 0)
        if pos != end:
            raise ValueError(
                f"record length mismatch: ended {pos}, expected {end}"
            )
        out.append((key, value))
    return out


# -------------------------------------------------------------- client

class KafkaClient:
    """One broker connection: framed requests, correlation-id matched
    responses.

    Requests PIPELINE: each caller registers a future under its
    correlation id, writes its frame, and awaits the future; a single
    reader pump matches responses (in order per connection, but the
    id does the matching) back to their futures.  Concurrent produces
    no longer serialize on a lock held across the full round-trip —
    a slow broker delays only its own callers' futures."""

    def __init__(self, host: str, port: int,
                 client_id: str = "emqx_tpu") -> None:
        self.host = host
        self.port = port
        self.client_id = client_id
        self._r: Optional[asyncio.StreamReader] = None
        self._w: Optional[asyncio.StreamWriter] = None
        self._corr = 0
        self._pending: Dict[int, asyncio.Future] = {}
        self._reader: Optional[asyncio.Task] = None
        self._connecting: Optional[asyncio.Task] = None

    async def connect(self) -> None:
        self._r, self._w = await asyncio.open_connection(
            self.host, self.port
        )
        # fresh pending map per connection: a stale pump's teardown
        # must never fail futures registered against its successor
        self._pending = {}
        self._reader = asyncio.get_running_loop().create_task(
            self._read_loop(self._r, self._pending)
        )

    async def _ensure(self) -> None:
        """Connect once, even under concurrent callers: the first
        caller starts the dial, the rest await the same task (a
        failure propagates to all and the next call retries)."""
        if self.connected:
            return
        if self._connecting is None or self._connecting.done():
            self._connecting = asyncio.get_running_loop().create_task(
                self.connect()
            )
        await asyncio.shield(self._connecting)

    async def _read_loop(
        self, r: asyncio.StreamReader,
        pending: Dict[int, asyncio.Future],
    ) -> None:
        """Reader pump: one task demultiplexes every response to its
        caller's future by correlation id."""
        try:
            while True:
                raw = await r.readexactly(4)
                (size,) = struct.unpack(">i", raw)
                payload = await r.readexactly(size)
                (corr,) = struct.unpack_from(">i", payload, 0)
                fut = pending.pop(corr, None)
                if fut is not None and not fut.done():
                    fut.set_result(payload[4:])
        except asyncio.CancelledError:
            raise
        except Exception:
            pass  # connection loss surfaces via the pending futures
        finally:
            exc = ConnectionError(
                f"kafka connection {self.host}:{self.port} lost"
            )
            for fut in pending.values():
                if not fut.done():
                    fut.set_exception(exc)
            pending.clear()
            # tear the transport down with the pump: a half-closed
            # socket must read as disconnected, or every later
            # request() would register in an unpumped map and hang to
            # its timeout instead of re-dialing
            if self._r is r and self._w is not None:
                w, self._w, self._r = self._w, None, None
                w.close()

    def close(self) -> None:
        if self._reader is not None:
            self._reader.cancel()
            self._reader = None
        self._connecting = None
        if self._w is not None:
            self._w.close()
            self._r = self._w = None

    @property
    def connected(self) -> bool:
        return self._w is not None and not self._w.is_closing()

    async def request(self, api_key: int, api_version: int,
                      body: bytes, timeout: float = 10.0) -> bytes:
        await self._ensure()
        self._corr += 1
        corr = self._corr
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[corr] = fut
        header = (
            struct.pack(">hhi", api_key, api_version, corr)
            + _string(self.client_id)
        )
        msg = header + body
        try:
            self._w.write(struct.pack(">i", len(msg)) + msg)
            await self._w.drain()
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(corr, None)

    # ------------------------------------------------------- metadata

    async def metadata(
        self, topics: Sequence[str], timeout: float = 10.0
    ) -> Dict[str, Any]:
        body = struct.pack(">i", len(topics)) + b"".join(
            _string(t) for t in topics
        )
        resp = await self.request(API_METADATA, 1, body, timeout)
        off = 0

        def take(fmt):
            nonlocal off
            vals = struct.unpack_from(">" + fmt, resp, off)
            off += struct.calcsize(">" + fmt)
            return vals if len(vals) > 1 else vals[0]

        def take_str():
            nonlocal off
            (ln,) = struct.unpack_from(">h", resp, off)
            off += 2
            if ln < 0:
                return None
            s = resp[off:off + ln].decode()
            off += ln
            return s

        brokers = {}
        for _ in range(take("i")):
            nid = take("i")
            host = take_str()
            port = take("i")
            take_str()  # rack
            brokers[nid] = (host, port)
        take("i")  # controller id
        out_topics: Dict[str, Dict[int, int]] = {}
        errors: Dict[str, int] = {}
        for _ in range(take("i")):
            err = take("h")
            name = take_str()
            take("b")  # is_internal
            parts: Dict[int, int] = {}
            for _ in range(take("i")):
                perr = take("h")
                pid = take("i")
                leader = take("i")
                for _ in range(take("i")):
                    take("i")  # replicas
                for _ in range(take("i")):
                    take("i")  # isr
                if perr == ERR_NONE:
                    parts[pid] = leader
            out_topics[name] = parts
            errors[name] = err
        return {"brokers": brokers, "topics": out_topics,
                "errors": errors}

    # -------------------------------------------------------- produce

    async def produce(
        self,
        topic_partitions: Dict[Tuple[str, int], bytes],
        acks: int = -1,
        timeout_ms: int = 10_000,
        timeout: float = 10.0,
    ) -> Dict[Tuple[str, int], int]:
        """Produce v3: {(topic, partition): record_batch} -> error
        code per partition."""
        act = None
        if failpoints.enabled:
            # error (ConnectionError) exercises the park-and-retry
            # path; drop answers REQUEST_TIMED_OUT (retriable) without
            # touching the wire; duplicate really produces twice
            # (at-least-once duplication)
            act = await failpoints.evaluate_async(
                "kafka.produce", key=f"{self.host}:{self.port}"
            )
            if act == "drop":
                return {tp: 7 for tp in topic_partitions}
        by_topic: Dict[str, List[Tuple[int, bytes]]] = {}
        for (t, p), batch in topic_partitions.items():
            by_topic.setdefault(t, []).append((p, batch))
        body = bytearray()
        body += _string(None)  # transactional_id
        body += struct.pack(">hi", acks, timeout_ms)
        body += struct.pack(">i", len(by_topic))
        for t, parts in by_topic.items():
            body += _string(t)
            body += struct.pack(">i", len(parts))
            for p, batch in parts:
                body += struct.pack(">i", p)
                body += _bytes32(batch)
        resp = await self.request(API_PRODUCE, 3, bytes(body), timeout)
        if act == "duplicate":
            resp = await self.request(
                API_PRODUCE, 3, bytes(body), timeout
            )
        off = 0
        out: Dict[Tuple[str, int], int] = {}
        (n_topics,) = struct.unpack_from(">i", resp, off)
        off += 4
        for _ in range(n_topics):
            (ln,) = struct.unpack_from(">h", resp, off)
            off += 2
            tname = resp[off:off + ln].decode()
            off += ln
            (n_parts,) = struct.unpack_from(">i", resp, off)
            off += 4
            for _ in range(n_parts):
                pid, err, _base, _lat = struct.unpack_from(
                    ">ihqq", resp, off
                )
                off += 4 + 2 + 8 + 8
                out[(tname, pid)] = err
        return out


# ------------------------------------------------------------ resource

class KafkaProducerResource:
    """Batched Kafka producer on the resource buffer-worker path.

    Queries are ``value`` bytes/str or ``(key, value)`` tuples (rule
    SinkActions enqueue rendered strings; `KafkaBridge`-style callers
    pass the MQTT topic as the key so per-topic ordering maps to a
    partition).  One flush groups records by partition, then by the
    partition's LEADER broker, and sends one Produce per broker.
    Retriable per-partition errors re-enqueue only THAT partition's
    records (bounded attempts) after a metadata refresh."""

    max_batch = 512  # buffer-worker drains up to this many per flush

    def __init__(
        self,
        bootstrap: Sequence[Tuple[str, int]],
        topic: str,
        acks: int = -1,
        client_id: str = "emqx_tpu",
        max_attempts: int = 5,
    ) -> None:
        self.bootstrap = list(bootstrap)
        self.topic = topic
        self.acks = acks
        self.client_id = client_id
        self.max_attempts = max_attempts
        self._clients: Dict[Tuple[str, int], KafkaClient] = {}
        self._leaders: Dict[int, Tuple[str, int]] = {}  # partition->addr
        self._n_partitions = 0  # topic TOTAL, incl leaderless ones
        self._rr = 0
        self.stats = {"produced": 0, "partition_retries": 0,
                      "abandoned": 0}
        self._requeue: List[Tuple[int, Any]] = []  # (attempt, query)

    # ------------------------------------------------------- lifecycle

    def _client(self, addr: Tuple[str, int]) -> KafkaClient:
        c = self._clients.get(addr)
        if c is None:
            c = self._clients[addr] = KafkaClient(
                addr[0], addr[1], self.client_id
            )
        return c

    async def on_start(self) -> None:
        await self._refresh_metadata()

    async def on_stop(self) -> None:
        for c in self._clients.values():
            c.close()
        self._clients.clear()

    async def _refresh_metadata(self) -> None:
        last_exc: Optional[Exception] = None
        for addr in self.bootstrap:
            try:
                md = await self._client(addr).metadata([self.topic])
                parts = md["topics"].get(self.topic, {})
                if not parts:
                    raise ConnectionError(
                        f"topic {self.topic!r} has no partitions "
                        f"(error {md['errors'].get(self.topic)})"
                    )
                self._leaders = {
                    pid: md["brokers"][leader]
                    for pid, leader in parts.items()
                    if leader in md["brokers"]
                }
                self._n_partitions = max(parts, default=-1) + 1
                return
            except Exception as exc:  # try the next bootstrap broker
                last_exc = exc
        raise last_exc or ConnectionError("no bootstrap broker")

    async def health_check(self) -> bool:
        try:
            await self._refresh_metadata()
            if self._requeue:
                # the periodic probe doubles as the retry tick for
                # records parked by a partial partition failure
                await self.on_query_batch([])
            return bool(self._leaders)
        except Exception:
            return False

    # ---------------------------------------------------------- flush

    def _partition_of(self, key: Optional[bytes]) -> int:
        """Kafka's DefaultPartitioner mapping over the topic's TOTAL
        partition count — keyed records land exactly where a Java/
        librdkafka producer puts them (toPositive mask included), so
        co-partitioned consumers keep their ordering guarantee.  A
        currently-leaderless target partition parks the records on
        the retry path instead of silently remapping them."""
        if not self._n_partitions:
            raise ConnectionError("no partition metadata")
        if key is None:
            pids = sorted(self._leaders) or [0]
            self._rr += 1
            return pids[self._rr % len(pids)]
        return (murmur2(key) & 0x7FFFFFFF) % self._n_partitions

    @staticmethod
    def _to_record(query: Any) -> Tuple[Optional[bytes], bytes]:
        if isinstance(query, tuple):
            key, value = query
            key = key.encode() if isinstance(key, str) else key
        else:
            key, value = None, query
        value = value.encode() if isinstance(value, str) else value
        return key, value

    async def on_query(self, query: Any) -> None:
        await self.on_query_batch([query])

    async def on_query_batch(self, queries: Sequence[Any]) -> int:
        """Returns how many head queries were consumed.  Every head
        query IS consumed on a normal return: records for failed
        partitions move to the internal ``_requeue`` (bounded
        attempts) and ride the next flush or health tick, so a single
        wedged partition neither stalls the others nor double-produces
        the records that already landed."""
        parked, self._requeue = self._requeue, []
        try:
            work: List[Tuple[int, Any]] = parked + [
                (0, q) for q in queries
            ]
            if not work:
                return 0
            if not self._leaders:
                await self._refresh_metadata()
            per_part: Dict[int, List[Tuple[int, Any]]] = {}
            for attempt, q in work:
                try:
                    key, _value = self._to_record(q)
                except Exception:
                    # one malformed query must not poison the batch —
                    # or discard previously parked records with it
                    self.stats["abandoned"] += 1
                    log.warning("kafka: unencodable query dropped")
                    continue
                per_part.setdefault(
                    self._partition_of(key), []
                ).append((attempt, q))
            by_broker: Dict[
                Tuple[str, int], Dict[Tuple[str, int], bytes]
            ] = {}
            failed_parts: List[int] = []
            for pid, items in per_part.items():
                leader = self._leaders.get(pid)
                if leader is None:
                    failed_parts.append(pid)  # leaderless: park + retry
                    continue
                batch = encode_record_batch(
                    [self._to_record(q) for _, q in items]
                )
                by_broker.setdefault(
                    leader, {}
                )[(self.topic, pid)] = batch
        except BaseException:
            # nothing was sent: restore the parked retry records so a
            # metadata failure cannot silently drop them
            self._requeue = parked + self._requeue
            raise
        for addr, tps in by_broker.items():
            try:
                errs = await self._client(addr).produce(
                    tps, acks=self.acks
                )
            except Exception:
                self._client(addr).close()
                failed_parts.extend(p for (_, p) in tps)
                continue
            for (t, p), err in errs.items():
                if err == ERR_NONE:
                    self.stats["produced"] += len(per_part[p])
                elif err in RETRIABLE:
                    failed_parts.append(p)
                else:
                    # non-retriable (auth, too-large, ...): drop loudly
                    self.stats["abandoned"] += len(per_part[p])
                    log.error(
                        "kafka produce to %s[%d] failed hard: error %d "
                        "(%d records dropped)", t, p, err,
                        len(per_part[p]),
                    )
        if failed_parts:
            try:
                await self._refresh_metadata()
            except Exception:
                pass
            for p in failed_parts:
                for attempt, q in per_part[p]:
                    if attempt + 1 >= self.max_attempts:
                        self.stats["abandoned"] += 1
                        log.warning(
                            "kafka record abandoned after %d attempts "
                            "(partition %d)", self.max_attempts, p,
                        )
                    else:
                        self.stats["partition_retries"] += 1
                        self._requeue.append((attempt + 1, q))
        return len(queries)
