"""$SYS broker self-topics: periodic heartbeat publishes.

The `emqx_sys` role (/root/reference/apps/emqx/src/emqx_sys.erl):
version/uptime/datetime heartbeats plus live stats and metrics snapshots
under ``$SYS/brokers/<node>/...``, so any MQTT client monitoring
``$SYS/#`` observes the broker.  Messages carry ``sys=True`` so they
bypass retained storage and the persistence gate.
"""

from __future__ import annotations

import json
import time
from typing import List

from .message import Message

VERSION = "emqx_tpu 0.3.0"


class SysTopics:
    def __init__(self, broker, node_name: str | None = None) -> None:
        self.broker = broker
        self.node = node_name or broker.config.node_name
        self.started_at = time.time()
        self._last = 0.0

    def _msg(self, suffix: str, value) -> Message:
        payload = (
            value
            if isinstance(value, bytes)
            else json.dumps(value).encode()
            if not isinstance(value, str)
            else value.encode()
        )
        return Message(
            topic=f"$SYS/brokers/{self.node}/{suffix}",
            payload=payload,
            qos=0,
            sys=True,
        )

    def heartbeat_messages(self) -> List[Message]:
        b = self.broker
        uptime = int(time.time() - self.started_at)
        stats = b.stats.all()
        stats["connections.count"] = len(b.cm)
        stats["topics.count"] = len(b.router.topics())
        stats["retained.count"] = len(b.retainer)
        out = [
            self._msg("version", VERSION),
            self._msg("uptime", str(uptime)),
            self._msg("datetime", time.strftime("%Y-%m-%dT%H:%M:%S%z")),
            self._msg("sysdescr", "TPU-native MQTT broker"),
            self._msg("stats", stats),
            self._msg("metrics", b.metrics.all()),
            self._msg("clients/count", str(len(b.cm))),
            self._msg(
                "subscriptions/count", str(b.router.subscription_count())
            ),
        ]
        prof = getattr(b, "profiler", None)
        if prof is not None and prof.enabled:
            # periodic window-pipeline summary: per-stage p50/p99 +
            # the engine gauge surface, so a plain MQTT monitor on
            # $SYS/# sees where window time goes
            out.append(self._msg("profiler", {
                "stages_us": {
                    name: {
                        "count": d["count"],
                        "p50": d["p50"],
                        "p99": d["p99"],
                    }
                    for name, d in prof.summary().items()
                    if d["count"]
                },
                "engine": b.router.engine.stats(),
            }))
        return out

    def tick(self, now: float | None = None) -> int:
        """Publish the heartbeat when the configured interval elapsed;
        returns the number of $SYS messages published."""
        cfg = self.broker.config.sys
        if not cfg.enable:
            return 0
        now = now if now is not None else time.time()
        if now - self._last < cfg.interval:
            return 0
        self._last = now
        msgs = self.heartbeat_messages()
        self.broker.publish_many(msgs)
        return len(msgs)
