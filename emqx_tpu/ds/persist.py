"""Durable (persistent) sessions over the DS storage engine.

The `emqx_persistent_session_ds` + `emqx_persistent_message` slice
(/root/reference/apps/emqx/src/emqx_persistent_session_ds.erl,
emqx_persistent_message.erl:98-113): messages matching a persistent
session's subscriptions are persisted to DS, session metadata is
checkpointed on disconnect, and a reconnect after a broker restart
rebuilds the session and replays the missed interval from storage.

Division of labor with the in-memory session: while the broker stays
up, a detached session's messages queue in its mqueue (fast path).  DS
replay serves the case the mqueue cannot: the broker process restarted
and in-memory state is gone.  The persistence *gate* mirrors
emqx_persistent_message:persist/1 — a message is stored only when some
persistent session's filter matches it.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, List, Optional, Tuple

from .. import failpoints
from .. import topic as T
from ..engine import MatchEngine
from ..message import Message
from . import atomicio
from .api import IterRef, StreamRef
from .builtin_local import LocalStorage
from .durability import GateGroup, SyncGate
from .replication import rendezvous_pick

log = logging.getLogger("emqx_tpu.ds")


def _stream_pkey(s: StreamRef) -> str:
    """Stable progress/rendezvous key for a stream: the bare in-store
    shard for store 0 (byte-compatible with pre-sharded progress
    files), ``store:shard`` otherwise — shard numbers repeat across
    stores, so the store index must disambiguate or two shards'
    progress would clobber each other."""
    return str(s.shard) if not s.store else f"{s.store}:{s.shard}"


class SessionState:
    """One checkpointed session (the state emqx_persistent_session_ds
    keeps in DS session tables)."""

    def __init__(
        self,
        clientid: str,
        subs: Dict[str, Dict],
        expiry: float,
        disconnected_at: float,
        iters: Optional[Dict[str, List[Dict]]] = None,
    ) -> None:
        self.clientid = clientid
        self.subs = subs  # filter -> SubOpts-as-dict
        self.expiry = expiry
        self.disconnected_at = disconnected_at
        # replay progress: filter -> list of IterRef json cursors.
        # None = replay not started; persisted mid-replay so a crash
        # resumes from the cursors instead of re-reading from
        # disconnected_at (the reference persists per-stream progress
        # the same way, emqx_persistent_session_ds_stream_scheduler).
        self.iters = iters
        # transient: message-id dedup across overlapping filters within
        # ONE replay run (lost on crash — replay is at-least-once)
        self._replay_seen: set = set()

    def expired(self, now: float) -> bool:
        return now - self.disconnected_at > self.expiry

    def to_json(self) -> Dict:
        out = {
            "clientid": self.clientid,
            "subs": self.subs,
            "expiry": self.expiry,
            "disconnected_at": self.disconnected_at,
        }
        if self.iters is not None:
            out["iters"] = self.iters
        return out

    @staticmethod
    def from_json(obj: Dict) -> "SessionState":
        return SessionState(
            clientid=obj["clientid"],
            subs=obj["subs"],
            expiry=obj["expiry"],
            disconnected_at=obj["disconnected_at"],
            iters=obj.get("iters"),
        )


class DurableSessions:
    def __init__(
        self,
        directory: str,
        n_streams: int = 16,
        store_qos0: bool = False,
        layout: str = "lts",
        fsync: str = "interval",
        n_shards: int = 1,
    ) -> None:
        # durability mode (config `durable.fsync`): `never` = no
        # fsyncs, `interval` = periodic group flush off the broker
        # tick, `always` = group-commit — QoS>=1 acks for captured
        # messages park until the covering dslog_sync lands, ONE fsync
        # amortized per dispatch window.  Metadata sidecars fsync on
        # every write only in `always` (atomic replace + CRC apply in
        # every mode).
        self.fsync_mode = fsync
        self.meta_fsync = fsync == "always"
        # detected-corruption surface: events buffer here until the
        # broker wires `on_corruption` (alarm + counter); counts feed
        # sync_stats either way
        self.on_corruption = None
        self.corruption_events: List[Dict] = []
        self.corruption_counts = {"storage": 0, "meta": 0}
        msg_dir = os.path.join(directory, "messages")
        os.makedirs(msg_dir, exist_ok=True)
        # the layout is a property of the DATA: records written under
        # one keymapper are unreadable under another, so a directory
        # marker pins it and wins over a changed config (with a loud
        # log) instead of silently orphaning the history.  Pre-marker
        # directories (older builds) are the hash layout — their
        # census.json gives them away.
        marker = os.path.join(msg_dir, "LAYOUT")
        on_layout, on_shards = self._read_layout_marker(marker, msg_dir)
        if on_layout and on_layout != layout:
            log.warning(
                "durable layout pinned to %r by existing data "
                "(config asked for %r)", on_layout, layout,
            )
            layout = on_layout
        # the shard count is ALSO a property of the data: it decides
        # which shard directory a topic's records live in, so existing
        # data pins it the same way the keymapper layout is pinned
        if on_shards is not None and on_shards != n_shards:
            log.warning(
                "durable shard count pinned to %d by existing data "
                "(config asked for %d)", on_shards, n_shards,
            )
            n_shards = on_shards
        if on_layout is None:
            atomicio.atomic_write_json(
                marker,
                layout if n_shards == 1
                else {"layout": layout, "shards": n_shards},
                fsync=self.meta_fsync,
            )
        self.layout = layout
        self.n_shards = n_shards
        if n_shards > 1:
            from .sharded import ShardedStorage

            self.storage = ShardedStorage(
                msg_dir, n_shards=n_shards, layout=layout,
                n_streams=n_streams,
            )
        elif layout == "lts":
            from .lts import LtsStorage

            self.storage = LtsStorage(msg_dir)
        else:
            self.storage = LocalStorage(msg_dir, n_streams=n_streams)
        self.storage.meta_fsync = self.meta_fsync
        # adopt corruption the storage detected during ITS load, then
        # route everything after through our reporter
        for evt in self.storage.corruption_events:
            self._report_corruption(**evt)
        self.storage.corruption_events = []
        self.storage.on_corruption = (
            lambda evt: self._report_corruption(**evt)
        )
        # census-rebuild surface (the ds_meta_rebuild alarm): same
        # adoption shape — events buffer until the broker wires it
        self.on_rebuild = None
        self.rebuild_events: List[Dict] = []
        for evt in getattr(self.storage, "rebuild_events", ()):
            self._forward_rebuild(evt)
        if hasattr(self.storage, "rebuild_events"):
            self.storage.rebuild_events = []
        if hasattr(self.storage, "on_rebuild"):
            self.storage.on_rebuild = self._forward_rebuild
        # the group-commit fsync gate (see ds/durability.py): persist()
        # advances its watermark, the broker's dispatch loop parks acks
        # on it in `always` mode, the tick flushes through it in
        # `interval` mode — so every fsync is counted/attributed once.
        # Sharded: ONE gate per shard (independent append watermarks +
        # fsync barriers — the scaling point) fronted by a GateGroup
        # that keeps the broker's single-gate contract, including the
        # cross-shard ack barrier.
        if n_shards > 1:
            self._shard_gates: Optional[List[SyncGate]] = [
                SyncGate(st.sync_data) for st in self.storage.stores
            ]
            self.gate = GateGroup(self._shard_gates)
        else:
            self._shard_gates = None
            self.gate = SyncGate(self.storage.sync_data)
        self.state_dir = os.path.join(directory, "sessions")
        os.makedirs(self.state_dir, exist_ok=True)
        self.store_qos0 = store_qos0
        # persistence gate: filters of every persistent session (live or
        # detached), refcounted; host matching is fine at this rate
        self._gate = MatchEngine(use_device=False)
        self._refs: Dict[str, int] = {}
        # detached states restored from disk at boot
        self._boot_states: Dict[str, SessionState] = {}
        # fired (with the clientid) when a boot checkpoint is dropped —
        # the broker uses it to retract the routes it advertised for
        # the detached session
        self.on_drop = None
        # grouped long-poll over the message log (the beamformer):
        # stores fire beams waking coherent parked readers
        from .beamformer import Beamformer

        self.beamformer = Beamformer(self.storage)
        # durable $share membership (the emqx_ds_shared_sub leader
        # state, persisted): stream assignment must see EVERY member —
        # detached, resumed, or mid-replay — regardless of liveness or
        # checkpoint presence
        self._share_members: Dict[str, List[str]] = {}
        self._share_path = os.path.join(directory, "share_members.json")
        # missing = fresh start; UNREADABLE = alarm + conservative
        # fallback — the persisted registry is gone, but
        # `shared_group_members` still unions every checkpointed
        # subscriber, so stream assignment degrades to the
        # checkpoint-derived membership instead of silently shrinking
        # to nobody
        obj = self._load_meta(self._share_path, "share membership")
        if obj is not None:
            try:
                self._share_members = {
                    k: list(v) for k, v in obj.items()
                }
            except (AttributeError, TypeError):
                self._report_corruption(
                    "meta", self._share_path, "not a members mapping"
                )
        # GROUP-level consumed progress per (share filter, stream):
        # the emqx_ds_shared_sub leader's per-stream offsets.  Replay
        # never re-reads below it, so membership churn (a member
        # leaving after consuming its share) cannot re-deliver the
        # consumed interval to the survivors.
        self._share_progress: Dict[str, Dict[str, List[int]]] = {}
        self._share_prog_path = os.path.join(
            directory, "share_progress.json"
        )
        # UNREADABLE progress falls back to empty — which means
        # "nothing consumed yet": replay restarts from disconnected_at,
        # strictly MORE redelivery (at-least-once), never loss — with
        # the alarm raised (the pre-PR silent `{}` reset looked
        # identical to a fresh directory)
        obj = self._load_meta(self._share_prog_path, "share progress")
        if isinstance(obj, dict):
            self._share_progress = obj
        elif obj is not None:
            self._report_corruption(
                "meta", self._share_prog_path, "not a progress mapping"
            )
        self._load_states()

    def boot_states(self) -> List[SessionState]:
        return list(self._boot_states.values())

    def has_checkpoint(self, clientid: str) -> bool:
        return clientid in self._boot_states

    # ---------------------------------------------------- meta/alarms

    def _report_corruption(self, kind: str, path: str, detail: str,
                           records: int = 0) -> None:
        """ONE funnel for every detected-corruption event (storage
        quarantine or unreadable sidecar): counted, logged, and either
        delivered to the broker's alarm wiring or buffered for it to
        drain after construction."""
        self.corruption_counts[kind] = (
            self.corruption_counts.get(kind, 0) + 1
        )
        log.error("ds %s corruption at %s: %s", kind, path, detail)
        evt = {"kind": kind, "path": path, "detail": detail}
        if records:
            evt["records"] = records
        if self.on_corruption is not None:
            self.on_corruption(evt)
        else:
            self.corruption_events.append(evt)

    def _forward_rebuild(self, evt: Dict) -> None:
        """Census-rebuild lifecycle events (start/done/aborted) flow
        to the broker's alarm wiring, or buffer until it exists."""
        log.warning(
            "ds census rebuild %s at %s (%d/%d streams)",
            evt.get("event"), evt.get("path"),
            evt.get("scanned", 0), evt.get("total", 0),
        )
        if self.on_rebuild is not None:
            self.on_rebuild(evt)
        else:
            self.rebuild_events.append(evt)

    def rebuild_now(self) -> None:
        """Block until any in-flight background census rebuild lands
        (tests/ctl)."""
        self.storage.rebuild_now()

    def _load_meta(self, path: str, what: str):
        """Load one sidecar: None for missing (fresh start) OR
        unreadable — but the unreadable case is alarmed first, so the
        conservative fallback is never silent."""
        try:
            return atomicio.load_json(path)
        except FileNotFoundError:
            return None
        except atomicio.MetaCorruption as exc:
            self._report_corruption("meta", exc.path, exc.detail)
            return None

    def _read_layout_marker(
        self, marker: str, msg_dir: str
    ) -> Tuple[Optional[str], Optional[int]]:
        """The LAYOUT pin as ``(layout, n_shards)``: legacy markers
        are the raw layout string or its checksummed document (both
        mean 1 shard — flat directory); sharded directories carry a
        ``{"layout": ..., "shards": N}`` document.  Garbage content is
        corruption — fall back to the pre-marker heuristic (a
        census.json means the flat hash layout) rather than pinning
        the directory to an unreadable value."""
        try:
            with open(marker) as f:
                raw = f.read()
        except OSError:
            if os.path.exists(os.path.join(msg_dir, "census.json")):
                return "hash", 1
            return None, None
        if raw.strip() in ("lts", "hash"):
            return raw.strip(), 1
        try:
            val = atomicio.loads_checked(raw, marker)
        except atomicio.MetaCorruption as exc:
            self._report_corruption("meta", exc.path, exc.detail)
            val = None
        if val in ("lts", "hash"):
            return val, 1
        if isinstance(val, dict) and val.get("layout") in ("lts", "hash"):
            try:
                shards = int(val.get("shards", 1))
            except (TypeError, ValueError):
                shards = 1
            return val["layout"], max(1, shards)
        if val is not None:
            self._report_corruption(
                "meta", marker, f"unknown layout {val!r}"
            )
        if os.path.exists(os.path.join(msg_dir, "census.json")):
            return "hash", 1
        return None, None

    # ------------------------------------------------------------ gate

    def add_filter(self, flt: str) -> None:
        n = self._refs.get(flt, 0)
        if n == 0:
            self._gate.insert(flt, flt)
        self._refs[flt] = n + 1

    def remove_filter(self, flt: str) -> None:
        n = self._refs.get(flt, 0)
        if n <= 1:
            self._refs.pop(flt, None)
            self._gate.delete(flt)
        else:
            self._refs[flt] = n - 1

    def persist(self, msgs: List[Message]) -> int:
        """Store messages a persistent session could need on resume."""
        batch = []
        for msg in msgs:
            if msg.sys or (msg.qos == 0 and not self.store_qos0):
                continue
            if self._gate.match(msg.topic):
                batch.append(msg)
        if batch:
            counts = self.storage.store_batch(batch)
            # advance the group-commit watermark: the broker's
            # dispatch barrier ("always" mode) parks this window's
            # acks until a flush covers it.  Sharded: each shard's OWN
            # gate is marked with that shard's count — the barrier
            # then only waits on shards this window actually touched.
            if self._shard_gates is not None and counts:
                for idx, n in counts.items():
                    self._shard_gates[idx].mark_appended(n)
            else:
                self.gate.mark_appended(len(batch))
            if self.beamformer.has_parked():
                self.beamformer.notify({
                    self.storage.stream_key(m.topic) for m in batch
                })
        return len(batch)

    # ------------------------------------------------------ checkpoints

    def _state_path(self, clientid: str) -> str:
        import hashlib

        safe = hashlib.sha1(clientid.encode()).hexdigest()
        return os.path.join(self.state_dir, safe + ".json")

    def save(
        self,
        clientid: str,
        subs: Dict[str, object],
        expiry: float,
        now: Optional[float] = None,
    ) -> None:
        state = SessionState(
            clientid=clientid,
            subs={
                flt: opts.to_dict() if hasattr(opts, "to_dict") else dict(opts)
                for flt, opts in subs.items()
            },
            expiry=expiry,
            disconnected_at=now if now is not None else time.time(),
        )
        atomicio.atomic_write_json(
            self._state_path(clientid), state.to_json(),
            fsync=self.meta_fsync,
        )
        # group progress rides the checkpoint cadence (see
        # _advance_share_progress)
        self._flush_share_progress()

    def load(self, clientid: str) -> Optional[SessionState]:
        """Boot-restored state for a reconnecting client (None if the
        broker never restarted or no checkpoint exists/survives)."""
        state = self._boot_states.get(clientid)
        if state is not None and state.expired(time.time()):
            self.drop_checkpoint(clientid)
            return None
        return state

    def discard(self, clientid: str) -> None:
        self._boot_states.pop(clientid, None)
        try:
            os.unlink(self._state_path(clientid))
        except OSError:
            pass

    def drop_checkpoint(self, clientid: str) -> None:
        """Discard a boot checkpoint AND release the gate refs
        _load_states took for it (a plain discard leaks them when no
        live session inherits the filters)."""
        state = self._boot_states.get(clientid)
        if state is not None:
            self.remove_session_filters(state.subs, clientid)
            if self.on_drop is not None:
                self.on_drop(clientid)
        self.discard(clientid)

    def _load_states(self) -> None:
        for name in os.listdir(self.state_dir):
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.state_dir, name)
            obj = self._load_meta(path, "session checkpoint")
            if obj is None:
                continue  # already alarmed (missing is impossible:
                # listdir just returned it)
            try:
                state = SessionState.from_json(obj)
            except (ValueError, KeyError, TypeError):
                # parseable-but-wrong schema: the checkpoint cannot be
                # trusted — alarm, never silently pretend it was absent
                self._report_corruption(
                    "meta", path, "checkpoint schema unreadable"
                )
                continue
            self._boot_states[state.clientid] = state
            for flt in state.subs:
                share = T.parse_share(flt)
                self.add_filter(share.topic if share else flt)

    def purge_expired(self, now: Optional[float] = None) -> List[str]:
        now = now if now is not None else time.time()
        dead = [
            cid
            for cid, st in self._boot_states.items()
            if st.expired(now)
        ]
        for cid in dead:
            self.drop_checkpoint(cid)
        return dead

    # ---------------------------------------------------------- replay

    def remove_session_filters(
        self, subs: Dict[str, object], clientid: Optional[str] = None
    ) -> None:
        """Drop a discarded/expired session's filters from the gate (and
        its checkpoint must be discarded separately).  $share filters
        release their REAL-topic ref (mirroring _load_states) and,
        when the clientid is known, leave the durable group — a ghost
        member would keep streams rendezvous-assigned to a session
        that can never replay them."""
        for flt in subs:
            share = T.parse_share(flt)
            self.remove_filter(share.topic if share else flt)
            if share is not None and clientid is not None:
                self.shared_leave(flt, clientid)

    def gc(self, cutoff_ts_us: int) -> int:
        """Retention pass over the message log, honoring GENERATION
        PINS: a detached session mid-replay pins, per shard, every
        generation at/after its replay cursor — GC reclaims only
        unpinned generations, so retention can never pull a segment
        out from under a resuming session's cursor (the property the
        pin suite tests).  Sessions whose replay has not STARTED have
        no cursors yet; they conservatively clamp the time cutoff to
        their disconnect instant instead."""
        floors, ts_floor = self._gc_pins()
        cutoff = cutoff_ts_us
        if ts_floor is not None and ts_floor < cutoff:
            cutoff = ts_floor
        return self.storage.gc_pinned(cutoff, floors)

    def _gc_pins(self) -> Tuple[Dict[int, int], Optional[int]]:
        """(per-store generation floors, time floor) derived from the
        boot states: a state with materialized cursors pins each
        cursor's generation (`seg_for`); one whose replay has not
        started pins by TIME (everything since its disconnect)."""
        floors: Dict[int, int] = {}
        ts_floor: Optional[int] = None
        for state in self._boot_states.values():
            if state.iters is None:
                t = int(state.disconnected_at * 1e6)
                if ts_floor is None or t < ts_floor:
                    ts_floor = t
                continue
            for cursors in state.iters.values():
                for cur in cursors:
                    it = IterRef.from_json(cur)
                    seg = self.storage.seg_for(
                        it.stream, it.ts, it.seq
                    )
                    if seg < 0:
                        continue  # exhausted: pins nothing
                    store = it.stream.store
                    if store not in floors or seg < floors[store]:
                        floors[store] = seg
        return floors, ts_floor

    def sync(self) -> None:
        """Full flush: group fsync (through the gate, so it is counted
        and releases any parked acks) + metadata checkpoint."""
        self.gate.sync_now()
        self.checkpoint_meta()

    def checkpoint_meta(self) -> None:
        """Metadata checkpoint cadence (the broker tick): storage
        caches (census / LTS index) + dirty share progress."""
        self.storage.save_meta()
        self._flush_share_progress()

    def sync_soon(self) -> None:
        """Interval-mode flush kick (asynchronous when a loop runs)."""
        self.gate.sync_soon()

    async def wait_durable(self) -> None:
        """The dispatch loop's group-commit barrier (`always` mode)."""
        await self.gate.wait_durable()

    def sync_stats(self) -> Dict:
        """The durability ops surface (/api/v5/nodes, ctl status,
        /metrics gauges): rolled-up totals, the census-rebuild gauge,
        and — sharded — a per-shard breakdown (each shard's own
        unsynced watermark, parked windows and quarantine counts)."""
        out = {"fsync": self.fsync_mode}
        out.update(self.gate.stats())
        out.update(self.storage.corruption_stats())
        out["meta_corruption"] = self.corruption_counts.get("meta", 0)
        out["shards"] = self.n_shards
        # numeric top-level fields so /metrics emits them as gauges
        out["meta_rebuild"] = 1 if self.storage.rebuilding else 0
        prog = self.storage.rebuild_progress
        out["meta_rebuild_scanned"] = prog.get("scanned", 0)
        out["meta_rebuild_total"] = prog.get("total", 0)
        if self._shard_gates is not None:
            rows = []
            for i, (g, st) in enumerate(
                zip(self._shard_gates, self.storage.stores)
            ):
                row = {"shard": i}
                row.update(g.stats())
                row.update(st.corruption_stats())
                rows.append(row)
            out["per_shard"] = rows
        return out

    def _save_share_members(self) -> None:
        atomicio.atomic_write_json(
            self._share_path, self._share_members,
            fsync=self.meta_fsync,
        )

    def shared_join(self, share_flt: str, clientid: str) -> None:
        members = self._share_members.setdefault(share_flt, [])
        if clientid not in members:
            members.append(clientid)
            self._save_share_members()

    def shared_leave(self, share_flt: str, clientid: str) -> None:
        members = self._share_members.get(share_flt)
        if members and clientid in members:
            members.remove(clientid)
            if not members:
                del self._share_members[share_flt]
            self._save_share_members()

    def _advance_share_progress(self, share_flt: str,
                                it: IterRef) -> None:
        """In-MEMORY only: the consumed interval lives in session
        mqueues until a checkpoint persists it, so the progress file
        is flushed together with checkpoints (`save`/`close`) — a
        crash mid-replay re-replays (at-least-once) instead of
        skipping undelivered messages (the broker.py replay-cursor
        invariant, applied group-wide)."""
        prog = self._share_progress.setdefault(share_flt, {})
        key = _stream_pkey(it.stream)
        cur = prog.get(key)
        if cur is None or (it.ts, it.seq) > (cur[0], cur[1]):
            prog[key] = [it.ts, it.seq]
            self._share_prog_dirty = True

    def _flush_share_progress(self) -> None:
        if not getattr(self, "_share_prog_dirty", False):
            return
        atomicio.atomic_write_json(
            self._share_prog_path, self._share_progress,
            fsync=self.meta_fsync,
        )
        self._share_prog_dirty = False

    def shared_group_members(self, share_flt: str) -> List[str]:
        """Members of this exact $share filter: the PERSISTED registry
        (survives restarts and stays stable across the whole replay
        sequence — a member leaving _boot_states on ITS resume must
        not shrink the assignment its peers derive), plus any
        checkpointed stragglers; sorted, so every member computes the
        same stream split."""
        members = set(self._share_members.get(share_flt, ()))
        for cid, st in self._boot_states.items():
            if share_flt in st.subs:
                members.add(cid)
        return sorted(members)

    def _replay_read(
        self, it: IterRef, n: int
    ) -> Tuple[IterRef, List[Message], bool]:
        """ONE storage read on the replay path — the ``ds.replay.read``
        failpoint seam (chaos: a DS read failing/stalling exactly when
        a reconnect storm replays millions of backlogs).  Returns
        ``(iterator, messages, ok)``:

          * ``error``/``panic`` raise out to the caller's recovery
            (the resume scheduler backs the session off and retries);
          * ``delay`` stalls the read (storm pacing under slow disk);
          * ``drop`` returns ``ok=False`` with the cursor UNCHANGED —
            a dropped read must never look like stream exhaustion, or
            replay would silently skip the interval behind it (QoS1
            loss); callers treat it like a budget stop and retry;
          * ``duplicate`` returns the batch with the PRE-read cursor,
            so the next read re-reads it — at-least-once duplication
            through the mid-dedup/inflight path.
        """
        if failpoints.enabled:
            act = failpoints.evaluate(  # brokerlint: ignore[ASYNC101] — delay action is the chaos point; production paths run this from the scheduler's bounded round
                "ds.replay.read",
                key=f"{it.stream.shard}:{it.topic_filter}",
            )
            if act == "drop":
                return it, [], False
            if act == "duplicate":
                _it2, msgs = self.storage.next(it, n)
                return it, msgs, True
        it2, msgs = self.storage.next(it, n)
        return it2, msgs, True

    def _ensure_iters(self, state: SessionState) -> None:
        """Lazily materialize the state's per-(filter, stream) replay
        cursors (shared by the scalar and windowed replay paths).
        Built into a LOCAL dict and assigned in one step: a storage
        fault midway must leave ``state.iters`` None, or the next call
        would skip the missing filters' whole intervals (loss)."""
        if state.iters is None:
            since_us = int(state.disconnected_at * 1e6)
            iters: Dict[str, List[Dict]] = {}
            for flt in state.subs:
                share = T.parse_share(flt)
                if share is None:
                    iters[flt] = [
                        self.storage.make_iterator(
                            s, flt, since_us
                        ).to_json()
                        for s in self.storage.get_streams(flt, since_us)
                    ]
                    continue
                # DURABLE SHARED SUBS (emqx_ds_shared_sub): the group's
                # offline interval replays EXACTLY ONCE across its
                # persistent members — each DS stream is assigned to
                # one member by rendezvous hash over the member set, so
                # every member independently derives the same split
                # without a live leader (the reference elects one;
                # deterministic assignment is this fs-backend's
                # equivalent)
                members = self.shared_group_members(flt)
                streams = [
                    s for s in self.storage.get_streams(
                        share.topic, since_us
                    )
                    if not members
                    or rendezvous_pick(
                        f"{share.group}:{_stream_pkey(s)}", members, 1
                    )[0] == state.clientid
                ]
                prog = self._share_progress.get(flt, {})
                its = []
                for s in streams:
                    it = self.storage.make_iterator(
                        s, share.topic, since_us
                    )
                    p = prog.get(_stream_pkey(s))
                    if p and (p[0], p[1]) > (it.ts, it.seq):
                        # group already consumed past here
                        it = IterRef(
                            stream=s, topic_filter=share.topic,
                            ts=p[0], seq=p[1],
                        )
                    its.append(it.to_json())
                iters[flt] = its
            state.iters = iters

    def replay_chunk(
        self, state: SessionState, max_msgs: int = 1024
    ) -> Tuple[List[Tuple[str, Message]], bool]:
        """Up to ``max_msgs`` messages persisted since the checkpoint,
        advancing the state's per-(filter, stream) iterator cursors.
        A caller that durably hands off each chunk may checkpoint the
        cursors between chunks (`save_state`) so a crash resumes
        mid-interval; a caller that only buffers in memory (the
        broker's resume path) must NOT, or a crash would skip the
        buffered chunk — chunking still bounds its replay memory.
        Returns ``(messages, done)``; message ids dedup across
        overlapping filters within one run (at-least-once across a
        crash)."""
        out, done, _nbytes, _err = self._replay_one(
            state, max_msgs, None
        )
        return out, done

    def _replay_one(
        self,
        state: SessionState,
        max_msgs: int,
        cache: Optional[Dict],
    ) -> Tuple[List[Tuple[str, Message]], bool, int,
               Optional[BaseException]]:
        """One session's replay round: the cursor walk shared by the
        scalar `replay_chunk` and the windowed `replay_chunk_many`.

        With ``cache`` (windowed mode) reads are a fixed 256 records
        and shared through it — sessions whose cursors sit at the same
        (stream, filter, position) cost ONE storage read, the
        mass-reconnect shape where thousands of sessions checkpointed
        at the same outage walk the same streams.  A chunk may then
        overshoot ``max_msgs`` by up to one read batch (cursors move
        batch-at-a-time; messages a read returned cannot be dropped
        once the cursor passed them).  Without a cache the reads size
        themselves to the remaining budget — `replay_chunk`'s exact
        legacy shape.  Message ORDER per session is identical either
        way: (filter, stream, record) order, which is what lets the
        windowed dispatch be property-tested bit-identical against
        the scalar resume wire.

        Returns ``(messages, done, payload_bytes_read, error)``:
        ``error`` is the exception of a read that FAULTED mid-round —
        the already-read prefix is still returned (its dedup/cursor
        state is committed and correct) and the faulted cursor is
        UNCHANGED, so the retry re-reads exactly the unread region.
        Raising past the mutations instead would poison the dedup
        set: the discarded prefix's mids would read as "seen" on
        retry, the cursor would skip them, and the interval would be
        silently lost.  `FailpointPanic` (process death) still flies
        — in-memory state dies with the process."""
        self._ensure_iters(state)
        seen = state._replay_seen
        out: List[Tuple[str, Message]] = []
        nbytes = 0
        for flt, cursors in state.iters.items():
            is_shared = T.parse_share(flt) is not None
            i = 0
            while i < len(cursors):
                it = IterRef.from_json(cursors[i])
                exhausted = False
                while len(out) < max_msgs:
                    try:
                        if cache is None:
                            it2, msgs, ok = self._replay_read(
                                it, min(256, max_msgs - len(out))
                            )
                            mids = mbytes = None
                        else:
                            ckey = (
                                it.stream.store, it.stream.shard,
                                it.topic_filter, it.ts, it.seq,
                            )
                            hit = cache.get(ckey)
                            if hit is None:
                                it2, msgs, ok = self._replay_read(
                                    it, 256
                                )
                                hit = cache[ckey] = (
                                    it2, msgs, ok,
                                    frozenset(m.mid for m in msgs),
                                    sum(
                                        len(m.payload) + len(m.topic)
                                        for m in msgs
                                    ),
                                )
                            it2, msgs, ok, mids, mbytes = hit
                    except Exception as exc:
                        # fault mid-round: commit the prefix, keep
                        # the cursor (see docstring) — never raise
                        # past the dedup/cursor mutations
                        cursors[i] = it.to_json()
                        return out, False, nbytes, exc
                    if not ok:
                        # dropped read (chaos): NOT exhaustion — keep
                        # the cursor and come back, or the interval
                        # behind it would be skipped
                        break
                    dup = it2.ts == it.ts and it2.seq == it.seq
                    it = it2
                    if not msgs:
                        exhausted = True
                        break
                    if mids is not None and seen.isdisjoint(mids):
                        # batch fast path (the mass-reconnect shape:
                        # thousands of sessions consuming the same
                        # cached batches): no overlap with this
                        # session's seen-set, so the whole batch
                        # appends in one C-speed extend
                        seen.update(mids)
                        out.extend((flt, m) for m in msgs)
                        nbytes += mbytes
                        continue
                    for msg in msgs:
                        if msg.mid not in seen:
                            seen.add(msg.mid)
                            out.append((flt, msg))
                            nbytes += len(msg.payload) + len(msg.topic)
                    if dup:
                        # duplicate-action read: cursor did not move;
                        # stop this cursor for the round so an armed
                        # unlimited duplicate cannot livelock the loop
                        break
                if is_shared:
                    # group progress: the interval up to this cursor is
                    # CONSUMED for the whole group — survivors must not
                    # re-read it after membership churn
                    self._advance_share_progress(flt, it)
                if exhausted:
                    cursors.pop(i)
                else:  # budget hit / blocked read: keep progress in
                    # memory, come back later
                    cursors[i] = it.to_json()
                    return out, False, nbytes, None
        state.iters = {f: c for f, c in state.iters.items() if c}
        return out, not any(state.iters.values()), nbytes, None

    def replay_chunk_many(
        self,
        states: List[SessionState],
        max_msgs: int = 1024,
        byte_budget: Optional[int] = None,
    ) -> Tuple[Dict[str, List[Tuple[str, Message]]], Dict[str, bool],
               int, Dict[str, str]]:
        """Windowed multi-session replay: one pass pulls up to
        ``max_msgs`` messages for EACH of ``states``, sharing storage
        reads across sessions whose cursors sit at the same (stream,
        filter, position) — the beamformer idea applied to resume:
        coherent readers are served by one sweep instead of one read
        cycle each.  ``byte_budget`` caps the total payload bytes one
        call pulls (the resume scheduler's per-round budget); sessions
        past the cap read nothing this round and simply go next round.

        Returns ``(chunks, done, bytes_read, errors)``: per-clientid
        message lists in exactly the order `replay_chunk` would
        produce them, per-clientid completion flags, the payload byte
        total, and per-clientid error strings for sessions whose read
        raised (failpoint or real IO fault) — an error on one
        session's stream must not abort the other thousand resumes in
        the window.  Cursor discipline is `replay_chunk`'s: cursors
        advance in MEMORY only; the caller checkpoints nothing until
        its window is durably handed off (a crash re-replays —
        at-least-once, never loss)."""
        cache: Dict = {}
        chunks: Dict[str, List[Tuple[str, Message]]] = {}
        done: Dict[str, bool] = {}
        errors: Dict[str, str] = {}
        total = 0
        for state in states:
            if byte_budget is not None and total >= byte_budget:
                break  # over budget: the rest go next round
            try:
                out, fin, nbytes, err = self._replay_one(
                    state, max_msgs, cache
                )
            except Exception as exc:
                # defensive only: read faults fail SOFT inside
                # _replay_one (partial prefix committed + returned);
                # panic (BaseException) flies
                errors[state.clientid] = repr(exc)
                continue
            chunks[state.clientid] = out
            done[state.clientid] = fin
            total += nbytes
            if err is not None:
                # partial round: the prefix in chunks[cid] is good and
                # MUST be delivered; the caller backs the session off
                # before the next read
                errors[state.clientid] = repr(err)
        return chunks, done, total, errors

    def save_state(self, state: SessionState) -> None:
        """Persist a state object as-is (mid-replay checkpoint)."""
        atomicio.atomic_write_json(
            self._state_path(state.clientid), state.to_json(),
            fsync=self.meta_fsync,
        )

    def replay(
        self, state: SessionState
    ) -> List[Tuple[str, Message]]:
        """Whole-interval replay (chunked under the hood)."""
        out: List[Tuple[str, Message]] = []
        while True:
            msgs, done = self.replay_chunk(state)
            out.extend(msgs)
            if done:
                return out

    def close(self) -> None:
        self._flush_share_progress()
        try:
            # clean shutdown leaves the log durable in every mode (a
            # mode says how much a POWER CUT may take, not a shutdown)
            self.gate.sync_now()
        except Exception:
            log.exception("final ds sync failed")
        self.gate.stop()
        self.storage.close()
