"""Durable (persistent) sessions over the DS storage engine.

The `emqx_persistent_session_ds` + `emqx_persistent_message` slice
(/root/reference/apps/emqx/src/emqx_persistent_session_ds.erl,
emqx_persistent_message.erl:98-113): messages matching a persistent
session's subscriptions are persisted to DS, session metadata is
checkpointed on disconnect, and a reconnect after a broker restart
rebuilds the session and replays the missed interval from storage.

Division of labor with the in-memory session: while the broker stays
up, a detached session's messages queue in its mqueue (fast path).  DS
replay serves the case the mqueue cannot: the broker process restarted
and in-memory state is gone.  The persistence *gate* mirrors
emqx_persistent_message:persist/1 — a message is stored only when some
persistent session's filter matches it.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Tuple

from .. import topic as T
from ..engine import MatchEngine
from ..message import Message
from .builtin_local import LocalStorage


class SessionState:
    """One checkpointed session (the state emqx_persistent_session_ds
    keeps in DS session tables)."""

    def __init__(
        self,
        clientid: str,
        subs: Dict[str, Dict],
        expiry: float,
        disconnected_at: float,
    ) -> None:
        self.clientid = clientid
        self.subs = subs  # filter -> SubOpts-as-dict
        self.expiry = expiry
        self.disconnected_at = disconnected_at

    def expired(self, now: float) -> bool:
        return now - self.disconnected_at > self.expiry

    def to_json(self) -> Dict:
        return {
            "clientid": self.clientid,
            "subs": self.subs,
            "expiry": self.expiry,
            "disconnected_at": self.disconnected_at,
        }

    @staticmethod
    def from_json(obj: Dict) -> "SessionState":
        return SessionState(
            clientid=obj["clientid"],
            subs=obj["subs"],
            expiry=obj["expiry"],
            disconnected_at=obj["disconnected_at"],
        )


class DurableSessions:
    def __init__(
        self,
        directory: str,
        n_streams: int = 16,
        store_qos0: bool = False,
    ) -> None:
        self.storage = LocalStorage(
            os.path.join(directory, "messages"), n_streams=n_streams
        )
        self.state_dir = os.path.join(directory, "sessions")
        os.makedirs(self.state_dir, exist_ok=True)
        self.store_qos0 = store_qos0
        # persistence gate: filters of every persistent session (live or
        # detached), refcounted; host matching is fine at this rate
        self._gate = MatchEngine(use_device=False)
        self._refs: Dict[str, int] = {}
        # detached states restored from disk at boot
        self._boot_states: Dict[str, SessionState] = {}
        # fired (with the clientid) when a boot checkpoint is dropped —
        # the broker uses it to retract the routes it advertised for
        # the detached session
        self.on_drop = None
        self._load_states()

    def boot_states(self) -> List[SessionState]:
        return list(self._boot_states.values())

    def has_checkpoint(self, clientid: str) -> bool:
        return clientid in self._boot_states

    # ------------------------------------------------------------ gate

    def add_filter(self, flt: str) -> None:
        n = self._refs.get(flt, 0)
        if n == 0:
            self._gate.insert(flt, flt)
        self._refs[flt] = n + 1

    def remove_filter(self, flt: str) -> None:
        n = self._refs.get(flt, 0)
        if n <= 1:
            self._refs.pop(flt, None)
            self._gate.delete(flt)
        else:
            self._refs[flt] = n - 1

    def persist(self, msgs: List[Message]) -> int:
        """Store messages a persistent session could need on resume."""
        batch = []
        for msg in msgs:
            if msg.sys or (msg.qos == 0 and not self.store_qos0):
                continue
            if self._gate.match(msg.topic):
                batch.append(msg)
        if batch:
            self.storage.store_batch(batch)
        return len(batch)

    # ------------------------------------------------------ checkpoints

    def _state_path(self, clientid: str) -> str:
        import hashlib

        safe = hashlib.sha1(clientid.encode()).hexdigest()
        return os.path.join(self.state_dir, safe + ".json")

    def save(
        self,
        clientid: str,
        subs: Dict[str, object],
        expiry: float,
        now: Optional[float] = None,
    ) -> None:
        state = SessionState(
            clientid=clientid,
            subs={
                flt: opts.to_dict() if hasattr(opts, "to_dict") else dict(opts)
                for flt, opts in subs.items()
            },
            expiry=expiry,
            disconnected_at=now if now is not None else time.time(),
        )
        tmp = self._state_path(clientid) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state.to_json(), f)
        os.replace(tmp, self._state_path(clientid))

    def load(self, clientid: str) -> Optional[SessionState]:
        """Boot-restored state for a reconnecting client (None if the
        broker never restarted or no checkpoint exists/survives)."""
        state = self._boot_states.get(clientid)
        if state is not None and state.expired(time.time()):
            self.drop_checkpoint(clientid)
            return None
        return state

    def discard(self, clientid: str) -> None:
        self._boot_states.pop(clientid, None)
        try:
            os.unlink(self._state_path(clientid))
        except OSError:
            pass

    def drop_checkpoint(self, clientid: str) -> None:
        """Discard a boot checkpoint AND release the gate refs
        _load_states took for it (a plain discard leaks them when no
        live session inherits the filters)."""
        state = self._boot_states.get(clientid)
        if state is not None:
            self.remove_session_filters(state.subs)
            if self.on_drop is not None:
                self.on_drop(clientid)
        self.discard(clientid)

    def _load_states(self) -> None:
        for name in os.listdir(self.state_dir):
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.state_dir, name)) as f:
                    state = SessionState.from_json(json.load(f))
            except (OSError, ValueError, KeyError):
                continue
            self._boot_states[state.clientid] = state
            for flt in state.subs:
                if not T.parse_share(flt):
                    self.add_filter(flt)

    def purge_expired(self, now: Optional[float] = None) -> List[str]:
        now = now if now is not None else time.time()
        dead = [
            cid
            for cid, st in self._boot_states.items()
            if st.expired(now)
        ]
        for cid in dead:
            self.drop_checkpoint(cid)
        return dead

    # ---------------------------------------------------------- replay

    def remove_session_filters(self, subs: Dict[str, object]) -> None:
        """Drop a discarded/expired session's filters from the gate (and
        its checkpoint must be discarded separately)."""
        for flt in subs:
            if T.parse_share(flt) is None:
                self.remove_filter(flt)

    def gc(self, cutoff_ts_us: int) -> int:
        """Retention pass over the message log."""
        return self.storage.gc(cutoff_ts_us)

    def sync(self) -> None:
        self.storage.sync()

    def replay(
        self, state: SessionState
    ) -> List[Tuple[str, Message]]:
        """Messages persisted since the checkpoint, per matching filter,
        deduped by message id across overlapping filters; ordered by
        storage order within each stream."""
        since_us = int(state.disconnected_at * 1e6)
        seen: set = set()
        out: List[Tuple[str, Message]] = []
        for flt in state.subs:
            if T.parse_share(flt):
                continue  # shared subs don't replay ([MQTT-4.8.2-27])
            for stream in self.storage.get_streams(flt, since_us):
                it = self.storage.make_iterator(stream, flt, since_us)
                while True:
                    it, msgs = self.storage.next(it, 256)
                    if not msgs:
                        break
                    for msg in msgs:
                        if msg.mid not in seen:
                            seen.add(msg.mid)
                            out.append((flt, msg))
        return out

    def close(self) -> None:
        self.storage.close()
