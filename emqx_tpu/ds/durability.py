"""Group-commit fsync gate: the "acked means durable" machinery.

EMQX's durable sessions get crash safety from RocksDB's WAL + ra raft
commit; our dslog engine appends without fsync on the hot path, so
before this gate an acked QoS1 publish could evaporate at power fail.
The naive fix — fsync per message — costs a disk round trip (~3-4 ms
on commodity ext4) per publish.  The house answer is the same shape as
every other hot-path cost in this repo: batch it onto the dispatch
window.  `SyncGate` amortizes ONE fsync per window, coalescing
concurrent windows onto the same disk flush:

  * every persisted append advances the ``appended`` watermark;
  * a window whose PUBACKs must imply durability parks on
    `wait_durable` — the gate snapshots the watermark, runs ONE
    ``dslog_sync`` in an executor, and releases every parked window
    whose appends that flush covered (windows that arrive while a
    flush is in flight simply ride the next one: two disk flushes
    bound ANY number of concurrent windows);
  * a sync fault (disk error, `ds.store.sync` chaos) keeps the parked
    windows parked and retries with backoff — PUBACKs are delayed,
    never issued un-durably and never dropped;
  * `sync_now` is the synchronous entry for the loop-less paths (the
    non-batched publish path, the broker tick's interval flush,
    shutdown).

The gate is mode-agnostic: `DurableSessions` always owns one (the
watermarks feed the ``ds.unsynced`` gauge in every mode); only the
``always`` fsync mode parks acks on it.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Callable, List, Optional, Tuple

_RETRY_BASE = 0.05
_RETRY_MAX = 1.0


class SyncGate:
    def __init__(
        self,
        sync_fn: Callable[[], None],
        on_sync: Optional[Callable[[float], None]] = None,
        on_error: Optional[Callable[[BaseException], None]] = None,
    ) -> None:
        self._sync_fn = sync_fn
        # fired with the flush duration (seconds) after every
        # successful sync — the broker wires ds.sync.count + the
        # profiler's ds_sync stage here
        self.on_sync = on_sync
        self.on_error = on_error
        self._lock = threading.Lock()
        self._appended = 0  # records persisted (watermark)
        self._synced = 0    # watermark covered by a completed fsync
        self._waiters: List[Tuple[int, "asyncio.Future"]] = []
        self._task: Optional["asyncio.Task"] = None
        self.sync_count = 0
        self.sync_errors = 0
        self._closed = False

    # ------------------------------------------------------ watermarks

    def mark_appended(self, n: int) -> int:
        """Record ``n`` appended records; returns the new watermark."""
        with self._lock:
            self._appended += n
            return self._appended

    @property
    def appended(self) -> int:
        """The append watermark (callers snapshot it around a window
        to ask "did THIS window capture anything?")."""
        return self._appended

    @property
    def dirty(self) -> bool:
        """Records appended that no completed fsync covers yet."""
        return self._appended > self._synced

    @property
    def unsynced(self) -> int:
        return max(0, self._appended - self._synced)

    @property
    def parked(self) -> int:
        """Windows currently parked on `wait_durable` (their acks are
        owed to publishers but held for the covering flush)."""
        return len(self._waiters)

    def stats(self) -> dict:
        with self._lock:
            return {
                "sync_count": self.sync_count,
                "sync_errors": self.sync_errors,
                "unsynced": max(0, self._appended - self._synced),
                "parked": len(self._waiters),
            }

    # ----------------------------------------------------- sync paths

    def sync_now(self) -> None:
        """Blocking group flush: everything appended so far is durable
        when this returns.  Thread-safe against the async worker (the
        underlying fsync serializes on the store's own mutex)."""
        with self._lock:
            target = self._appended
            if target <= self._synced:
                return
        t0 = time.perf_counter()
        try:
            self._sync_fn()
        except Exception:
            with self._lock:
                self.sync_errors += 1
            raise
        self._finish(target, time.perf_counter() - t0)

    def sync_soon(self) -> None:
        """Kick an asynchronous flush if anything is unsynced: the
        broker tick's interval-mode entry.  Falls back to the blocking
        flush when no event loop is running (tests driving tick()
        synchronously)."""
        if not self.dirty:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            self.sync_now()
            return
        with self._lock:
            if self._task is None or self._task.done():
                self._task = loop.create_task(self._drain())

    async def wait_durable(self) -> None:
        """Park until a flush covers every record appended before this
        call — the dispatch loop's group-commit barrier (``always``
        mode).  Returns immediately when nothing is unsynced, so
        non-persistent traffic pays one watermark compare."""
        loop = asyncio.get_running_loop()
        # lock-ownership: watermark/waiter-list mutations only — every
        # critical section is a few integer/list ops, never IO (the
        # fsync itself runs OUTSIDE the lock, in the executor), so a
        # thread holding it cannot stall the loop measurably
        with self._lock:
            target = self._appended
            if target <= self._synced:
                return
            fut: asyncio.Future = loop.create_future()
            self._waiters.append((target, fut))
            if self._task is None or self._task.done():
                self._task = loop.create_task(self._drain())
        await fut

    async def _drain(self) -> None:
        """The sync worker: one executor fsync per round, covering
        every waiter parked at round start; a fault backs off and
        retries with the waiters still parked."""
        backoff = _RETRY_BASE
        loop = asyncio.get_running_loop()
        idle_flushed = False  # one no-waiter round per kick (interval
        # mode: the next tick re-kicks; without this a steady append
        # stream would fsync back-to-back instead of per interval)
        while True:
            # lock-ownership: see wait_durable — integer/list ops only
            with self._lock:
                if self._closed or (
                    not self._waiters and (not self.dirty or idle_flushed)
                ):
                    self._task = None
                    return
                idle_flushed = not self._waiters
                target = self._appended
            t0 = time.perf_counter()
            try:
                await loop.run_in_executor(None, self._sync_fn)
            except Exception as exc:
                # lock-ownership: see wait_durable — counter bump only
                with self._lock:
                    self.sync_errors += 1
                if self.on_error is not None:
                    self.on_error(exc)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, _RETRY_MAX)
                continue
            backoff = _RETRY_BASE
            self._finish(target, time.perf_counter() - t0)

    def _finish(self, target: int, dur_s: float) -> None:
        done = []
        with self._lock:
            if target > self._synced:
                self._synced = target
            self.sync_count += 1
            keep = []
            for wm, fut in self._waiters:
                (done if wm <= self._synced else keep).append((wm, fut))
            self._waiters = keep
        if self.on_sync is not None:
            self.on_sync(dur_s)
        for _wm, fut in done:
            # sync_now may run off-loop (tick fallback, shutdown):
            # futures resolve on their owning loop either way
            try:
                fut.get_loop().call_soon_threadsafe(
                    _resolve_waiter, fut
                )
            except RuntimeError:
                pass  # owning loop already closed

    # ------------------------------------------------------- lifecycle

    def stop(self) -> None:
        """Cancel the worker and fail any parked windows (broker
        shutdown: their batch futures are being failed anyway)."""
        with self._lock:
            self._closed = True
            task, self._task = self._task, None
            waiters, self._waiters = self._waiters, []
        if task is not None and not task.done():
            task.cancel()
        for _wm, fut in waiters:
            # cancel (not fail): an abandoned window's barrier must not
            # leave a never-retrieved exception behind
            fut.cancel()


def _resolve_waiter(fut: "asyncio.Future") -> None:
    if not fut.done():
        fut.set_result(None)


class GateGroup:
    """Facade over the per-shard SyncGates of a sharded store, keeping
    the broker's single-gate contract (``dur.gate.*``) intact.

    Each shard owns an independent append watermark and fsync barrier
    — that independence is the whole point of sharding (N disks' worth
    of group-commit concurrency) — but ACK CONSISTENCY is cross-shard:
    a dispatch window's barrier must cover every shard its appends
    touched.  `wait_durable` gathers ALL member gates' barriers; a
    clean gate returns immediately (one watermark compare), so the
    cost is one parked future per DIRTY shard and the window's acks
    can never straddle shards inconsistently (crash-suite-tested: a
    crash between two shards' fsyncs un-acks the whole window).

    There is deliberately no `mark_appended` here: the store's
    persist path marks each shard's own gate with that shard's count —
    the group only aggregates.
    """

    def __init__(self, gates: List[SyncGate]) -> None:
        self._gates = list(gates)

    def __iter__(self):
        return iter(self._gates)

    def __len__(self) -> int:
        return len(self._gates)

    @property
    def gates(self) -> List[SyncGate]:
        return self._gates

    # ---------------------------------------------------- aggregates

    @property
    def appended(self) -> int:
        return sum(g.appended for g in self._gates)

    @property
    def dirty(self) -> bool:
        return any(g.dirty for g in self._gates)

    @property
    def unsynced(self) -> int:
        return sum(g.unsynced for g in self._gates)

    @property
    def parked(self) -> int:
        return sum(g.parked for g in self._gates)

    @property
    def sync_count(self) -> int:
        return sum(g.sync_count for g in self._gates)

    @property
    def sync_errors(self) -> int:
        return sum(g.sync_errors for g in self._gates)

    def stats(self) -> dict:
        return {
            "sync_count": self.sync_count,
            "sync_errors": self.sync_errors,
            "unsynced": self.unsynced,
            "parked": self.parked,
        }

    # ----------------------------------------------------- callbacks

    @property
    def on_sync(self):
        return self._gates[0].on_sync if self._gates else None

    @on_sync.setter
    def on_sync(self, fn) -> None:
        for g in self._gates:
            g.on_sync = fn

    @property
    def on_error(self):
        return self._gates[0].on_error if self._gates else None

    @on_error.setter
    def on_error(self, fn) -> None:
        for g in self._gates:
            g.on_error = fn

    # ---------------------------------------------------- sync paths

    def sync_now(self) -> None:
        """Blocking flush of every dirty shard (tick fallback,
        shutdown).  Per-shard: a clean shard costs one compare."""
        for g in self._gates:
            g.sync_now()

    def sync_soon(self) -> None:
        for g in self._gates:
            g.sync_soon()

    async def wait_durable(self) -> None:
        """The cross-shard group-commit barrier: resolve only when a
        flush on EVERY shard covers the records appended to it before
        this call.  Dirty shards park concurrently — wall time is the
        slowest single fsync, not the sum."""
        dirty = [g for g in self._gates if g.dirty]
        if not dirty:
            return
        if len(dirty) == 1:
            await dirty[0].wait_durable()
            return
        await asyncio.gather(*(g.wait_durable() for g in dirty))

    def stop(self) -> None:
        for g in self._gates:
            g.stop()
