"""Durable-session replication across cluster nodes.

The role of `emqx_ds_builtin_raft` (/root/reference/apps/
emqx_ds_builtin_raft/src/emqx_ds_replication_layer.erl: replicated DS
shards so node loss doesn't lose durable messages), deliberately
simplified: instead of Raft consensus, each node replicates the durable
state a persistent session depends on — its checkpoint and its gated
message batches — to a deterministic BUDDY peer (rendezvous hash per
clientid over alive peers).  When a client reconnects elsewhere after
its home node died, the new node restores from its local replica store.

Consistency model (documented, weaker than the reference's Raft):
asynchronous replication, last-write-wins per clientid; a crash between
local persist and the replication cast can lose the tail batch.  That
trades the reference's quorum latency for zero write-path round-trips,
and converts "node loss = total session loss" into "node loss loses at
most the un-replicated tail".
"""

from __future__ import annotations

import hashlib
import logging
import time
from typing import Dict, List, Optional

log = logging.getLogger("emqx_tpu.ds.replication")


def rendezvous_pick(key: str, nodes: List[str], k: int = 1) -> List[str]:
    """Highest-random-weight hashing: stable buddy choice that only
    moves keys owned by a node that joined/left."""
    scored = sorted(
        nodes,
        key=lambda n: hashlib.blake2b(
            f"{key}\x00{n}".encode(), digest_size=8
        ).digest(),
        reverse=True,
    )
    return scored[:k]


class ReplicaStore:
    """This node's copy of OTHER nodes' persistent sessions: checkpoint
    + pending messages per clientid, consulted when a client lands here
    after its home node died."""

    def __init__(self, cap_per_client: int = 10_000) -> None:
        self.cap_per_client = cap_per_client
        # clientid -> {"subs", "expiry", "saved_at", "queued"}
        self._checkpoints: Dict[str, Dict] = {}
        # clientid -> wire-dict message buffers (+ first-append stamp,
        # so orphaned buffers — messages without a checkpoint, e.g.
        # after a buddy reassignment — age out instead of leaking)
        self._messages: Dict[str, List[Dict]] = {}
        self._msg_since: Dict[str, float] = {}

    def store_checkpoint(self, clientid: str, state: Dict) -> None:
        self._checkpoints[clientid] = state

    def drop(self, clientid: str) -> None:
        self._checkpoints.pop(clientid, None)
        self._messages.pop(clientid, None)
        self._msg_since.pop(clientid, None)

    def append_messages(self, clientid: str, msgs: List[Dict]) -> None:
        """Messages arrive (and stay) in wire-dict form — only a
        restore pays the decode."""
        buf = self._messages.setdefault(clientid, [])
        self._msg_since.setdefault(clientid, time.time())
        buf.extend(msgs)
        del buf[: -self.cap_per_client]

    def peek(self, clientid: str) -> Optional[Dict]:
        """Non-destructive view in the restore shape (used by remote
        ds_take: the claimant's session-open op performs the drop)."""
        state = self._checkpoints.get(clientid)
        if state is None:
            return None
        return {
            "subs": dict(state.get("subs", {})),
            "expiry": state.get("expiry", 0),
            "queued": list(state.get("queued", []))
            + list(self._messages.get(clientid, [])),
            "awaiting_rel": [],
        }

    def take(self, clientid: str) -> Optional[Dict]:
        """Claim a replica for restore (removes it).  The returned dict
        matches the takeover-export shape, so Broker.import_session
        consumes both."""
        state = self._checkpoints.pop(clientid, None)
        if state is None:
            # keep any orphaned message buffer: a checkpoint may still
            # arrive (buddy reassignment race); it ages out via
            # purge_expired otherwise
            return None
        msgs = self._messages.pop(clientid, [])
        self._msg_since.pop(clientid, None)
        return {
            "subs": state.get("subs", {}),
            "expiry": state.get("expiry", 0),
            "queued": list(state.get("queued", [])) + msgs,
            "awaiting_rel": [],
        }

    def purge_expired(
        self, now: Optional[float] = None, orphan_ttl: float = 86400.0
    ) -> int:
        now = now if now is not None else time.time()
        dead = [
            cid
            for cid, st in self._checkpoints.items()
            if now - st.get("saved_at", now) > st.get("expiry", 0)
        ]
        for cid in dead:
            self.drop(cid)
        orphans = [
            cid
            for cid, since in self._msg_since.items()
            if cid not in self._checkpoints and now - since > orphan_ttl
        ]
        for cid in orphans:
            self.drop(cid)
        return len(dead) + len(orphans)

    def info(self) -> Dict[str, int]:
        return {
            "checkpoints": len(self._checkpoints),
            "buffered_messages": sum(
                len(v) for v in self._messages.values()
            ),
        }
