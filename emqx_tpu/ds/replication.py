"""Durable-session replication across cluster nodes.

The role of `emqx_ds_builtin_raft` (/root/reference/apps/
emqx_ds_builtin_raft/src/emqx_ds_replication_layer.erl: replicated DS
shards so node loss doesn't lose durable messages), deliberately
simplified: instead of Raft consensus, each node replicates the durable
state a persistent session depends on — its checkpoint and its gated
message batches — to a deterministic BUDDY peer (rendezvous hash per
clientid over alive peers).  When a client reconnects elsewhere after
its home node died, the new node restores from its local replica store.

Consistency model (documented, weaker than the reference's Raft):
asynchronous replication, last-write-wins per clientid; a crash between
local persist and the replication cast can lose the tail batch.  That
trades the reference's quorum latency for zero write-path round-trips,
and converts "node loss = total session loss" into "node loss loses at
most the un-replicated tail".
"""

from __future__ import annotations

import hashlib
import logging
import time
from typing import Dict, List, Optional

from .. import failpoints

log = logging.getLogger("emqx_tpu.ds.replication")


def rendezvous_pick(key: str, nodes: List[str], k: int = 1) -> List[str]:
    """Highest-random-weight hashing: stable buddy choice that only
    moves keys owned by a node that joined/left."""
    scored = sorted(
        nodes,
        key=lambda n: hashlib.blake2b(
            f"{key}\x00{n}".encode(), digest_size=8
        ).digest(),
        reverse=True,
    )
    return scored[:k]


class ReplicaStore:
    """This node's copy of OTHER nodes' persistent sessions: checkpoint
    + pending messages per clientid, consulted when a client lands here
    after its home node died."""

    def __init__(self, cap_per_client: int = 10_000,
                 orphan_cap: int = 100_000) -> None:
        self.cap_per_client = cap_per_client
        # the orphan pool is GLOBAL (cross-client): its own cap, and
        # never 0 (a 0 per-client cap must not unbound it)
        self.orphan_cap = max(orphan_cap, 1024)
        # clientid -> {"subs", "expiry", "saved_at", "queued"}
        self._checkpoints: Dict[str, Dict] = {}
        # clientid -> wire-dict message buffers (+ first-append stamp,
        # so orphaned buffers — messages without a checkpoint, e.g.
        # after a buddy reassignment — age out instead of leaking)
        self._messages: Dict[str, List[Dict]] = {}
        self._msg_since: Dict[str, float] = {}
        # quorum-stored messages whose TARGET node died before
        # confirming (raft mode's forward fallback): keyed by TOPIC,
        # matched against a restoring session's filters.  At-least-once
        # semantics: a copy the home also replicated may double-deliver.
        # Each orphan tracks which clients it was handed to, so a
        # client reconnecting repeatedly is not re-served the same
        # orphan for the whole TTL
        self._orphans: List[tuple] = []  # (wire, stored_at, delivered_to)

    def store_checkpoint(self, clientid: str, state: Dict) -> None:
        """Buffered messages the checkpoint INCLUDES (same mid) leave
        the append buffer — it absorbed them.  Only those: a
        checkpoint built from a stale snapshot (an adopter's import
        racing the log tail) may apply AFTER a message entry it never
        saw, and clearing wholesale would destroy that entry's only
        replica copy."""
        if failpoints.enabled:
            # replica-write seam: drop loses this checkpoint silently
            # (the documented async-replication tail loss); error
            # raises out to the replication handler.  NOTE: this is a
            # sync seam on the event-loop thread — an armed `delay`
            # blocks the whole loop, not just this write; inject
            # latency at cluster.transport.* instead
            if failpoints.evaluate(
                "ds.replication.store", key=clientid
            ) == "drop":
                return
        self._checkpoints[clientid] = state
        buf = self._messages.get(clientid)
        if buf:
            included = {
                m.get("mid") for m in state.get("queued", ())
            }
            kept = [m for m in buf if m.get("mid") not in included]
            if kept:
                self._messages[clientid] = kept
            else:
                self._messages.pop(clientid, None)
                self._msg_since.pop(clientid, None)

    def drop(self, clientid: str) -> None:
        self._checkpoints.pop(clientid, None)
        self._messages.pop(clientid, None)
        self._msg_since.pop(clientid, None)

    def append_messages(self, clientid: str, msgs: List[Dict]) -> None:
        """Messages arrive (and stay) in wire-dict form — only a
        restore pays the decode."""
        if failpoints.enabled:
            if failpoints.evaluate(
                "ds.replication.store", key=clientid
            ) == "drop":
                return
        buf = self._messages.setdefault(clientid, [])
        self._msg_since.setdefault(clientid, time.time())
        buf.extend(msgs)
        del buf[: -self.cap_per_client]

    def add_orphans(self, wire_msgs) -> None:
        now = time.time()
        self._orphans.extend((w, now, set()) for w in wire_msgs)
        if len(self._orphans) > self.orphan_cap:
            # oldest-first eviction against the GLOBAL cap (evicting
            # with the per-client cap threw away other clients'
            # quorum-stored messages)
            del self._orphans[: len(self._orphans) - self.orphan_cap]

    def _matching_orphans(
        self, subs: Dict, clientid: Optional[str] = None,
        mark: bool = False,
    ) -> List[Dict]:
        """Orphans matching `subs` that `clientid` has not been served
        yet; ``mark=True`` records the hand-off (destructive restore
        paths), the non-destructive remote peek leaves it unmarked."""
        if not self._orphans or not subs:
            return []
        from .. import topic as T

        filters = []
        for f in subs:
            share = T.parse_share(f)
            filters.append(share.topic if share else f)
        out = []
        for w, _ts, delivered in self._orphans:
            if clientid is not None and clientid in delivered:
                continue
            if any(T.match(w.get("topic", ""), f) for f in filters):
                out.append(w)
                if mark and clientid is not None:
                    delivered.add(clientid)
        return out

    def peek(self, clientid: str,
             mark_orphans: bool = False) -> Optional[Dict]:
        """Non-destructive view in the restore shape (used by remote
        ds_take: the claimant's session-open op performs the drop).
        ``mark_orphans=True`` for peeks that DO deliver (the local
        resume merge) so repeated reconnects aren't re-served the same
        orphans."""
        state = self._checkpoints.get(clientid)
        if state is None:
            return None
        subs = dict(state.get("subs", {}))
        return {
            "subs": subs,
            "expiry": state.get("expiry", 0),
            "queued": list(state.get("queued", []))
            + list(self._messages.get(clientid, []))
            + self._matching_orphans(subs, clientid, mark=mark_orphans),
            "awaiting_rel": [],
        }

    def take(self, clientid: str) -> Optional[Dict]:
        """Claim a replica for restore (removes it).  The returned dict
        matches the takeover-export shape, so Broker.import_session
        consumes both.  Orphans stay (other sessions may match them);
        they age out via purge_expired."""
        state = self._checkpoints.pop(clientid, None)
        if state is None:
            # keep any orphaned message buffer: a checkpoint may still
            # arrive (buddy reassignment race); it ages out via
            # purge_expired otherwise
            return None
        msgs = self._messages.pop(clientid, [])
        self._msg_since.pop(clientid, None)
        subs = state.get("subs", {})
        return {
            "subs": subs,
            "expiry": state.get("expiry", 0),
            "queued": list(state.get("queued", [])) + msgs
            + self._matching_orphans(subs, clientid, mark=True),
            "awaiting_rel": [],
        }

    def purge_expired(
        self, now: Optional[float] = None, orphan_ttl: float = 86400.0
    ) -> int:
        now = now if now is not None else time.time()
        dead = [
            cid
            for cid, st in self._checkpoints.items()
            if now - st.get("saved_at", now) > st.get("expiry", 0)
        ]
        for cid in dead:
            self.drop(cid)
        orphans = [
            cid
            for cid, since in self._msg_since.items()
            if cid not in self._checkpoints and now - since > orphan_ttl
        ]
        for cid in orphans:
            self.drop(cid)
        n_top = len(self._orphans)
        self._orphans = [
            e for e in self._orphans if now - e[1] <= orphan_ttl
        ]
        return len(dead) + len(orphans) + n_top - len(self._orphans)

    def info(self) -> Dict[str, int]:
        return {
            "checkpoints": len(self._checkpoints),
            "buffered_messages": sum(
                len(v) for v in self._messages.values()
            ),
        }
