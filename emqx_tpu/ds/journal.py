"""Incremental metadata journal + fold (the O(delta) recovery core).

Census and LTS checkpoints used to be whole-file atomicio rewrites
whose cost grows with store size — and a store that crashed after its
last save paid a FULL log scan on the next open (12.7 s at 1M messages
vs 0.78 s for the segment-scan itself).  This module replaces both
with the classic journal + snapshot shape (the same recovery algebra
as the dslog segment log, applied to the metadata layer):

  * MUTATE — every metadata delta (a stream's first sighting of a
    topic, a census spill to opaque, a new LTS structure pattern) is
    an append-only RECORD in ``<sidecar>.journal``, written through
    checksummed binary frames (``atomicio.pack_frame``) — O(1) per
    delta, never O(store);
  * WATERMARK — a ``{"t": "wm", "ts": ...}`` record asserts "the
    snapshot plus every journal record before me covers the log up to
    ts" — recovery scans each stream only FROM the last watermark
    (learning is idempotent, so the overlap re-learns harmlessly);
  * FOLD — at idle/boot/close the snapshot is rewritten ONCE from the
    in-memory state (through ``atomicio.atomic_write_json``, the
    ``ds.meta.write`` seam) and the journal truncates.  The ordering
    makes a crash at ANY point idempotent: snapshot-then-truncate
    means a crash between the two leaves records in the journal that
    the snapshot already holds — replaying them is a no-op, and a
    re-fold produces the identical snapshot (property-tested).

Failure algebra mirrors the segment log: a torn journal TAIL is the
normal crash artifact (silently dropped — the watermark scan covers
it); an INTERIOR break means a once-valid suffix was flipped on disk —
its records are gone, so the loader reports corruption (alarm) and the
delta scan conservatively widens to the last watermark the valid
prefix asserts.

``MetaJournal.append`` is the ``ds.journal.append`` failpoint seam;
the fold's snapshot write rides the existing ``ds.meta.write`` seam.
brokerlint DUR702 pins every store-metadata snapshot write in
``emqx_tpu/ds/`` to this module's fold path.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Tuple

from .. import failpoints
from . import atomicio


class MetaJournal:
    """One append-only delta journal next to a metadata snapshot."""

    def __init__(self, path: str) -> None:
        self.path = path

    def size(self) -> int:
        """Journal byte size (the owner's fold trigger)."""
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    # ------------------------------------------------------- mutation

    def append(self, recs: List[Any], fsync: bool = False) -> None:
        """Append delta records as checksummed frames — the
        ``ds.journal.append`` failpoint seam:

          * ``error``/``panic`` raise out to the metadata flush (the
            tick logs it loudly; the deltas stay buffered in memory and
            the next flush retries — on a crash before one lands, the
            watermark scan re-learns them);
          * ``delay`` stalls the append (slow disk under the tick);
          * ``drop`` silently loses the frames (torn-power analogue:
            recovery must come out correct from the watermark scan —
            crash-suite-tested);
          * ``duplicate`` appends everything twice (replay is
            idempotent).
        """
        if not recs:
            return
        act = None
        if failpoints.enabled:
            act = failpoints.evaluate("ds.journal.append", key=self.path)
            if act == "drop":
                return
        blob = b"".join(atomicio.pack_frame(r) for r in recs)
        self._write(blob, fsync)
        if act == "duplicate":
            self._write(blob, fsync)
        rec = atomicio.recorder
        if rec is not None:
            on_jappend = getattr(rec, "on_jappend", None)
            if on_jappend is not None:
                on_jappend(self.path, blob)

    def _write(self, blob: bytes, fsync: bool) -> None:
        fresh = not os.path.exists(self.path)
        with open(self.path, "ab") as f:
            f.write(blob)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        if fresh and fsync:
            atomicio._fsync_dir(os.path.dirname(self.path) or ".")

    # ------------------------------------------------------- recovery

    def load(self) -> Tuple[List[Any], Optional[str]]:
        """``(records, corrupt_detail)`` — the valid record prefix
        plus None (clean or torn tail: the normal crash artifact) or a
        detail string (interior break: alarm + conservative
        fallback)."""
        try:
            with open(self.path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return [], None
        except OSError as exc:
            return [], f"{self.path}: unreadable: {exc}"
        return atomicio.iter_frames(blob, self.path)

    # ----------------------------------------------------------- fold

    def fold(
        self,
        snapshot_path: str,
        obj: Any,
        fsync: bool = False,
        extra: Optional[List[Tuple[str, Any]]] = None,
    ) -> None:
        """Compact: write the full snapshot atomically (plus any
        ``extra`` companion snapshots — e.g. the LTS pattern registry
        folds together with its index), THEN truncate the journal.
        Crash-idempotent in every ordering a power cut can leave:
        old-snapshot+journal (nothing happened), new-snapshot+journal
        (replaying the journal over the new snapshot is a no-op —
        records are already folded in, and loaders dedup), or
        new-snapshot+empty (the completed fold)."""
        atomicio.atomic_write_json(snapshot_path, obj, fsync=fsync)
        for path, eobj in extra or ():
            atomicio.atomic_write_json(path, eobj, fsync=fsync)
        self.truncate(fsync)

    def truncate(self, fsync: bool = False) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "wb") as f:
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        rec = atomicio.recorder
        if rec is not None:
            on_jtrunc = getattr(rec, "on_jtrunc", None)
            if on_jtrunc is not None:
                on_jtrunc(self.path)
