"""Durable storage API: the `emqx_ds` behavior surface.

Mirrors the reference's callback set (/root/reference/apps/
emqx_durable_storage/src/emqx_ds.erl:39-48 — store_batch, get_streams,
make_iterator, next; :255-261 behavior callbacks) with value-typed,
serializable iterators so persistent sessions can checkpoint replay
progress and resume after restart.

Stream partitioning is the bitfield-LTS idea reduced to its core
(emqx_ds_storage_bitfield_lts.erl / emqx_ds_lts.erl:100-143 learned
topic structure): a message's stream is a hash of its first topic
levels, and each backend tracks which concrete topics a stream holds so
`get_streams` can prune non-matching streams for concrete filters.
"""

from __future__ import annotations

import json
import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .. import topic as T
from ..message import Message

# hash this many leading topic levels into the stream id
STREAM_LEVELS = 2


def stream_of(topic: str, n_streams: int) -> int:
    words = topic.split("/")[:STREAM_LEVELS]
    return zlib.crc32("/".join(words).encode()) % n_streams


def filter_streams(flt: str, n_streams: int) -> Optional[int]:
    """Stream that could hold matches for `flt`, or None = all streams
    (wildcard inside the hashed prefix)."""
    words = T.words(flt)[:STREAM_LEVELS]
    if any(w in ("+", "#") for w in words):
        return None
    if len(words) < STREAM_LEVELS:
        # filter shorter than the hashed prefix: only an exact topic of
        # the same depth hashes the same way; a trailing '#' widens it
        if not flt.endswith("#"):
            return zlib.crc32("/".join(words).encode()) % n_streams
        return None
    return zlib.crc32("/".join(words).encode()) % n_streams


@dataclass(frozen=True)
class StreamRef:
    """Opaque-but-serializable stream handle (emqx_ds stream).

    ``store`` addresses the physical shard in a sharded store (the
    index of the inner segment-log + SyncGate pair); the single-shard
    layouts leave it 0 and serialize without it, so checkpoints from
    pre-sharded data dirs load unchanged."""

    shard: int
    store: int = 0

    def to_json(self) -> Dict:
        if self.store:
            return {"shard": self.shard, "store": self.store}
        return {"shard": self.shard}

    @staticmethod
    def from_json(obj: Dict) -> "StreamRef":
        return StreamRef(shard=obj["shard"], store=obj.get("store", 0))


@dataclass(frozen=True)
class IterRef:
    """Value-typed iterator: replay cursor into one stream.  ``ts`` is
    in integer microseconds; (ts, seq) orders records totally."""

    stream: StreamRef
    topic_filter: str
    ts: int = 0
    seq: int = 0

    def to_json(self) -> Dict:
        return {
            "stream": self.stream.to_json(),
            "filter": self.topic_filter,
            "ts": self.ts,
            "seq": self.seq,
        }

    @staticmethod
    def from_json(obj: Dict) -> "IterRef":
        return IterRef(
            stream=StreamRef.from_json(obj["stream"]),
            topic_filter=obj["filter"],
            ts=obj["ts"],
            seq=obj["seq"],
        )


def encode_message(msg: Message) -> bytes:
    """Binary message record: length-prefixed topic/payload/meta, MQTT 5
    properties as JSON (bytes values b64-wrapped by the cluster codec
    convention)."""
    topic = msg.topic.encode()
    from_client = msg.from_client.encode()
    from_username = (msg.from_username or "").encode()
    props = json.dumps(
        _props_jsonable(msg.properties), separators=(",", ":")
    ).encode()
    flags = (
        (1 if msg.retain else 0)
        | (2 if msg.sys else 0)
        | (4 if msg.dup else 0)
        | (8 if msg.from_username is not None else 0)
    )
    return (
        struct.pack(
            ">BBdH",
            msg.qos,
            flags,
            msg.timestamp,
            len(topic),
        )
        + topic
        + struct.pack(">16s", msg.mid)
        + struct.pack(">H", len(from_client))
        + from_client
        + struct.pack(">H", len(from_username))
        + from_username
        + struct.pack(">I", len(props))
        + props
        + struct.pack(">I", len(msg.payload))
        + msg.payload
    )


def decode_message(data: bytes) -> Message:
    qos, flags, timestamp, tlen = struct.unpack_from(">BBdH", data, 0)
    off = 12
    topic = data[off : off + tlen].decode()
    off += tlen
    mid = struct.unpack_from(">16s", data, off)[0]
    off += 16
    (clen,) = struct.unpack_from(">H", data, off)
    off += 2
    from_client = data[off : off + clen].decode()
    off += clen
    (ulen,) = struct.unpack_from(">H", data, off)
    off += 2
    from_username = data[off : off + ulen].decode()
    off += ulen
    (plen,) = struct.unpack_from(">I", data, off)
    off += 4
    props = _props_restore(json.loads(data[off : off + plen].decode()))
    off += plen
    (paylen,) = struct.unpack_from(">I", data, off)
    off += 4
    payload = data[off : off + paylen]
    return Message(
        topic=topic,
        payload=payload,
        qos=qos,
        retain=bool(flags & 1),
        sys=bool(flags & 2),
        dup=bool(flags & 4),
        from_client=from_client,
        from_username=from_username if flags & 8 else None,
        mid=mid,
        timestamp=timestamp,
        properties=props,
    )


def _props_jsonable(props: Dict) -> Dict:
    from ..cluster.node import _props_to_wire

    return _props_to_wire(props)


def _props_restore(props: Dict) -> Dict:
    from ..cluster.node import _props_from_wire

    return _props_from_wire(props)


class DurableStorage:
    # metadata sidecars fsync on every write only in the `always`
    # durability mode (DurableSessions sets this from durable.fsync);
    # atomic replace + CRC apply in every mode
    meta_fsync = False

    def stream_key(self, topic: str) -> int:
        """The write-side stream a topic maps to — the key layer
        callers (the beamformer's store-notify) must share with
        `store_batch`.  Layouts override; the default is the 2-level
        hash partitioning."""
        return stream_of(topic, getattr(self, "n_streams", 16))

    def _report_corruption(self, kind: str, path: str, detail: str,
                           records: int = 0) -> None:
        """Surface detected corruption (never swallow it): through
        ``on_corruption`` when the owner wired one, else buffered in
        ``corruption_events`` for the owner to drain after
        construction (loads run inside ``__init__``, before any
        callback can exist).  ``kind`` is ``storage`` (quarantined log
        records) or ``meta`` (unreadable sidecar)."""
        evt = {"kind": kind, "path": path, "detail": detail}
        if records:
            evt["records"] = records
        cb = getattr(self, "on_corruption", None)
        if cb is not None:
            cb(evt)
        else:
            self.corruption_events.append(evt)

    """Backend behavior (emqx_ds.erl:255-261 callback set)."""

    def store_batch(
        self, msgs: Sequence[Message], sync: bool = False
    ) -> None:
        raise NotImplementedError

    def get_streams(
        self, topic_filter: str, start_time_us: int = 0
    ) -> List[StreamRef]:
        raise NotImplementedError

    def make_iterator(
        self, stream: StreamRef, topic_filter: str, start_time_us: int = 0
    ) -> IterRef:
        return IterRef(
            stream=stream, topic_filter=topic_filter, ts=start_time_us
        )

    def next(
        self, it: IterRef, n: int
    ) -> Tuple[IterRef, List[Message]]:
        raise NotImplementedError

    def sync_data(self) -> None:
        """fsync the message log ONLY — the group-commit gate's flush
        (metadata checkpoints ride their own cadence via
        `save_meta`).  In-memory backends no-op."""

    def save_meta(self) -> None:
        """Checkpoint the layout's metadata caches (atomic + CRC; no
        fsync unless ``meta_fsync``)."""

    def sync(self) -> None:
        self.sync_data()
        self.save_meta()

    def save_meta_full(self) -> None:
        """Force a full metadata compaction (journal fold) where the
        layout keeps incremental metadata; plain checkpoint
        otherwise."""
        self.save_meta()

    def gc(self, cutoff_ts_us: int,
           pin_floor: Optional[int] = None) -> int:
        """Reclaim records older than the cutoff; generations at/above
        ``pin_floor`` survive (a replay cursor pins them).  In-memory
        backends no-op."""
        return 0

    def gc_pinned(self, cutoff_ts_us: int,
                  floors: Dict[int, int]) -> int:
        """Retention with per-shard generation pins (``floors``: store
        index -> lowest pinned generation).  Single-store backends use
        store 0's floor; sharded storage overrides."""
        return self.gc(cutoff_ts_us, pin_floor=floors.get(0))

    def seg_for(self, stream: StreamRef, ts: int, seq: int) -> int:
        """Generation the replay cursor (stream, ts, seq) pins; -1 if
        exhausted (or the backend has no generations)."""
        return -1

    def generation(self) -> int:
        """Current write generation (0 for ungenerational backends)."""
        return 0

    # ---------------------------------------------- census rebuild
    # surface (layouts that background their metadata rebuild
    # override; everything else reports "not rebuilding")

    rebuilding = False
    rebuild_progress = {"scanned": 0, "total": 0}

    def rebuild_now(self) -> None:
        """Block until any in-flight background metadata rebuild
        completes."""

    def corruption_stats(self) -> Dict[str, int]:
        return {"corrupt_records": 0, "quarantined_segments": 0}

    def close(self) -> None:
        pass
