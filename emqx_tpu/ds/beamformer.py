"""Beamformer: grouped long-poll over DS iterators.

The `emqx_ds_beamformer` role (/root/reference/apps/
emqx_durable_storage/src/emqx_ds_beamformer.erl:16-60): many readers
waiting for NEW data on the same streams are served together — a
store_batch triggers ONE sweep ("beam") that answers every coherent
parked poll, instead of each reader burning its own timer/poll cycle.

`poll(iterator, n, timeout)` returns immediately when data already
exists past the cursor, otherwise parks until the owning stream
receives an append (or the timeout elapses, returning the unchanged
iterator and no messages — the reference's poll timeout shape).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Set, Tuple

from .. import failpoints
from ..message import Message
from .api import IterRef

log = logging.getLogger("emqx_tpu.ds.beamformer")


class Beamformer:
    def __init__(self, storage) -> None:
        self.storage = storage
        # shard -> parked pollers' wakeup events
        self._parked: Dict[int, List[asyncio.Event]] = {}
        self.stats = {"polls": 0, "parked": 0, "beams": 0, "woken": 0}

    async def poll(
        self, it: IterRef, n: int = 256, timeout: float = 10.0
    ) -> Tuple[IterRef, List[Message]]:
        """Long-poll one iterator: (advanced iterator, messages);
        empty after `timeout` with no new matching data."""
        if failpoints.enabled:
            # chaos seam: `delay` injects long-poll latency, `drop`
            # answers this poll empty immediately (the timeout shape —
            # a beam the reader missed; callers re-poll), `error`
            # raises out to the poller's own recovery
            act = await failpoints.evaluate_async(
                "ds.beamformer.poll", key=str(it.stream.shard)
            )
            if act == "drop":
                return it, []
        self.stats["polls"] += 1
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while True:
            it2, msgs = self.storage.next(it, n)
            if msgs:
                return it2, msgs
            it = it2  # cursor may advance past non-matching records
            remaining = deadline - loop.time()
            if remaining <= 0:
                return it, []
            ev = asyncio.Event()
            shard = it.stream.shard
            self._parked.setdefault(shard, []).append(ev)
            self.stats["parked"] += 1
            try:
                await asyncio.wait_for(ev.wait(), remaining)
            except asyncio.TimeoutError:
                return it, []
            finally:
                waiters = self._parked.get(shard)
                if waiters is not None and ev in waiters:
                    waiters.remove(ev)
                    if not waiters:
                        self._parked.pop(shard, None)
            # woken by a beam: loop re-reads the stream (the data may
            # not match THIS reader's filter — it re-parks then)

    def has_parked(self) -> bool:
        """Cheap guard for the hot persist path: shard-set building and
        notify are skipped entirely while no reader is parked."""
        return bool(self._parked)

    def notify(self, shards: Set[int]) -> None:
        """A store_batch landed in `shards`: fire one beam per shard,
        waking every parked reader of it at once."""
        for shard in shards:
            waiters = self._parked.pop(shard, None)
            if not waiters:
                continue
            self.stats["beams"] += 1
            self.stats["woken"] += len(waiters)
            for ev in waiters:
                ev.set()

    def info(self) -> Dict:
        return {
            **self.stats,
            "parked_now": sum(len(v) for v in self._parked.values()),
        }
