"""Learned-topic-structure (LTS) storage layout + bitmask keymapper.

The reference's storage moat is `emqx_ds_lts`
(/root/reference/apps/emqx_durable_storage/src/emqx_ds_lts.erl:100-143):
a trie learned from observed topics discovers which levels are
"wildcard-worthy" (high-variability — device ids, session ids), and
`emqx_ds_bitmask_keymapper.erl:20-70` composes storage keys from the
static topic structure, the varying-level hashes, and time, so replay
touches only the key ranges a filter can match.

Same idea, TPU-repo shape, on the native dslog engine:

  * LEARNING — a trie counts distinct children per level; a level
    whose branching exceeds ``var_threshold`` flips (stickily) to
    VARYING.  A topic's STRUCTURE is the topic with varying levels
    replaced by '+': ``vehicles/v123/sensors/temp`` under a varying
    level 1 has structure ``vehicles/+/sensors/temp``.
  * KEYMAPPER — the dslog stream id is the composite
    ``structure_id << VAR_BITS | crc32(varying words) & VAR_MASK``:
    one structure spreads over up to 2^VAR_BITS sub-streams keyed by
    its varying words, and (stream, ts) keys order records in time.
  * REPLAY — a CONCRETE filter maps to exactly one composite stream
    (structure + var hash).  A wildcard filter scans only the
    sub-streams of the structures it OVERLAPS — sub-linear in the
    total record count because non-matching structures are never
    touched, where the flat hash layout decodes and match-tests every
    record of a 2-level hash shard.

Structure evolution is append-only: when a level flips to varying,
records already written keep their old (concrete-structure) streams
and new writes use the '+' structure; replay consults every structure
overlapping the filter, so nothing is rewritten and nothing is lost.
"""

from __future__ import annotations

import os
import zlib
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .. import topic as T
from ..message import Message
from . import atomicio
from .api import (
    DurableStorage,
    IterRef,
    StreamRef,
    decode_message,
    encode_message,
)
from .journal import MetaJournal
from .native import DsLog

VAR_BITS = 12
VAR_MASK = (1 << VAR_BITS) - 1

# journal size that triggers a fold into the snapshots at the next
# metadata flush
_FOLD_BYTES = 256 * 1024


def _overlaps(fw: Sequence[str], pw: Sequence[str]) -> bool:
    """Can filter `fw` match any topic of structure `pw`?  Patterns
    contain only literals and '+' (never '#')."""
    i = 0
    while True:
        if i == len(fw):
            return i == len(pw)
        if fw[i] == "#":
            return True
        if i == len(pw):
            return False
        if fw[i] != "+" and pw[i] != "+" and fw[i] != pw[i]:
            return False
        i += 1


class LtsIndex:
    """The learned trie + structure registry + keymapper."""

    def __init__(self, var_threshold: int = 32) -> None:
        self.var_threshold = var_threshold
        self._root = self._node()
        self._sids: Dict[str, int] = {}  # pattern -> structure id
        self._patterns: List[str] = []   # sid -> pattern
        # fired with the pattern whenever a NEW structure id is
        # minted: the storage journals it IMMEDIATELY (one O(1) delta
        # frame, not a registry rewrite) — sids are baked into on-disk
        # stream keys, so the sid->pattern mapping must never be
        # reconstructed by re-learning (a rebuild after gc could
        # assign shifted ids and silently mis-prune replay)
        self.on_new_pattern = None

    @staticmethod
    def _node() -> Dict:
        return {"c": {}, "v": False, "p": None}

    def _sid(self, pattern: str) -> int:
        sid = self._sids.get(pattern)
        if sid is None:
            sid = self._sids[pattern] = len(self._patterns)
            self._patterns.append(pattern)
            if self.on_new_pattern is not None:
                self.on_new_pattern(pattern)
        return sid

    def seed_patterns(self, patterns: List[str]) -> None:
        """Adopt a persisted sid->pattern table (authoritative: ids
        must match the ones already baked into stream keys)."""
        self._patterns = list(patterns)
        self._sids = {p: i for i, p in enumerate(self._patterns)}

    def learn(self, words: Sequence[str]) -> Tuple[int, List[str]]:
        """Insert a topic; returns (structure id, varying words)."""
        node = self._root
        pattern: List[str] = []
        varw: List[str] = []
        for w in words:
            if not node["v"]:
                child = node["c"].get(w)
                if child is None:
                    if len(node["c"]) >= self.var_threshold:
                        # flip (sticky): this level is wildcard-worthy.
                        # Existing concrete children stay reachable as
                        # their OLD structures (append-only evolution);
                        # new descents merge under the '+' child.
                        node["v"] = True
                        node["p"] = self._node()
                        node["c"] = {}
                    else:
                        child = node["c"][w] = self._node()
            if node["v"]:
                pattern.append("+")
                varw.append(w)
                node = node["p"]
            else:
                pattern.append(w)
                node = node["c"][w]
        return self._sid("/".join(pattern)), varw

    def key_of(self, topic: str) -> int:
        sid, varw = self.learn(T.words(topic))
        vh = (
            zlib.crc32("/".join(varw).encode()) & VAR_MASK
            if varw else 0
        )
        return (sid << VAR_BITS) | vh

    def shards_for_filter(
        self, flt: str, present: Iterable[int]
    ) -> List[int]:
        """Composite streams that could hold matches for `flt` —
        concrete var words collapse a structure to ONE sub-stream."""
        fw = T.words(flt)
        present = sorted(set(present))
        by_sid: Dict[int, List[int]] = {}
        for shard in present:
            by_sid.setdefault(shard >> VAR_BITS, []).append(shard)
        out: List[int] = []
        for sid, shards in by_sid.items():
            if sid >= len(self._patterns):
                out.extend(shards)  # unknown structure: cannot prune
                continue
            pw = self._patterns[sid].split("/")
            if not _overlaps(fw, pw):
                continue
            varw: Optional[List[str]] = []
            for i, p in enumerate(pw):
                if p != "+":
                    continue
                # positions at/after a trailing '#' (or beyond the
                # filter, only reachable under one) are unconstrained
                if i >= len(fw) or fw[i] in ("+", "#"):
                    varw = None  # wildcard over a varying level
                    break
                varw.append(fw[i])
            if varw is None:
                out.extend(shards)
            else:
                vh = (
                    zlib.crc32("/".join(varw).encode()) & VAR_MASK
                    if varw else 0
                )
                key = (sid << VAR_BITS) | vh
                if key in shards:
                    out.append(key)
        return sorted(out)

    # --------------------------------------------------- persistence

    def to_json(self) -> Dict:
        return {
            "var_threshold": self.var_threshold,
            "patterns": self._patterns,
            "trie": self._root,
        }

    @classmethod
    def from_json(cls, obj: Dict) -> "LtsIndex":
        idx = cls(var_threshold=int(obj.get("var_threshold", 32)))
        idx._patterns = list(obj.get("patterns", ()))
        idx._sids = {p: i for i, p in enumerate(idx._patterns)}
        idx._root = obj.get("trie") or cls._node()
        return idx


class LtsStorage(DurableStorage):
    """dslog-backed storage with the LTS layout (drop-in sibling of
    builtin_local.LocalStorage; differential-tested against
    ds/reference.py)."""

    def __init__(
        self,
        directory: str,
        var_threshold: int = 32,
        seg_bytes: int = 0,
    ) -> None:
        self.directory = directory
        self.on_corruption = None
        self.corruption_events: List[Dict] = []
        self._log = DsLog(directory, seg_bytes=seg_bytes)
        ncorrupt = self._log.corrupt_records()
        if ncorrupt:
            self._report_corruption(
                "storage", directory,
                f"{ncorrupt} record(s) quarantined in "
                f"{self._log.quarantined_count()} segment(s)",
                records=ncorrupt,
            )
        self._index_path = os.path.join(directory, "lts_index.json")
        # the sid->pattern registry persists SEPARATELY from the trie
        # cache: stream keys embed sids, so this mapping is append-only
        # ground truth that must survive any crash/gc combination the
        # trie does not.  New patterns journal as O(1) delta frames
        # (the registry file itself is only rewritten by the fold)
        self._patterns_path = os.path.join(
            directory, "lts_patterns.json"
        )
        self._journal = MetaJournal(os.path.join(directory, "lts.journal"))
        self._wm = 0
        self._max_ts_us = 0
        self._need_fold = False
        self.index = self._load_index(var_threshold)
        self.index.on_new_pattern = self._journal_pattern

    # ----------------------------------------------------------- write

    def store_batch(
        self, msgs: Sequence[Message], sync: bool = False
    ) -> None:
        for msg in msgs:
            key = self.index.key_of(msg.topic)
            ts_us = int(msg.timestamp * 1e6)
            self._log.append(key, ts_us, encode_message(msg))
            if ts_us > self._max_ts_us:
                self._max_ts_us = ts_us
        if sync:
            self._log.sync()
            self.save_meta()

    def stream_key(self, topic: str) -> int:
        return self.index.key_of(topic)

    # ------------------------------------------------------------ read

    def get_streams(
        self, topic_filter: str, start_time_us: int = 0
    ) -> List[StreamRef]:
        shards = self.index.shards_for_filter(
            topic_filter, self._log.streams()
        )
        return [StreamRef(shard=s) for s in shards]

    def next(self, it: IterRef, n: int) -> Tuple[IterRef, List[Message]]:
        # the layout prunes WHICH streams are scanned; each record is
        # still filter-checked, so correctness never rests on the
        # learned structure being right
        out: List[Message] = []
        ts, seq = it.ts, it.seq
        fwords = T.words(it.topic_filter)
        for ets, eseq, payload in self._log.scan(it.stream.shard, ts):
            if (ets, eseq) <= (ts, seq):
                continue
            if len(out) >= n:
                break
            msg = decode_message(payload)
            if T.match_words(T.words(msg.topic), fwords):
                out.append(msg)
            ts, seq = ets, eseq
        return IterRef(it.stream, it.topic_filter, ts, seq), out

    # ------------------------------------------------------ lifecycle

    def _load_patterns(self) -> List[str]:
        """Missing = fresh dir; unreadable = alarm + empty seed.  The
        empty fallback is CONSERVATIVE for replay: an unknown sid can
        never be pruned (`shards_for_filter` serves every stream of an
        unregistered structure and `next` filter-checks each record),
        so corruption degrades to wider scans, not loss."""
        try:
            return list(atomicio.load_json(self._patterns_path))
        except FileNotFoundError:
            return []
        except atomicio.MetaCorruption as exc:
            self._report_corruption("meta", exc.path, exc.detail)
            return []
        except (TypeError, ValueError):
            self._report_corruption(
                "meta", self._patterns_path, "pattern registry not a list"
            )
            return []

    def _journal_pattern(self, pattern: str) -> None:
        """A new structure id was minted: journal it NOW (one delta
        frame) — the sid is about to be baked into stream keys, so it
        cannot wait for the flush cadence the trie cache rides."""
        self._journal.append(
            [{"t": "pattern", "p": pattern}], fsync=self.meta_fsync
        )

    def _load_index(self, var_threshold: int) -> LtsIndex:
        """Snapshot + journal replay + delta re-learn from the
        watermark (O(records since the last flush)).  Only a store
        with no usable watermark pays the full re-learn — which stays
        SYNCHRONOUS (unlike the hash census): the trie and the sid
        table feed `key_of` on the write path, so serving writes
        against a half-learned trie would mint unstable structures."""
        try:
            obj = atomicio.load_json(self._index_path)
        except FileNotFoundError:
            obj = None
        except atomicio.MetaCorruption as exc:
            # the trie is a cache over the log: re-learning (below) is
            # full recovery, but a torn index is still counted/alarmed
            self._report_corruption("meta", exc.path, exc.detail)
            obj = None
        jrecs, jdetail = self._journal.load()
        if jdetail:
            self._report_corruption("meta", self._journal.path, jdetail)
        patterns = self._load_patterns()
        if not patterns and obj is not None:
            # pre-registry data dir: the stale index's table is still
            # a better sid seed than renumbering from scratch
            patterns = list(obj["index"].get("patterns", ()))
        wm: Optional[int] = None
        if obj is not None and "wm" in obj:
            wm = int(obj["wm"])
        for r in jrecs:
            t = r.get("t")
            if t == "pattern":
                # minted after the registry was last folded; dedup
                # absorbs a crash between the fold's two writes
                if r["p"] not in patterns:
                    patterns.append(r["p"])
            elif t == "wm":
                ts = int(r["ts"])
                if wm is None or ts > wm:
                    wm = ts
        if obj is not None and wm is not None:
            idx = LtsIndex.from_json(obj["index"])
            if len(patterns) > len(idx._patterns):
                idx.seed_patterns(patterns)  # registry ran ahead
            maxts = wm
            for shard in self._log.streams():
                for ets, _seq, payload in self._log.scan(shard, wm):
                    idx.learn(T.words(decode_message(payload).topic))
                    if ets > maxts:
                        maxts = ets
            self._wm = wm
            self._max_ts_us = maxts
            if maxts > wm or jrecs:
                # compact what replay accumulated — and persist any
                # sid minted by the delta re-learn (deterministic
                # until then: a crash re-learns the identical tail)
                self._need_fold = True
            return idx
        if obj is not None and obj.get("count") == self._record_count():
            # legacy snapshot (no watermark anywhere): the old count
            # check — matching means the trie is complete
            idx = LtsIndex.from_json(obj["index"])
            if len(patterns) > len(idx._patterns):
                idx.seed_patterns(patterns)
            return idx
        # stale-legacy or absent: re-learn the TRIE from the log, but
        # seed sid assignments from the persisted registry first —
        # re-learning must never renumber structures whose ids are
        # baked into on-disk stream keys (post-gc, an early
        # structure's records may be gone entirely and a fresh
        # numbering would shift every later sid)
        idx = LtsIndex(var_threshold)
        if patterns:
            idx.seed_patterns(patterns)
        rebuilt = False
        maxts = 0
        for shard in self._log.streams():
            for ets, _seq, payload in self._log.scan(shard, 0):
                idx.learn(T.words(decode_message(payload).topic))
                rebuilt = True
                if ets > maxts:
                    maxts = ets
        self._max_ts_us = maxts
        if rebuilt or obj is not None:
            self.index = idx
            self._fold_index()
        return idx

    def _record_count(self) -> int:
        return sum(
            self._log.stream_count(s) for s in self._log.streams()
        )

    def _fold_index(self) -> None:
        """Compact journal + registry + trie snapshot (the ONE place
        the LTS sidecars are rewritten — brokerlint DUR702 pins
        snapshot writes in emqx_tpu/ds/ to the journal fold path)."""
        self._journal.fold(
            self._index_path,
            {"count": self._record_count(),
             "wm": self._max_ts_us,
             "index": self.index.to_json()},
            fsync=self.meta_fsync,
            extra=[(self._patterns_path, self.index._patterns)],
        )
        self._wm = self._max_ts_us
        self._need_fold = False

    def gc(self, cutoff_ts_us: int,
           pin_floor: Optional[int] = None) -> int:
        return self._log.gc(cutoff_ts_us, pin_floor=pin_floor)

    def seg_for(self, stream: StreamRef, ts: int, seq: int) -> int:
        return self._log.seg_for(stream.shard, ts, seq)

    def generation(self) -> int:
        return self._log.generation()

    def sync_data(self) -> None:
        self._log.sync()

    def save_meta(self) -> None:
        """O(delta) metadata flush: a watermark frame (new patterns
        already journaled at mint time); fold only past the size
        threshold or when boot replay flagged a compaction."""
        if self._need_fold or self._journal.size() >= _FOLD_BYTES:
            self._fold_index()
            return
        if self._max_ts_us <= self._wm:
            return  # nothing new since the last flush
        self._journal.append(
            [{"t": "wm", "ts": self._max_ts_us}], fsync=self.meta_fsync
        )
        self._wm = self._max_ts_us

    def save_meta_full(self) -> None:
        self._fold_index()

    # sync() is the base composition: sync_data() + save_meta()

    def corruption_stats(self) -> Dict[str, int]:
        return {
            "corrupt_records": self._log.corrupt_records(),
            "quarantined_segments": self._log.quarantined_count(),
        }

    def stats(self) -> Dict[str, int]:
        n = self._record_count()
        return {
            "streams": len(self._log.streams()),
            "structures": len(self.index._patterns),
            "messages": n,
            "records": n,
            **self.corruption_stats(),
        }

    def close(self) -> None:
        if getattr(self, "_closed", False):
            return  # idempotent: server stop + explicit close both land
        self._closed = True
        try:
            self._fold_index()
        except OSError:
            pass
        self._log.close()
