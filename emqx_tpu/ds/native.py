"""ctypes binding for the native dslog storage engine.

Builds ``native/dslog.cpp`` on demand with g++ (the environment bakes
the toolchain in; pybind11 is not available so the C ABI + ctypes is
the binding layer — see native/dslog.cpp for the format).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from .. import failpoints

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "native", "dslog.cpp")
_SO = os.path.join(_REPO, "native", "build", "libdslog.so")

_lock = threading.Lock()
_lib = None


def _build() -> None:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    # one-time toolchain rebuild of a stale .so (dev boxes only;
    # production loads the checked-in binary) — never on the
    # steady-state path, so the loop stall is accepted
    # brokerlint: ignore[ASYNC101]
    subprocess.run(
        [
            "g++",
            "-O2",
            "-fPIC",
            "-shared",
            "-std=c++17",
            "-Wall",
            "-o",
            _SO,
            _SRC,
        ],
        check=True,
        capture_output=True,
    )


def load():
    """Load (building if stale) the dslog shared library."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(
            _SRC
        ):
            _build()
        lib = ctypes.CDLL(_SO)
        lib.dslog_open.restype = ctypes.c_void_p
        lib.dslog_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.dslog_close.argtypes = [ctypes.c_void_p]
        lib.dslog_append.restype = ctypes.c_int64
        lib.dslog_append.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint32,
            ctypes.c_uint64,
            ctypes.c_char_p,
            ctypes.c_uint32,
        ]
        lib.dslog_sync.restype = ctypes.c_int
        lib.dslog_sync.argtypes = [ctypes.c_void_p]
        lib.dslog_streams.restype = ctypes.c_int
        lib.dslog_streams.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_int,
        ]
        lib.dslog_iter_new.restype = ctypes.c_void_p
        lib.dslog_iter_new.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint32,
            ctypes.c_uint64,
        ]
        lib.dslog_iter_free.argtypes = [ctypes.c_void_p]
        lib.dslog_iter_next.restype = ctypes.c_int64
        lib.dslog_iter_next.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.dslog_stream_count.restype = ctypes.c_int64
        lib.dslog_stream_count.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.dslog_corrupt_records.restype = ctypes.c_int64
        lib.dslog_corrupt_records.argtypes = [ctypes.c_void_p]
        lib.dslog_quarantined_count.restype = ctypes.c_int
        lib.dslog_quarantined_count.argtypes = [ctypes.c_void_p]
        lib.dslog_gc.restype = ctypes.c_int64
        lib.dslog_gc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.dslog_gc2.restype = ctypes.c_int64
        lib.dslog_gc2.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_uint32,
        ]
        lib.dslog_seg_for.restype = ctypes.c_int64
        lib.dslog_seg_for.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint32,
            ctypes.c_uint64,
            ctypes.c_uint64,
        ]
        lib.dslog_cur_seg.restype = ctypes.c_int64
        lib.dslog_cur_seg.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class DsLog:
    """Thin OO wrapper over the C ABI.

    The two write-side methods are the broker's deepest storage IO
    seams: ``ds.store.append`` and ``ds.store.sync`` (chaos: a disk
    failing/stalling/lying exactly under the durable hot path).  The
    class-level ``recorder`` hook is the crash-point simulation
    harness's tap (tools/crashsim): when set, every successful
    open/append/sync is journaled so any crash prefix of the write
    trace can be materialized and recovered (ALICE-style).
    """

    # crashsim write-trace tap (None in production: one attr test per op)
    recorder = None

    def __init__(self, directory: str, seg_bytes: int = 0) -> None:
        self._lib = load()
        os.makedirs(directory, exist_ok=True)
        self._dir = directory
        self._seg_bytes = seg_bytes
        self._h = self._lib.dslog_open(directory.encode(), seg_bytes)
        if not self._h:
            raise OSError(f"dslog_open failed for {directory}")
        if DsLog.recorder is not None:
            DsLog.recorder.on_open(directory, seg_bytes)

    def append(self, stream: int, ts: int, data: bytes) -> int:
        """Append one record; the ``ds.store.append`` failpoint seam.

        * ``error``/``panic`` raise (callers see the same OSError path
          a full disk produces);
        * ``delay`` stalls the write (slow disk);
        * ``drop`` silently loses the record (a lying disk whose write
          never lands — what the crash-recovery property suite guards
          the replay contract against);
        * ``duplicate`` appends the record twice under distinct seqs
          (replay-side mid dedup absorbs it: at-least-once).
        """
        if failpoints.enabled:
            act = failpoints.evaluate("ds.store.append", key=str(stream))
            if act == "drop":
                return 0
            if act == "duplicate":
                self._append_raw(stream, ts, data)
        return self._append_raw(stream, ts, data)

    def _append_raw(self, stream: int, ts: int, data: bytes) -> int:
        seq = self._lib.dslog_append(self._h, stream, ts, data, len(data))
        if seq < 0:
            raise OSError(f"dslog_append failed: {seq}")
        if DsLog.recorder is not None:
            DsLog.recorder.on_append(self._dir, stream, ts, seq, data)
        return seq

    def sync(self) -> None:
        """fsync the current segment; the ``ds.store.sync`` failpoint
        seam.  ``error`` exercises the group-commit gate's
        park-and-retry path (PUBACKs stay parked until a sync lands);
        ``drop`` skips the fsync while reporting success — the lying
        disk the crashsim harness models; ``duplicate`` fsyncs twice
        (idempotent)."""
        if failpoints.enabled:
            act = failpoints.evaluate("ds.store.sync", key=self._dir)
            if act == "drop":
                return
        rc = self._lib.dslog_sync(self._h)
        if rc != 0:
            raise OSError(f"dslog_sync failed: {rc}")
        if DsLog.recorder is not None:
            DsLog.recorder.on_sync(self._dir)

    def streams(self) -> list:
        cap = 1024
        while True:
            buf = (ctypes.c_uint32 * cap)()
            n = self._lib.dslog_streams(self._h, buf, cap)
            if n <= cap:
                return list(buf[: max(n, 0)])
            cap = n

    def stream_count(self, stream: int) -> int:
        return self._lib.dslog_stream_count(self._h, stream)

    def corrupt_records(self) -> int:
        """Estimated records in quarantined suffixes (interior CRC
        breaks the recovery preserved instead of serving)."""
        return self._lib.dslog_corrupt_records(self._h)

    def quarantined_count(self) -> int:
        return self._lib.dslog_quarantined_count(self._h)

    def gc(self, cutoff_ts: int, pin_floor: Optional[int] = None) -> int:
        """Reclaim whole segments older than cutoff_ts (microseconds);
        returns records dropped.  ``pin_floor`` is the lowest GENERATION
        (segment id) a live replay cursor still needs — generations at
        or above it survive whatever their age (None = nothing pinned).

        The ``ds.gc.reclaim`` failpoint seam: ``error``/``panic`` raise
        out to the retention pass's recovery (the pass fails loudly and
        reclaims nothing — data is never at risk from a gc fault);
        ``delay`` stalls the reclaim (slow unlink on a loaded disk);
        ``drop`` skips the pass silently (a gc that never runs: the
        store only GROWS, which retention monitoring must surface);
        ``duplicate`` runs it twice (idempotent — the second pass finds
        nothing to reclaim)."""
        if failpoints.enabled:
            act = failpoints.evaluate("ds.gc.reclaim", key=self._dir)
            if act == "drop":
                return 0
            if act == "duplicate":
                self._gc_raw(cutoff_ts, pin_floor)
        return self._gc_raw(cutoff_ts, pin_floor)

    def _gc_raw(self, cutoff_ts: int, pin_floor: Optional[int]) -> int:
        floor = 0xFFFFFFFF if pin_floor is None else pin_floor
        return self._lib.dslog_gc2(self._h, cutoff_ts, floor)

    def seg_for(self, stream: int, ts: int, seq: int) -> int:
        """Generation (segment id) of the first record of ``stream``
        strictly after cursor (ts, seq) — what a live replay cursor
        pins; -1 when the cursor is exhausted."""
        return self._lib.dslog_seg_for(self._h, stream, ts, seq)

    def generation(self) -> int:
        """The current generation (segment new appends land in)."""
        return self._lib.dslog_cur_seg(self._h)

    def scan(self, stream: int, ts_from: int):
        """Generator over (ts, seq, payload) from ts_from (inclusive)."""
        it = self._lib.dslog_iter_new(self._h, stream, ts_from)
        cap = 64 * 1024
        buf = ctypes.create_string_buffer(cap)
        ts = ctypes.c_uint64()
        seq = ctypes.c_uint64()
        try:
            while True:
                n = self._lib.dslog_iter_next(
                    it, buf, cap, ctypes.byref(ts), ctypes.byref(seq)
                )
                if n == 0:
                    return
                if n == -7:  # -E2BIG: grow and retry
                    cap *= 4
                    buf = ctypes.create_string_buffer(cap)
                    continue
                if n < 0:
                    raise OSError(f"dslog_iter_next failed: {n}")
                yield ts.value, seq.value, buf.raw[:n]
        finally:
            self._lib.dslog_iter_free(it)

    def close(self) -> None:
        if self._h:
            self._lib.dslog_close(self._h)
            self._h = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass
