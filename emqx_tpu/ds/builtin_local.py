"""Single-node durable storage on the native dslog engine.

The `emqx_ds_builtin_local` analogue (/root/reference/apps/
emqx_ds_builtin_local/src/) with the storage layer in C++
(native/dslog.cpp) instead of RocksDB: messages append to a
(stream, time)-indexed log, streams are topic-prefix hash shards, and
a learned topic set per stream prunes `get_streams` for concrete
filters (the LTS idea, emqx_ds_lts.erl:100-143, without the adaptive
wildcard discovery — the topic census spills to 'opaque' past a bound
and the stream then serves every filter).

The census is maintained INCREMENTALLY (ds/journal.py): each new
(stream, topic) sighting appends one delta record to
``census.journal``, a watermark record per metadata flush asserts
coverage up to a log timestamp, and the ``census.json`` snapshot is
only rewritten by the journal FOLD (close / size threshold).  Recovery
is O(delta since the last flush) — snapshot + journal replay + a per-
stream scan from the watermark — instead of the whole-store rebuild a
stale count used to force.  Only a store with NO usable snapshot pays
the full rebuild, and that now runs in the BACKGROUND: an empty census
never prunes, so reads serve correct-but-wider from the log while the
scan proceeds (progress + the ``ds_meta_rebuild`` alarm surface it).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .. import topic as T
from ..message import Message
from . import atomicio
from .api import (
    DurableStorage,
    IterRef,
    StreamRef,
    decode_message,
    encode_message,
    filter_streams,
    stream_of,
)
from .journal import MetaJournal
from .native import DsLog

log = logging.getLogger("emqx_tpu.ds")

_TOPIC_CENSUS_MAX = 8192
# journal size that triggers a fold into the snapshot at the next
# metadata flush (bounds replay work AND journal growth)
_FOLD_BYTES = 256 * 1024


class LocalStorage(DurableStorage):
    def __init__(
        self,
        directory: str,
        n_streams: int = 16,
        seg_bytes: int = 0,
        background_rebuild: bool = True,
    ) -> None:
        self.directory = directory
        self.n_streams = n_streams
        self.on_corruption = None
        self.corruption_events: List[Dict] = []
        # census-rebuild progress surface (the `ds_meta_rebuild` alarm
        # + gauge): events buffer until the owner wires `on_rebuild`
        self.on_rebuild = None
        self.rebuild_events: List[Dict] = []
        self.rebuilding = False
        self.rebuild_progress = {"scanned": 0, "total": 0}
        self._background_rebuild = background_rebuild
        self._rebuild_lock = threading.Lock()
        self._rebuild_live: List[Tuple[int, str]] = []
        self._rebuild_stop = False
        self._rebuild_thread: Optional[threading.Thread] = None
        self._log = DsLog(directory, seg_bytes=seg_bytes)
        ncorrupt = self._log.corrupt_records()
        if ncorrupt:
            # the log recovery quarantined unreadable record suffixes:
            # the intact data keeps serving, and the owner raises the
            # ds_storage_corruption alarm — never a silent loss
            self._report_corruption(
                "storage", directory,
                f"{ncorrupt} record(s) quarantined in "
                f"{self._log.quarantined_count()} segment(s)",
                records=ncorrupt,
            )
        # learned topic structure: stream -> topics seen (None = opaque)
        self._census: Dict[int, Optional[Set[str]]] = {}
        self._census_path = os.path.join(directory, "census.json")
        self._journal = MetaJournal(
            os.path.join(directory, "census.journal")
        )
        # pending delta records (flushed by save_meta), the on-disk
        # coverage watermark, and the max append ts seen (the next
        # watermark candidate)
        self._jbuf: List[Dict] = []
        self._wm = 0
        self._max_ts_us = 0
        self._need_fold = False
        self._load_census()

    # ------------------------------------------------------------ write

    def store_batch(self, msgs: Sequence[Message], sync: bool = False) -> None:
        for msg in msgs:
            shard = stream_of(msg.topic, self.n_streams)
            ts_us = int(msg.timestamp * 1e6)
            self._log.append(shard, ts_us, encode_message(msg))
            if ts_us > self._max_ts_us:
                self._max_ts_us = ts_us
            if self.rebuilding:
                # census is being rebuilt in the background: defer the
                # update through the handoff list (the worker merges it
                # under the lock before declaring the census complete)
                with self._rebuild_lock:
                    if self.rebuilding:
                        self._rebuild_live.append((shard, msg.topic))
                        continue
            census = self._census.setdefault(shard, set())
            if census is not None and msg.topic not in census:
                census.add(msg.topic)
                if len(census) > _TOPIC_CENSUS_MAX:
                    self._census[shard] = None  # opaque from now on
                    self._jbuf.append({"t": "opaque", "s": shard})
                elif ts_us < self._wm:
                    # time-traveling append (clock step): a record
                    # BELOW the flushed watermark would be skipped by
                    # the delta scan, so its delta cannot wait for the
                    # next flush — journal it immediately
                    self._journal.append(
                        [{"t": "topic", "s": shard, "topic": msg.topic}],
                        fsync=self.meta_fsync,
                    )
                else:
                    self._jbuf.append(
                        {"t": "topic", "s": shard, "topic": msg.topic}
                    )
        if sync:
            self._log.sync()
            self.save_meta()

    # ------------------------------------------------------------- read

    def get_streams(
        self, topic_filter: str, start_time_us: int = 0
    ) -> List[StreamRef]:
        only = filter_streams(topic_filter, self.n_streams)
        present = set(self._log.streams()) | set(self._census)
        if only is not None:
            return [StreamRef(shard=only)] if only in present else []
        fwords = T.words(topic_filter)
        out = []
        rebuilding = self.rebuilding
        for shard in sorted(present):
            census = None if rebuilding else self._census.get(shard)
            if census is not None and not any(
                T.match_words(T.words(t), fwords) for t in census
            ):
                continue  # provably no matching topic in this stream
            out.append(StreamRef(shard=shard))
        return out

    def next(self, it: IterRef, n: int) -> Tuple[IterRef, List[Message]]:
        out: List[Message] = []
        ts, seq = it.ts, it.seq
        fwords = T.words(it.topic_filter)
        for ets, eseq, payload in self._log.scan(it.stream.shard, ts):
            if (ets, eseq) <= (ts, seq):
                continue
            if len(out) >= n:
                break
            msg = decode_message(payload)
            if T.match_words(T.words(msg.topic), fwords):
                out.append(msg)
            ts, seq = ets, eseq
        return IterRef(it.stream, it.topic_filter, ts, seq), out

    # ------------------------------------------------------- lifecycle

    def _total_count(self) -> int:
        return sum(self._log.stream_count(s) for s in self._log.streams())

    def _load_census(self) -> None:
        """Load the census: snapshot + journal replay + a per-stream
        delta scan from the watermark (O(records since the last flush),
        the log stays the source of truth).  Missing/corrupt snapshot
        falls back to the full rebuild — now backgrounded — with the
        corrupt case counted and alarmed, never silently absorbed."""
        raw = None
        try:
            raw = atomicio.load_json(self._census_path)
        except FileNotFoundError:
            pass
        except atomicio.MetaCorruption as exc:
            self._report_corruption("meta", exc.path, exc.detail)
        jrecs, jdetail = self._journal.load()
        if jdetail:
            # interior journal break: the valid prefix (and its last
            # watermark) still applies; the lost suffix's deltas are
            # re-learned by the scan from that earlier watermark
            self._report_corruption("meta", self._journal.path, jdetail)
        streams: Optional[Dict[int, Optional[Set[str]]]] = None
        snap_wm: Optional[int] = None
        if raw is not None:
            try:
                streams = {
                    int(k): (None if v is None else set(v))
                    for k, v in raw["streams"].items()
                }
                if "wm" in raw:
                    snap_wm = int(raw["wm"])
            except (ValueError, KeyError, AttributeError, TypeError):
                streams = None
        if streams is None:
            if raw is None and jrecs:
                # never folded: the journal holds EVERY delta since the
                # store was created (fold is the only truncation, and
                # it writes the snapshot first) — replay from empty
                streams = {}
            else:
                self._start_rebuild()
                return
        wm = snap_wm
        for r in jrecs:
            t = r.get("t")
            if t == "topic":
                c = streams.setdefault(int(r["s"]), set())
                if c is not None:
                    c.add(r["topic"])
                    if len(c) > _TOPIC_CENSUS_MAX:
                        streams[int(r["s"])] = None
            elif t == "opaque":
                streams[int(r["s"])] = None
            elif t == "wm":
                ts = int(r["ts"])
                if wm is None or ts > wm:
                    wm = ts
        if wm is None:
            # legacy snapshot (no watermark anywhere): the old count
            # check — matching means complete, stale means the full
            # rebuild the watermark scheme exists to avoid
            if raw is not None and raw.get("n") == self._total_count():
                self._census = streams
                return
            self._start_rebuild()
            return
        self._census = streams
        maxts = wm
        for shard in self._log.streams():
            if self._census.get(shard) is None and shard in self._census:
                continue  # opaque: trivially covered at any ts
            census = self._census.setdefault(shard, set())
            for ets, _seq, payload in self._log.scan(shard, wm):
                if census is not None:
                    census.add(decode_message(payload).topic)
                    if len(census) > _TOPIC_CENSUS_MAX:
                        census = self._census[shard] = None
                if ets > maxts:
                    maxts = ets
        self._wm = wm
        self._max_ts_us = maxts
        if maxts > wm or jrecs:
            self._need_fold = True  # boot fold: compact what replay
            # and the delta scan accumulated (next save_meta)

    # ------------------------------------------------- full rebuild

    def _start_rebuild(self) -> None:
        """Census lost (fresh dir, corrupt snapshot, stale legacy
        snapshot): rebuild from the log.  The store SERVES during the
        rebuild — an absent census entry never prunes, so reads are
        correct-but-wider until the scan lands."""
        self._census = {}
        total = len(self._log.streams())
        self.rebuild_progress = {"scanned": 0, "total": total}
        if total == 0:
            return  # nothing to scan (fresh directory)
        self.rebuilding = True
        self._rebuild_live = []
        self._rebuild_stop = False
        self._notify_rebuild("start")
        if self._background_rebuild:
            t = threading.Thread(
                target=self._rebuild_worker,
                name="ds-census-rebuild",
                daemon=True,
            )
            self._rebuild_thread = t
            t.start()
        else:
            self._rebuild_worker()

    def _rebuild_worker(self) -> None:
        built: Dict[int, Optional[Set[str]]] = {}
        maxts = 0
        ok = True
        try:
            for shard in self._log.streams():
                if self._rebuild_stop:
                    ok = False
                    break
                census: Optional[Set[str]] = set()
                for ets, _seq, payload in self._log.scan(shard, 0):
                    if census is not None:
                        census.add(decode_message(payload).topic)
                        if len(census) > _TOPIC_CENSUS_MAX:
                            census = None
                    if ets > maxts:
                        maxts = ets
                built[shard] = census
                self.rebuild_progress["scanned"] += 1
        except Exception:
            log.exception("census rebuild failed for %s", self.directory)
            ok = False
        if not ok:
            # aborted/faulted: census stays empty (never prunes — reads
            # remain correct), the next open retries the rebuild
            self.rebuilding = False
            self._notify_rebuild("aborted")
            return
        with self._rebuild_lock:
            # merge topics appended while the scan ran, then flip the
            # flag under the lock — store_batch's deferred path also
            # holds it, so no sighting can fall between list and census
            for shard, topic in self._rebuild_live:
                c = built.setdefault(shard, set())
                if c is not None:
                    c.add(topic)
                    if len(c) > _TOPIC_CENSUS_MAX:
                        built[shard] = None
            self._census = built
            self._rebuild_live = []
            self.rebuilding = False
        if maxts > self._max_ts_us:
            self._max_ts_us = maxts
        # the rebuilt census exists only in memory: the next metadata
        # flush folds it into the snapshot (broker-thread-serialized —
        # the worker never races the tick on the snapshot file)
        self._need_fold = True
        self._notify_rebuild("done")

    def rebuild_now(self) -> None:
        """Block until any in-flight background rebuild completes (the
        loop-less test/bench entry)."""
        t = self._rebuild_thread
        if t is not None and t.is_alive():
            t.join()

    def _notify_rebuild(self, event: str) -> None:
        evt = {
            "event": event,
            "path": self.directory,
            **self.rebuild_progress,
        }
        if self.on_rebuild is not None:
            self.on_rebuild(evt)
        else:
            self.rebuild_events.append(evt)

    # --------------------------------------------------- metadata flush

    def save_meta(self) -> None:
        """The metadata-flush cadence (broker tick / sync): O(delta) —
        append the pending census records + a watermark frame; fold
        into the snapshot only past the size threshold (or after a
        rebuild/boot replay made the journal redundant)."""
        if self.rebuilding:
            return  # incomplete census: no snapshot/watermark may
            # assert coverage until the scan lands
        if self._need_fold or self._journal.size() >= _FOLD_BYTES:
            self._fold_census()
            return
        if not self._jbuf and self._max_ts_us <= self._wm:
            return  # nothing new since the last flush
        recs = self._jbuf + [{"t": "wm", "ts": self._max_ts_us}]
        self._journal.append(recs, fsync=self.meta_fsync)
        self._jbuf = []
        self._wm = self._max_ts_us

    def _fold_census(self) -> None:
        """Compact the journal into the ``census.json`` snapshot (the
        ONE place the census snapshot is rewritten — brokerlint DUR702
        pins snapshot writes to the journal fold path)."""
        self._journal.fold(
            self._census_path,
            {
                "n": self._total_count(),
                "wm": self._max_ts_us,
                "streams": {
                    str(k): (None if v is None else sorted(v))
                    for k, v in self._census.items()
                },
            },
            fsync=self.meta_fsync,
        )
        self._jbuf = []
        self._wm = self._max_ts_us
        self._need_fold = False

    def gc(self, cutoff_ts_us: int,
           pin_floor: Optional[int] = None) -> int:
        """Retention: reclaim segments wholly older than the cutoff,
        except generations at/above ``pin_floor`` (a live replay
        cursor's generation pin).  The census may now overstate topics
        (harmless: it only prunes when a topic is provably absent)."""
        return self._log.gc(cutoff_ts_us, pin_floor=pin_floor)

    def seg_for(self, stream: StreamRef, ts: int, seq: int) -> int:
        """Generation the cursor (stream, ts, seq) pins; -1 if
        exhausted."""
        return self._log.seg_for(stream.shard, ts, seq)

    def generation(self) -> int:
        return self._log.generation()

    def sync_data(self) -> None:
        self._log.sync()

    def save_meta_full(self) -> None:
        """Force a fold (shutdown / tests)."""
        if not self.rebuilding:
            self._fold_census()

    # sync() is the base composition: sync_data() + save_meta()

    def corruption_stats(self) -> Dict[str, int]:
        return {
            "corrupt_records": self._log.corrupt_records(),
            "quarantined_segments": self._log.quarantined_count(),
        }

    def stats(self) -> Dict[str, int]:
        return {
            "streams": len(self._log.streams()),
            "messages": sum(
                self._log.stream_count(s) for s in self._log.streams()
            ),
            **self.corruption_stats(),
        }

    def close(self) -> None:
        if self._log._h:  # idempotent: second close is a no-op
            if self.rebuilding:
                # abort the scan: folding a half-built census would
                # persist a snapshot that wrongly prunes — the next
                # open rebuilds instead
                self._rebuild_stop = True
                t = self._rebuild_thread
                if t is not None and t.is_alive():
                    t.join(timeout=5.0)
            if not self.rebuilding:
                self._fold_census()
            self._log.close()
