"""Single-node durable storage on the native dslog engine.

The `emqx_ds_builtin_local` analogue (/root/reference/apps/
emqx_ds_builtin_local/src/) with the storage layer in C++
(native/dslog.cpp) instead of RocksDB: messages append to a
(stream, time)-indexed log, streams are topic-prefix hash shards, and
a learned topic set per stream prunes `get_streams` for concrete
filters (the LTS idea, emqx_ds_lts.erl:100-143, without the adaptive
wildcard discovery — the topic census spills to 'opaque' past a bound
and the stream then serves every filter)."""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .. import topic as T
from ..message import Message
from . import atomicio
from .api import (
    DurableStorage,
    IterRef,
    StreamRef,
    decode_message,
    encode_message,
    filter_streams,
    stream_of,
)
from .native import DsLog

_TOPIC_CENSUS_MAX = 8192


class LocalStorage(DurableStorage):
    def __init__(
        self,
        directory: str,
        n_streams: int = 16,
        seg_bytes: int = 0,
    ) -> None:
        self.directory = directory
        self.n_streams = n_streams
        self.on_corruption = None
        self.corruption_events: List[Dict] = []
        self._log = DsLog(directory, seg_bytes=seg_bytes)
        ncorrupt = self._log.corrupt_records()
        if ncorrupt:
            # the log recovery quarantined unreadable record suffixes:
            # the intact data keeps serving, and the owner raises the
            # ds_storage_corruption alarm — never a silent loss
            self._report_corruption(
                "storage", directory,
                f"{ncorrupt} record(s) quarantined in "
                f"{self._log.quarantined_count()} segment(s)",
                records=ncorrupt,
            )
        # learned topic structure: stream -> topics seen (None = opaque)
        self._census: Dict[int, Optional[Set[str]]] = {}
        self._census_path = os.path.join(directory, "census.json")
        self._load_census()

    # ------------------------------------------------------------ write

    def store_batch(self, msgs: Sequence[Message], sync: bool = False) -> None:
        for msg in msgs:
            shard = stream_of(msg.topic, self.n_streams)
            ts_us = int(msg.timestamp * 1e6)
            self._log.append(shard, ts_us, encode_message(msg))
            census = self._census.setdefault(shard, set())
            if census is not None:
                census.add(msg.topic)
                if len(census) > _TOPIC_CENSUS_MAX:
                    self._census[shard] = None  # opaque from now on
        if sync:
            self._log.sync()
            self._save_census()

    # ------------------------------------------------------------- read

    def get_streams(
        self, topic_filter: str, start_time_us: int = 0
    ) -> List[StreamRef]:
        only = filter_streams(topic_filter, self.n_streams)
        present = set(self._log.streams()) | set(self._census)
        if only is not None:
            return [StreamRef(shard=only)] if only in present else []
        fwords = T.words(topic_filter)
        out = []
        for shard in sorted(present):
            census = self._census.get(shard)
            if census is not None and not any(
                T.match_words(T.words(t), fwords) for t in census
            ):
                continue  # provably no matching topic in this stream
            out.append(StreamRef(shard=shard))
        return out

    def next(self, it: IterRef, n: int) -> Tuple[IterRef, List[Message]]:
        out: List[Message] = []
        ts, seq = it.ts, it.seq
        fwords = T.words(it.topic_filter)
        for ets, eseq, payload in self._log.scan(it.stream.shard, ts):
            if (ets, eseq) <= (ts, seq):
                continue
            if len(out) >= n:
                break
            msg = decode_message(payload)
            if T.match_words(T.words(msg.topic), fwords):
                out.append(msg)
            ts, seq = ets, eseq
        return IterRef(it.stream, it.topic_filter, ts, seq), out

    # ------------------------------------------------------- lifecycle

    def _total_count(self) -> int:
        return sum(self._log.stream_count(s) for s in self._log.streams())

    def _load_census(self) -> None:
        """Load the census cache, validating it against the log (the
        log is the source of truth): a crash after the last save leaves
        the cache stale, and a stale census could wrongly prune streams
        — rebuild whenever the record count disagrees.  Missing or
        stale is the normal crash artifact (silent rebuild); an
        UNREADABLE file (torn write, CRC break) also rebuilds — the
        census is a cache, so the rebuild IS full recovery — but is
        counted and alarmed, never silently absorbed."""
        try:
            raw = atomicio.load_json(self._census_path)
        except FileNotFoundError:
            self._rebuild_census()
            return
        except atomicio.MetaCorruption as exc:
            self._report_corruption("meta", exc.path, exc.detail)
            self._rebuild_census()
            return
        try:
            if raw.get("n") != self._total_count():
                raise ValueError("census stale vs log")
            self._census = {
                int(k): (None if v is None else set(v))
                for k, v in raw["streams"].items()
            }
        except (ValueError, KeyError, AttributeError, TypeError):
            self._rebuild_census()

    def _rebuild_census(self) -> None:
        """Recover the topic census by scanning the log (the log is the
        source of truth; the census is a cache)."""
        self._census = {}
        for shard in self._log.streams():
            census: Optional[Set[str]] = set()
            for _, _, payload in self._log.scan(shard, 0):
                if census is not None:
                    census.add(decode_message(payload).topic)
                    if len(census) > _TOPIC_CENSUS_MAX:
                        census = None
                        break
            self._census[shard] = census

    def _save_census(self) -> None:
        atomicio.atomic_write_json(
            self._census_path,
            {
                "n": self._total_count(),
                "streams": {
                    str(k): (None if v is None else sorted(v))
                    for k, v in self._census.items()
                },
            },
            fsync=self.meta_fsync,
        )

    def gc(self, cutoff_ts_us: int) -> int:
        """Retention: reclaim segments wholly older than the cutoff.
        The census may now overstate topics (harmless: it only prunes
        when a topic is provably absent)."""
        return self._log.gc(cutoff_ts_us)

    def sync_data(self) -> None:
        self._log.sync()

    def save_meta(self) -> None:
        self._save_census()

    # sync() is the base composition: sync_data() + save_meta()

    def corruption_stats(self) -> Dict[str, int]:
        return {
            "corrupt_records": self._log.corrupt_records(),
            "quarantined_segments": self._log.quarantined_count(),
        }

    def stats(self) -> Dict[str, int]:
        return {
            "streams": len(self._log.streams()),
            "messages": sum(
                self._log.stream_count(s) for s in self._log.streams()
            ),
            **self.corruption_stats(),
        }

    def close(self) -> None:
        if self._log._h:  # idempotent: second close is a no-op
            self._save_census()
            self._log.close()
