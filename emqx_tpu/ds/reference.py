"""Trivially-correct in-memory DS backend — the test oracle.

The reference ships `emqx_ds_storage_reference` for exactly this
purpose (/root/reference/apps/emqx_durable_storage/src/
emqx_ds_storage_reference.erl): a backend simple enough to be obviously
right, used to differential-test the real storage layouts.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Sequence, Tuple

from .. import topic as T
from ..message import Message
from .api import DurableStorage, IterRef, StreamRef, filter_streams, stream_of


class ReferenceStorage(DurableStorage):
    def __init__(self, n_streams: int = 16) -> None:
        self.n_streams = n_streams
        self._seq = itertools.count(1)
        # shard -> ordered list of (ts_us, seq, Message)
        self._data: Dict[int, List[Tuple[int, int, Message]]] = {}

    def store_batch(self, msgs: Sequence[Message], sync: bool = False) -> None:
        for msg in msgs:
            shard = stream_of(msg.topic, self.n_streams)
            ts_us = int(msg.timestamp * 1e6)
            self._data.setdefault(shard, []).append(
                (ts_us, next(self._seq), msg)
            )
        for lst in self._data.values():
            lst.sort(key=lambda e: (e[0], e[1]))

    def get_streams(
        self, topic_filter: str, start_time_us: int = 0
    ) -> List[StreamRef]:
        only = filter_streams(topic_filter, self.n_streams)
        shards = self._data.keys() if only is None else [only]
        return [StreamRef(shard=s) for s in sorted(shards) if s in self._data]

    def next(self, it: IterRef, n: int) -> Tuple[IterRef, List[Message]]:
        out: List[Message] = []
        ts, seq = it.ts, it.seq
        fwords = T.words(it.topic_filter)
        for ets, eseq, msg in self._data.get(it.stream.shard, ()):
            # strictly-after cursor; the initial (start_ts, 0) cursor is
            # inclusive of start_ts because real seqs start at 1
            if (ets, eseq) <= (ts, seq):
                continue
            if len(out) >= n:
                break
            if T.match_words(T.words(msg.topic), fwords):
                out.append(msg)
            ts, seq = ets, eseq
        return IterRef(it.stream, it.topic_filter, ts, seq), out
