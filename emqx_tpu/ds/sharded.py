"""Sharded DS store: N independent segment-log + metadata pairs.

One dslog directory serializes every append through one segment file
and ONE fsync barrier — fine for thousands of sessions, a wall at a
million: the group-commit gate amortizes fsyncs per window, but all
windows still share a single disk queue, and restart scans one giant
segment chain.  `ShardedStorage` splits the store by STREAM HASH into
``n_shards`` inner stores (``shard-00/ .. shard-NN/``), each a full
LocalStorage/LtsStorage with its own segment chain, its own journal +
snapshot metadata, its own append watermark and its own SyncGate
(persist.py pairs one gate per shard and fronts them with
`durability.GateGroup`):

  * WRITES — a message routes by ``crc32(first STREAM_LEVELS topic
    levels) % n_shards`` — the same prefix family the in-shard stream
    hash and `filter_streams` use, so a CONCRETE filter routes to
    exactly one shard and a wildcard-in-prefix fans out to all;
  * FSYNC — shards flush independently (N disks' worth of group
    commit); cross-shard ACK consistency is the GateGroup's barrier,
    not the storage's problem;
  * RECOVERY — shards recover independently (quarantine in one shard
    never widens to another) and in O(delta) each via their metadata
    journals;
  * GC — generation pins are per-shard: `gc_pinned` takes a
    ``{store: floor}`` map because generation numbers only mean
    something within one shard's segment chain.

The shard index travels in ``StreamRef.store`` (serialized only when
nonzero, so single-shard checkpoints are byte-identical to the old
format) and every read routes by it.  ``n_shards`` is pinned by the
data directory's LAYOUT marker — it defines where records LIVE, so a
config change cannot quietly re-route reads away from old data.
"""

from __future__ import annotations

import os
from dataclasses import replace
from typing import Dict, List, Optional, Sequence, Tuple

from ..message import Message
from .api import (
    DurableStorage,
    IterRef,
    StreamRef,
    filter_streams,
    stream_of,
)
from .builtin_local import LocalStorage
from .lts import LtsStorage


class ShardedStorage(DurableStorage):
    def __init__(
        self,
        directory: str,
        n_shards: int,
        layout: str = "lts",
        n_streams: int = 16,
        seg_bytes: int = 0,
    ) -> None:
        self.directory = directory
        self.n_shards = n_shards
        self.layout = layout
        self.on_corruption = None
        self.corruption_events: List[Dict] = []
        self.on_rebuild = None
        self.rebuild_events: List[Dict] = []
        self.stores: List[DurableStorage] = []
        for i in range(n_shards):
            sub = os.path.join(directory, f"shard-{i:02d}")
            os.makedirs(sub, exist_ok=True)
            if layout == "lts":
                st: DurableStorage = LtsStorage(sub, seg_bytes=seg_bytes)
            else:
                st = LocalStorage(
                    sub, n_streams=n_streams, seg_bytes=seg_bytes
                )
            self.stores.append(st)
        # adopt whatever the inner loads detected, then route their
        # later events through this facade (same funnel discipline as
        # DurableSessions over its storage)
        for st in self.stores:
            for evt in st.corruption_events:
                self._forward_corruption(evt)
            st.corruption_events = []
            st.on_corruption = self._forward_corruption
            for evt in getattr(st, "rebuild_events", ()):
                self._forward_rebuild(evt)
            if hasattr(st, "rebuild_events"):
                st.rebuild_events = []
            if hasattr(st, "on_rebuild"):
                st.on_rebuild = self._forward_rebuild

    def _forward_corruption(self, evt: Dict) -> None:
        if self.on_corruption is not None:
            self.on_corruption(evt)
        else:
            self.corruption_events.append(evt)

    def _forward_rebuild(self, evt: Dict) -> None:
        if self.on_rebuild is not None:
            self.on_rebuild(evt)
        else:
            self.rebuild_events.append(evt)

    # metadata fsync propagates to the inner stores (they own the
    # sidecar writes)
    @property
    def meta_fsync(self) -> bool:
        return bool(self.stores and self.stores[0].meta_fsync)

    @meta_fsync.setter
    def meta_fsync(self, val: bool) -> None:
        for st in self.stores:
            st.meta_fsync = val

    # ---------------------------------------------------------- routing

    def shard_for(self, topic: str) -> int:
        return stream_of(topic, self.n_shards)

    def _route_filter(self, flt: str) -> List[int]:
        only = filter_streams(flt, self.n_shards)
        if only is not None:
            return [only]
        return list(range(self.n_shards))

    # ------------------------------------------------------------ write

    def store_batch(
        self, msgs: Sequence[Message], sync: bool = False
    ) -> Optional[Dict[int, int]]:
        """Partition the batch by shard hash and append to each inner
        store in arrival order.  Returns {store index: records
        appended} so the owner can mark each shard's OWN SyncGate —
        the per-shard watermark is what keeps one shard's fsync from
        covering (or blocking) another's."""
        parts: Dict[int, List[Message]] = {}
        for msg in msgs:
            parts.setdefault(self.shard_for(msg.topic), []).append(msg)
        for idx, batch in parts.items():
            self.stores[idx].store_batch(batch, sync=sync)
        return {idx: len(batch) for idx, batch in parts.items()}

    def stream_key(self, topic: str) -> int:
        # the beamformer's park/notify key: must equal the key of the
        # stream the topic's records land in, i.e. the INNER store's.
        # Keys may collide ACROSS shards — harmless: a spurious wakeup
        # polls, reads nothing, re-parks.
        return self.stores[self.shard_for(topic)].stream_key(topic)

    # ------------------------------------------------------------- read

    def get_streams(
        self, topic_filter: str, start_time_us: int = 0
    ) -> List[StreamRef]:
        out: List[StreamRef] = []
        for idx in self._route_filter(topic_filter):
            for s in self.stores[idx].get_streams(
                topic_filter, start_time_us
            ):
                out.append(replace(s, store=idx) if idx else s)
        return out

    def next(self, it: IterRef, n: int) -> Tuple[IterRef, List[Message]]:
        # inner stores only read it.stream.shard and rebuild IterRefs
        # around the SAME StreamRef, so the store tag round-trips
        return self.stores[it.stream.store].next(it, n)

    # -------------------------------------------------------- lifecycle

    def sync_data(self) -> None:
        for st in self.stores:
            st.sync_data()

    def save_meta(self) -> None:
        for st in self.stores:
            st.save_meta()

    def save_meta_full(self) -> None:
        for st in self.stores:
            st.save_meta_full()

    def gc(self, cutoff_ts_us: int,
           pin_floor: Optional[int] = None) -> int:
        # a single scalar floor cannot be right across shards (each
        # shard numbers its own generations) — only sensible unpinned
        return sum(
            st.gc(cutoff_ts_us, pin_floor=pin_floor)
            for st in self.stores
        )

    def gc_pinned(self, cutoff_ts_us: int,
                  floors: Dict[int, int]) -> int:
        """Retention with per-shard generation pins: ``floors`` maps
        store index -> lowest generation a live replay cursor in that
        shard still needs."""
        return sum(
            st.gc(cutoff_ts_us, pin_floor=floors.get(i))
            for i, st in enumerate(self.stores)
        )

    def seg_for(self, stream: StreamRef, ts: int, seq: int) -> int:
        return self.stores[stream.store].seg_for(stream, ts, seq)

    def generation(self) -> int:
        return max(st.generation() for st in self.stores)

    # ------------------------------------------------- rebuild surface

    @property
    def rebuilding(self) -> bool:
        return any(st.rebuilding for st in self.stores)

    @property
    def rebuild_progress(self) -> Dict[str, int]:
        scanned = total = 0
        for st in self.stores:
            p = st.rebuild_progress
            scanned += p.get("scanned", 0)
            total += p.get("total", 0)
        return {"scanned": scanned, "total": total}

    def rebuild_now(self) -> None:
        for st in self.stores:
            st.rebuild_now()

    # ----------------------------------------------------------- stats

    def corruption_stats(self) -> Dict[str, int]:
        out = {"corrupt_records": 0, "quarantined_segments": 0}
        for st in self.stores:
            for k, v in st.corruption_stats().items():
                out[k] = out.get(k, 0) + v
        return out

    def shard_stats(self) -> List[Dict[str, int]]:
        """Per-shard stats rows (the ops surface's breakdown)."""
        return [
            {"shard": i, **st.corruption_stats()}
            for i, st in enumerate(self.stores)
        ]

    def stats(self) -> Dict[str, int]:
        out: Dict[str, int] = {"shards": self.n_shards}
        for st in self.stores:
            for k, v in st.stats().items():
                if isinstance(v, int):
                    out[k] = out.get(k, 0) + v
        return out

    def close(self) -> None:
        for st in self.stores:
            st.close()
