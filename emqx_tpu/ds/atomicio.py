"""Checksummed atomic metadata writes for the DS sidecar files.

Every JSON sidecar the durable-session stack keeps next to the message
log (session checkpoints, ``share_progress.json``/
``share_members.json``, the topic census, the LTS index/pattern
registry) used to be written with a bare ``open(path, "w")`` or a
tmp+``os.replace`` WITHOUT file/dir fsync or any integrity check — a
power failure could leave a torn file that the loader's
``except (OSError, JSONDecodeError): {}`` silently turned into "fresh
start", resetting replay progress and losing acked QoS1 backlogs with
no alarm.  This module is the one write path for all of them
(brokerlint DUR701 enforces it):

  * WRITE — serialize with a CRC32 trailer, write to ``<path>.tmp``,
    fsync the tmp file, ``os.replace`` it over the target, fsync the
    directory (the crash-consistency literature's full atomic-rename
    recipe: ALICE, Pillai et al. OSDI '14).  ``fsync=False`` keeps the
    atomicity + CRC (process-crash safety) but skips the two fsyncs —
    the ``never``/``interval`` durability modes' metadata discipline.
  * LOAD — parse and verify.  A missing file raises
    ``FileNotFoundError`` ("fresh start" — fine); anything unreadable
    (IO error, broken JSON, CRC mismatch, truncation) raises
    `MetaCorruption` so the caller can raise the ``ds_meta_corruption``
    alarm and fall back CONSERVATIVELY (replay from the checkpoint,
    at-least-once) — never a silent reset to ``{}``.

Wrapped format: ``{"__dsmeta__": 1, "crc": <crc32>, "data": <obj>}``
where the crc covers the compact-canonical dump of ``data``.  Legacy
raw-JSON files (pre-PR data dirs) still load: parse success without the
wrapper is accepted as-is (there is nothing to verify them against).

``atomic_write_json`` is the ``ds.meta.write`` failpoint seam: chaos
runs inject write faults, lost writes, and duplicate writes at every
metadata boundary in one place.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Any, List, Optional, Tuple

from .. import failpoints

_MAGIC = "__dsmeta__"


class MetaCorruption(RuntimeError):
    """A metadata sidecar exists but cannot be trusted (torn write,
    bit rot, garbage).  Deliberately NOT an OSError: the legacy
    ``except OSError`` blocks this module replaces must never swallow
    it back into a silent empty-state reset."""

    def __init__(self, path: str, detail: str) -> None:
        super().__init__(f"{path}: {detail}")
        self.path = path
        self.detail = detail


def _canonical(obj: Any) -> str:
    return json.dumps(obj, separators=(",", ":"))


def dumps_checked(obj: Any) -> str:
    """The wrapped on-disk document for ``obj``."""
    payload = _canonical(obj)
    crc = zlib.crc32(payload.encode())
    return '{"%s":1,"crc":%d,"data":%s}' % (_MAGIC, crc, payload)


# crashsim write-trace tap (tools/crashsim): records every completed
# metadata replace so crash prefixes can be materialized
recorder = None


def atomic_write_json(path: str, obj: Any, fsync: bool = True) -> None:
    """Atomically (and, with ``fsync``, durably) replace ``path`` with
    the checksummed document for ``obj``.

    The ``ds.meta.write`` failpoint seam: ``error``/``panic`` raise
    before anything is written (the old file survives untouched),
    ``delay`` stalls the write, ``drop`` silently loses it (the torn-
    power scenario where the rename never persisted — recovery sees
    the previous checkpoint: conservative, at-least-once), and
    ``duplicate`` performs the replace twice (idempotent)."""
    doc = dumps_checked(obj)
    act = None
    if failpoints.enabled:
        act = failpoints.evaluate("ds.meta.write", key=path)
        if act == "drop":
            return
    _replace(path, doc, fsync)
    if act == "duplicate":
        _replace(path, doc, fsync)
    if recorder is not None:
        recorder.on_meta(path, doc.encode(), fsync)


def _replace(path: str, doc: str, fsync: bool) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(doc)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if fsync:
        _fsync_dir(os.path.dirname(path) or ".")


def _fsync_dir(dirpath: str) -> None:
    try:
        dfd = os.open(dirpath, os.O_RDONLY)
    except OSError:
        return  # platform without directory opens: best effort
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def loads_checked(raw: str, path: str = "<mem>") -> Any:
    """Parse a sidecar document: verified wrapped format, or legacy
    raw JSON (accepted unverified).  Raises `MetaCorruption`."""
    try:
        obj = json.loads(raw)
    except (json.JSONDecodeError, UnicodeDecodeError) as exc:
        raise MetaCorruption(path, f"unparseable: {exc}") from exc
    if isinstance(obj, dict) and obj.get(_MAGIC) == 1:
        if "crc" not in obj or "data" not in obj:
            raise MetaCorruption(path, "wrapper missing crc/data")
        payload = _canonical(obj["data"])
        crc = zlib.crc32(payload.encode())
        if crc != obj["crc"]:
            raise MetaCorruption(
                path, f"crc mismatch (stored {obj['crc']}, computed {crc})"
            )
        return obj["data"]
    return obj  # legacy raw JSON: parseable = accepted


def load_json(path: str) -> Any:
    """Load a sidecar.  ``FileNotFoundError`` = missing (fresh start);
    `MetaCorruption` = present but unreadable — the caller MUST alarm
    and fall back conservatively, never silently reset."""
    try:
        with open(path) as f:
            raw = f.read()
    except FileNotFoundError:
        raise
    except OSError as exc:
        raise MetaCorruption(path, f"unreadable: {exc}") from exc
    return loads_checked(raw, path)


# ------------------------------------------------------ journal frames
#
# Binary frames for the incremental metadata journals (ds/journal.py):
# ``[u32 len][u32 crc32(payload)][payload]`` where payload is compact
# JSON.  Same discipline as the dslog record format: a frame whose
# damage reaches EOF is the torn tail of a crashed append (stop
# silently — the delta scan re-learns it); damage with intact bytes
# AFTER it is interior corruption (stop AND report — the suffix's
# records are lost, so recovery must widen to the snapshot watermark
# and the alarm must fire).

_FRAME_HDR = struct.Struct("<II")
_MAX_FRAME_LEN = 16 << 20


def pack_frame(obj: Any) -> bytes:
    """One journal frame for ``obj``."""
    payload = _canonical(obj).encode()
    return _FRAME_HDR.pack(len(payload), zlib.crc32(payload)) + payload


def iter_frames(
    blob: bytes, path: str = "<mem>"
) -> Tuple[List[Any], Optional[str]]:
    """Decode a journal: ``(records, corrupt_detail)``.  The record
    list is always the valid prefix; ``corrupt_detail`` is None for a
    clean read OR a torn tail (the normal crash artifact), and a
    description when the break is INTERIOR (bytes follow the damage —
    a once-valid suffix was flipped on disk and its records are gone:
    the caller must alarm and fall back conservatively)."""
    out: List[Any] = []
    off, total = 0, len(blob)
    while off + _FRAME_HDR.size <= total:
        ln, crc = _FRAME_HDR.unpack_from(blob, off)
        end = off + _FRAME_HDR.size + ln
        if ln > _MAX_FRAME_LEN:
            # implausible length: flipped header.  Bytes beyond the
            # bare header mean data followed it — interior corruption.
            if total - off > _FRAME_HDR.size:
                return out, f"{path}: frame length {ln} implausible"
            return out, None
        if end > total:
            return out, None  # extends past EOF: torn tail
        payload = blob[off + _FRAME_HDR.size:end]
        if zlib.crc32(payload) != crc:
            if end < total:
                return out, f"{path}: interior frame crc break at {off}"
            return out, None  # torn tail of the crashed append
        try:
            out.append(json.loads(payload.decode()))
        except (json.JSONDecodeError, UnicodeDecodeError) as exc:
            # crc passed but payload unparseable: the frame was
            # WRITTEN corrupt — never a crash artifact, always report
            return out, f"{path}: frame at {off} unparseable: {exc}"
        off = end
    if off < total:
        return out, None  # partial header at EOF: torn tail
    return out, None


def try_load_json(path: str, default: Any) -> Tuple[Any, str]:
    """``(value, status)`` where status is ``ok`` | ``missing`` |
    ``corrupt``; ``default`` is returned for the last two.  The caller
    still owns reporting the ``corrupt`` case."""
    try:
        return load_json(path), "ok"
    except FileNotFoundError:
        return default, "missing"
    except MetaCorruption:
        return default, "corrupt"
