"""Durable storage (the `emqx_durable_storage` layer).

`api` defines the emqx_ds-style behavior (store_batch / get_streams /
make_iterator / next) with value-typed resumable iterators;
`builtin_local` is the real single-node backend on the native C++
dslog engine; `reference` is the trivially-correct in-memory oracle
used by the differential tests.
"""

from .api import DurableStorage, IterRef, StreamRef
from .builtin_local import LocalStorage
from .reference import ReferenceStorage

__all__ = [
    "DurableStorage",
    "IterRef",
    "StreamRef",
    "LocalStorage",
    "ReferenceStorage",
]
