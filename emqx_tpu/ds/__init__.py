"""Durable storage (the `emqx_durable_storage` layer).

`api` defines the emqx_ds-style behavior (store_batch / get_streams /
make_iterator / next) with value-typed resumable iterators;
`builtin_local` is the real single-node backend on the native C++
dslog engine; `lts` adds the learned topic structure on top of it;
`sharded` splits the store by stream hash into N independent
segment-log + metadata pairs; `journal` owns the incremental-metadata
algebra (append-only delta journal, fold-into-snapshot); `durability`
is the group-commit fsync gate (per-shard gates front a `GateGroup`);
`reference` is the trivially-correct in-memory oracle used by the
differential tests.
"""

from .api import DurableStorage, IterRef, StreamRef
from .builtin_local import LocalStorage
from .durability import GateGroup, SyncGate
from .journal import MetaJournal
from .lts import LtsStorage
from .reference import ReferenceStorage
from .sharded import ShardedStorage

__all__ = [
    "DurableStorage",
    "IterRef",
    "StreamRef",
    "LocalStorage",
    "LtsStorage",
    "ShardedStorage",
    "MetaJournal",
    "SyncGate",
    "GateGroup",
    "ReferenceStorage",
]
