"""Coordinated overload protection: the broker-wide load ladder.

The `emqx_olp` role (/root/reference/apps/emqx/src/emqx_olp.erl plus
the `emqx_os_mon`/`emqx_vm_mon` watermarks and `force_shutdown`): the
broker already grew the *sensors* — sysmon watermark alarms, limiter
token buckets, the profiler's stage histograms, the PublishBatcher
watermark, resume admission — but each subsystem degraded alone.
This module is the *coordinator*: one `LoadMonitor` folds the sensors
into a single load **level 0–3** with per-level enter/exit
thresholds, hysteresis (exit = enter × ``exit_factor``), and a
minimum hold time, and a degradation ladder wires that level through
the existing layers:

  ========  ========================================================
  level     degradation (cumulative: L2 includes L1, L3 includes L2)
  ========  ========================================================
  **L1**    new resume-scheduler admissions park (active replays keep
            draining); retained catch-up on subscribe defers (flushed
            when the ladder steps back to 0); background engine
            rebuilds defer; the batcher's max dispatch-window size
            shrinks to ``window_cap``.
  **L2**    effective-QoS0 *deliveries* shed via a mask folded into
            the window decision columns (one vectorized AND per QoS
            variant; $SYS messages exempt so the overload alarm
            itself survives); listener/zone shared token buckets
            clamp to ``limiter_clamp`` of their rate; CONNECT bursts
            over ``connect_budget``/s answer CONNACK server-busy.
  **L3**    QoS0 publishes drop at ingress; the ``slow_subs`` top-K
            slowest subscribers are force-closed with DISCONNECT
            server-busy (the ``force_shutdown`` analogue).
  ========  ========================================================

Invariant at every level: **zero QoS≥1 loss for admitted traffic** —
shedding is QoS0-only, refusals happen BEFORE state exists (CONNACK
server-busy), and every shed/deferred/refused unit is counted
(``olp.*`` / ``delivery.dropped.olp_shed`` counters), carried on the
standing ``overload`` $SYS alarm, and surfaced over ``GET
/api/v5/olp`` and ``ctl olp`` — never silent.

Signals sampled every ``sample_interval`` (all normalized against
config threshold triples, one per level):

  * ``loop_lag_ms``     — event-loop scheduling lag, measured as the
    housekeeping tick's overshoot past its 1 Hz cadence;
  * ``batcher_fill``    — PublishBatcher depth as a fraction of its
    global high watermark;
  * ``mqueue_backlog``  — aggregate mqueue backlog across sessions;
  * ``e2e_p99_ms``      — EWMA of the profiler's per-sample-interval
    publish→delivery p99 (PR 4 stage histograms, delta snapshots);
  * ``sysmem`` / ``procmem`` / ``cpu`` — the sysmon watermark inputs.

Ladder transitions step UP immediately (protection must react fast,
possibly jumping levels) and DOWN one level at a time, only after
``min_hold`` seconds AND once every signal sits below the current
level's exit threshold — the hysteresis that keeps a load square-wave
near a threshold from flapping the ladder.

Failpoint seams: ``olp.sample`` (a faulted sample round holds the
previous level) and ``olp.shed`` (a faulted shed-accounting path must
not break the protective action itself) — FP301-covered, chaos-tested.

Disabled by default (``olp.enable``), like the reference's
``overload_protection``: an unarmed broker pays one bool per tick and
one attribute load per dispatch window.
"""

from __future__ import annotations

import logging
import os
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from . import failpoints
from .observability import HistogramSnapshot
from .sysmon import _meminfo, _rss_bytes

log = logging.getLogger("emqx_tpu.olp")

# signal -> OlpConfig field carrying its (L1, L2, L3) enter thresholds
_SIGNAL_FIELDS = (
    "loop_lag_ms", "batcher_fill", "mqueue_backlog", "e2e_p99_ms",
    "sysmem", "procmem", "cpu",
)

# every ladder counter the REST/ctl surface reports (registry names)
COUNTERS = (
    "olp.level.changed",
    "olp.deferred.resume",
    "olp.deferred.retained",
    "olp.deferred.rebuild",
    "olp.dropped.retained",
    "olp.refused.connect",
    "olp.deferred.sink_flush",
    "olp.shed.publish_qos0",
    "olp.killed.slow_subs",
    "delivery.dropped.olp_shed",
    "delivery.dropped.out_buffer",
    "messages.dropped.olp_shed",
)

# the housekeeping cadence the loop-lag signal measures overshoot
# against (BrokerServer._housekeeping sleeps 1.0 s between ticks)
_TICK_INTERVAL = 1.0
# a tick gap beyond this is a clock jump or a test-injected timestamp,
# not event-loop lag
_LAG_CEILING_S = 60.0
# EWMA weight for the e2e-p99 signal; an idle interval decays the
# estimate by half so recovery is observable without fresh traffic
_EWMA_ALPHA = 0.3


class LoadMonitor:
    """Samples the broker's load sensors into one level 0-3 and owns
    the ladder's side effects.  Constructed unconditionally by the
    Broker; everything is a no-op while ``cfg.enable`` is False.

    Hot paths read the precomputed flag attributes only (one attribute
    load per window/run): ``shed_qos0_mask`` (L2), ``shed_ingress_qos0``
    (L3), ``defer_admissions`` (L1), ``defer_sink_flush`` (L1),
    ``window_cap_now`` (L1, 0 = off).
    """

    def __init__(self, broker, cfg) -> None:
        self.broker = broker
        self.cfg = cfg
        self.enabled = bool(cfg.enable)
        self.level = 0
        # shared limiters the L2 clamp scales (listener aggregates +
        # the node/zone bucket), registered by BrokerServer.start
        self.clamp_targets: List = []
        # hot-path flags (recomputed on every level transition)
        self.shed_qos0_mask = False
        self.shed_ingress_qos0 = False
        self.defer_admissions = False
        self.defer_sink_flush = False
        self.window_cap_now = 0
        self._thresholds: Dict[str, Tuple[float, float, float]] = {
            name: tuple(float(v) for v in getattr(cfg, name))
            for name in _SIGNAL_FIELDS
        }
        self._hold_until = 0.0
        self._clamped = False
        self._last_tick = 0.0
        self._last_sample = 0.0
        self._lag_ms = 0.0
        self._ewma_e2e = 0.0
        self._prev_e2e: Optional[HistogramSnapshot] = None
        self._signals: Dict[str, float] = {}
        self._transitions: deque = deque(maxlen=64)
        # deferred retained catch-up jobs, insertion-ordered (dict) so
        # the level-0 flush replays oldest-first; the value is None
        # (not matched yet) or the REMAINING message snapshot of a job
        # chunking across ticks — a numeric offset into a re-run match
        # would skip/duplicate messages when the retained set mutates
        # between ticks.  Bounded by ``retained_defer_cap`` (overflow
        # counted, never silent); snapshots exist only for the one job
        # a tick leaves mid-chunk.
        self._retained_defer: Dict[Tuple[str, str], Optional[List]] = {}
        self._shed_totals: Dict[str, int] = {}
        self._next_kill = 0.0
        self._rebuild_note = 0.0
        self._rebuild_deferred = False
        # L2 CONNECT admission budget (token bucket; refusals never
        # consume — a refused client's retry competes for the same
        # tokens)
        self._cb_tokens = float(cfg.connect_budget)
        self._cb_at = time.monotonic()

    # ------------------------------------------------------- sampling

    def tick(self, now: Optional[float] = None) -> int:
        """Driven at 1 Hz by `Broker.tick`: measures event-loop lag
        from the tick cadence, runs a full sample every
        ``sample_interval``, and advances the level-dependent
        housekeeping (retained-catch-up flush at level 0, periodic
        slow-subscriber kills at level 3)."""
        if not self.enabled:
            return self.level
        now = time.time() if now is None else now
        if self._last_tick:
            overshoot = (now - self._last_tick) - _TICK_INTERVAL
            # a forward jump past the ceiling is a clock jump (or a
            # test driving tick with synthetic times), not loop lag
            if 0.0 < overshoot < _LAG_CEILING_S:
                self._lag_ms = overshoot * 1000.0
            else:
                self._lag_ms = 0.0
        self._last_tick = now
        if now - self._last_sample >= float(self.cfg.sample_interval):
            self._last_sample = now
            try:
                self.sample(now)
            except failpoints.FailpointPanic:
                raise
            except Exception:
                # a faulted sample round must never take the broker
                # down with it; the PREVIOUS level (and its ladder
                # effects) hold until sampling recovers
                log.exception("olp sample failed; level %d held",
                              self.level)
        if self.level == 0 and self._rebuild_deferred:
            # sweep for the defer_rebuild/_set_level(0) race: an
            # engine mutation thread may flag a deferral just as the
            # ladder steps down — the tick catches it within a second
            self._rebuild_deferred = False
            try:
                self.broker.router.engine.kick_rebuild()
            except Exception:
                log.exception("olp recovery rebuild kick failed")
        if self.level == 0 and self._retained_defer:
            self._flush_retained()
        elif self.level >= 3 and now >= self._next_kill:
            self._next_kill = now + float(self.cfg.slow_kill_interval)
            self._kill_slow_subs()
        return self.level

    def sample(self, now: Optional[float] = None) -> Dict[str, float]:
        """Collect one signal snapshot and feed the level machine.
        Failpoint seam ``olp.sample``: drop = skip this round (level
        held), error = the tick's guard holds the level, delay = a
        slow sampler (chaos measures the ladder still converges)."""
        now = time.time() if now is None else now
        act = failpoints.evaluate("olp.sample")
        if act == "drop":
            return self._signals
        b = self.broker
        sig: Dict[str, float] = {"loop_lag_ms": self._lag_ms}
        batcher = b.batcher
        sig["batcher_fill"] = (
            batcher.depth() / max(batcher.global_high, 1)
            if batcher is not None else 0.0
        )
        sig["mqueue_backlog"] = float(b.cm.total_mqueued())
        sig["e2e_p99_ms"] = self._stage_p99()
        mem = _meminfo()
        total = mem.get("MemTotal", 0)
        avail = mem.get("MemAvailable", 0)
        sig["sysmem"] = 1.0 - (avail / total) if total else 0.0
        sig["procmem"] = (_rss_bytes() / total) if total else 0.0
        try:
            load1 = os.getloadavg()[0]
        except OSError:
            load1 = 0.0
        sig["cpu"] = load1 / (os.cpu_count() or 1)
        self.observe(sig, now)
        return sig

    def _stage_p99(self) -> float:
        """EWMA of the per-sample-interval e2e (publish→delivery) p99
        in ms, from delta snapshots of the profiler's cumulative
        histogram; idle intervals decay the estimate by half so the
        ladder can step down once traffic subsides."""
        snap = self.broker.profiler.snapshots().get("e2e")
        if snap is None:
            return self._ewma_e2e
        prev, self._prev_e2e = self._prev_e2e, snap
        if prev is None:
            return self._ewma_e2e
        d_count = snap.count - prev.count
        if d_count <= 0:
            self._ewma_e2e *= 0.5
            return self._ewma_e2e
        delta = HistogramSnapshot(
            tuple(a - b for a, b in zip(snap.counts, prev.counts)),
            snap.sum - prev.sum, d_count,
        )
        p99_ms = delta.percentile(99) / 1000.0  # recorded in µs
        self._ewma_e2e = (
            p99_ms if self._ewma_e2e == 0.0
            else _EWMA_ALPHA * p99_ms + (1 - _EWMA_ALPHA) * self._ewma_e2e
        )
        return self._ewma_e2e

    # --------------------------------------------------- level machine

    def observe(self, signals: Dict[str, float],
                now: Optional[float] = None) -> int:
        """Fold one signal snapshot into the level: UP transitions are
        immediate (and may jump several levels), DOWN transitions step
        ONE level at a time, only after ``min_hold`` seconds and once
        every signal is below the exit threshold (enter ×
        ``exit_factor``) of the current level — the hysteresis.  Pure
        against injected ``now``/signals, which is what the seeded
        property tests drive."""
        if not self.enabled:
            return self.level
        now = time.time() if now is None else now
        self._signals = dict(signals)
        xf = float(self.cfg.exit_factor)
        enter = 0
        exit_floor = 0
        for name, val in signals.items():
            t = self._thresholds.get(name)
            if t is None:
                continue
            for i in (2, 1, 0):
                if val >= t[i]:
                    if i + 1 > enter:
                        enter = i + 1
                    break
            for i in (2, 1, 0):
                if val >= t[i] * xf:
                    if i + 1 > exit_floor:
                        exit_floor = i + 1
                    break
        if enter > self.level:
            self._set_level(enter, now)
        elif exit_floor < self.level and now >= self._hold_until:
            self._set_level(self.level - 1, now)
        return self.level

    def _set_level(self, new: int, now: float) -> None:
        """One ladder transition: recompute the hot-path flags, apply
        the side effects that live on level EDGES (limiter clamp,
        slow-sub kill), and keep the operator surfaces honest ($SYS
        alarm with flap damping, metrics, the transition ring)."""
        old, self.level = self.level, new
        self._hold_until = now + float(self.cfg.min_hold)
        b = self.broker
        b.metrics.inc("olp.level.changed")
        b.stats.set("olp.level", new)
        self._transitions.append({
            "at": now, "from": old, "to": new,
            "signals": {k: round(v, 3) for k, v in self._signals.items()},
        })
        fl = getattr(b, "flight", None)
        if fl is not None:
            # the transition (with its sensor snapshot) joins the black
            # box; a jump INTO L2+ is itself a dump trigger — the ring
            # holds the minute of windows that pushed the ladder up
            fl.olp_transition(
                old, new, self._lag_ms,
                {k: round(v, 3) for k, v in self._signals.items()},
            )
        self.shed_qos0_mask = new >= 2
        self.shed_ingress_qos0 = new >= 3
        self.defer_admissions = new >= 1
        # sink micro-batch flushes stretch their linger at L1+ —
        # egress deferral buys headroom BEFORE any QoS0 shedding
        self.defer_sink_flush = new >= 1
        self.window_cap_now = int(self.cfg.window_cap) if new >= 1 else 0
        want_clamp = new >= 2
        if want_clamp != self._clamped:
            self._clamped = want_clamp
            factor = float(self.cfg.limiter_clamp) if want_clamp else 1.0
            for lim in self.clamp_targets:
                try:
                    lim.clamp(factor)
                except Exception:
                    log.exception("olp limiter clamp failed")
        try:
            if new >= 1:
                b.alarms.update(
                    "overload",
                    details={
                        "level": new,
                        "signals": {
                            k: round(v, 3)
                            for k, v in self._signals.items()
                        },
                        "shed": dict(self._shed_totals),
                    },
                    message=f"broker overload ladder at level {new}",
                    min_reraise=float(self.cfg.alarm_min_reraise),
                    now=now,
                )
            else:
                # hysteresis hold on the deactivate too: a re-raise
                # inside the hold cancels it without $SYS churn
                b.alarms.deactivate(
                    "overload", hold=float(self.cfg.alarm_hold), now=now
                )
        except Exception:
            log.exception("olp alarm update failed")
        if new >= 3 and old < 3:
            self._next_kill = now + float(self.cfg.slow_kill_interval)
            self._kill_slow_subs()
        if new == 0 and self._rebuild_deferred:
            # recovery kick: a rebuild deferred during the episode
            # must not wait for the next unrelated mutation (a stable
            # fleet may never mutate again)
            self._rebuild_deferred = False
            try:
                b.router.engine.kick_rebuild()
            except Exception:
                log.exception("olp recovery rebuild kick failed")
        (log.warning if new > old else log.info)(
            "olp level %d -> %d (signals: %s)", old, new,
            {k: round(v, 3) for k, v in self._signals.items()},
        )

    # ------------------------------------------------ shed accounting

    def shed(self, kind: str, n: int = 1) -> None:
        """The ONE accounting point for ladder shed/defer/refuse
        EVENTS: counter (``olp.<kind>``), the REST ledger, and — via
        the standing ``overload`` alarm details — $SYS.  (Per-DELIVERY
        sheds are counted by the dispatch window itself, batched into
        its ``mloc`` flush under the ``delivery.dropped.olp_shed``
        registry names.)  Failpoint seam ``olp.shed``: an injected (or
        real) accounting fault must never break the protective action
        itself, so faults short of a panic still count through the
        direct metrics path."""
        try:
            failpoints.evaluate("olp.shed", key=kind)
            self._shed_totals[kind] = self._shed_totals.get(kind, 0) + n
            self.broker.metrics.inc("olp." + kind, n)
        except failpoints.FailpointPanic:
            raise
        except Exception:
            # the shed itself already happened (or is about to): keep
            # it observable even when the primary accounting faulted
            try:
                self.broker.metrics.inc("olp." + kind, n)
            except Exception:
                pass
            log.exception("olp shed accounting failed for %s", kind)

    # --------------------------------------------------- L1 deferrals

    def defer_retained(self, clientid: str, flt: str) -> bool:
        """L1: park a subscription's retained catch-up (the match walk
        + delivery burst) until the ladder steps back to 0; the tick
        then flushes ``retained_flush_per_tick`` jobs per second.
        Returns True when the caller must answer with no retained
        messages now.  Past ``retained_defer_cap`` the job is dropped
        — counted (``olp.dropped.retained``), never silent; the
        client re-subscribing after recovery replays normally."""
        if self.level < 1:
            return False
        key = (clientid, flt)
        if key not in self._retained_defer:
            if len(self._retained_defer) >= int(
                self.cfg.retained_defer_cap
            ):
                self.shed("dropped.retained")
            else:
                self._retained_defer[key] = None
                self.shed("deferred.retained")
        return True

    def cancel_retained_client(self, clientid: str) -> None:
        """Drop every parked catch-up job of a discarded/terminated/
        exported session — dead clients' jobs must not exhaust
        ``retained_defer_cap`` and crowd out live subscribers."""
        if not self._retained_defer:
            return
        for key in [
            k for k in self._retained_defer if k[0] == clientid
        ]:
            del self._retained_defer[key]

    def cancel_retained(self, clientid: str, flt: str) -> None:
        """Drop a parked catch-up job: the client unsubscribed, or
        re-subscribed with retain_handling that forbids retained —
        the flush must not deliver a burst the CURRENT subscription
        options disallow."""
        self._retained_defer.pop((clientid, flt), None)

    def _flush_retained(self) -> None:
        """Level back at 0: replay deferred retained catch-up, oldest
        first, paced at ``retained_flush_per_tick`` MESSAGES per tick
        — a single filter matching a huge retained set chunks across
        ticks (the job re-parks with its offset) — so recovery itself
        cannot stall the event loop and re-trigger the ladder.  Jobs
        whose session/subscription vanished meanwhile (or whose
        CURRENT options forbid retained) are skipped — a reconnect's
        fresh SUBSCRIBE replays retained normally."""
        from .broker.session import SubOpts

        b = self.broker
        budget = int(self.cfg.retained_flush_per_tick)
        while self._retained_defer and budget > 0 and self.level == 0:
            key = next(iter(self._retained_defer))
            remaining = self._retained_defer.pop(key)
            cid, flt = key
            session = b.cm.lookup(cid)
            if session is None:
                budget -= 1  # every job costs >= 1 (bounded scans)
                continue
            opts = session.subscriptions.get(flt)
            if (
                opts is None
                or opts.share_group is not None
                or opts.retain_handling == 2
            ):
                budget -= 1
                continue
            if remaining is None:
                # first chunk: ONE match walk per job; the tail (if
                # any) re-parks as a message snapshot, so a mutating
                # retained set can't skip or duplicate deliveries
                try:
                    msgs = b.retainer.match(flt)
                except Exception:
                    log.exception(
                        "deferred retained match failed for %s", flt
                    )
                    budget -= 1
                    continue
            else:
                msgs = remaining
            if not msgs:
                budget -= 1
                continue
            if len(msgs) > budget:
                # chunk: deliver a budget's worth now, re-park the
                # tail snapshot (FIFO end — other jobs go first)
                self._retained_defer[key] = msgs[budget:]
                msgs = msgs[:budget]
            budget -= max(len(msgs), 1)
            # retained replay keeps the retain bit set [MQTT-3.3.1-8],
            # exactly as the in-line subscribe path builds it
            ropts = SubOpts(
                qos=opts.qos, retain_as_published=True, subid=opts.subid
            )
            jobs = [(m, ropts) for m in msgs]
            channel = b.cm.channel(cid)
            from collections import Counter

            mloc: "Counter" = Counter()
            try:
                if channel is not None and not b._stalled(
                    session, channel
                ):
                    channel.send_packets(session.deliver(jobs))
                elif channel is not None:
                    # still over its outbound watermark: the catch-up
                    # burst must respect the SAME stall gate as live
                    # dispatch (QoS0 dropped + counted, QoS>0 parked)
                    # — not pile onto the overflowing buffer
                    b._queue_stalled_run(
                        session, cid, jobs, mloc, None
                    )
                else:
                    # detached persistent session: the shared queue
                    # path — QoS>0 to the mqueue, QoS0 dropped AND
                    # counted (never silent), no_local respected
                    b._queue_detached_run(
                        session, cid, jobs, mloc, None
                    )
            except Exception:
                log.exception("deferred retained flush to %s failed",
                              cid)
            if mloc:
                b.metrics.inc_bulk(mloc)

    def defer_rebuild(self) -> bool:
        """L1: the match engine asks before scheduling a background
        rebuild; True = defer (the delta tiers keep serving
        correctness, the rebuild fires on the first post-recovery
        delta).  Called from engine mutation paths — possibly off the
        loop thread — so it touches only counters."""
        if not self.defer_admissions:
            return False
        self._rebuild_deferred = True  # recovery kicks it (level 0)
        now = time.time()
        if now - self._rebuild_note >= 1.0:
            # throttle: one counted deferral per second, not one per
            # blocked insert batch
            self._rebuild_note = now
            self.shed("deferred.rebuild")
        return True

    # ------------------------------------------------ L2 connect gate

    def refuse_connect(self, now: Optional[float] = None) -> bool:
        """L2: CONNECT admission budget — ``connect_budget`` tokens/s,
        refusals do NOT consume (a refused client's retry competes for
        the same tokens).  True = answer CONNACK server-busy."""
        if self.level < 2:
            return False
        rate = float(self.cfg.connect_budget)
        if rate <= 0:
            return False
        now = time.monotonic() if now is None else now
        self._cb_tokens = min(
            rate, self._cb_tokens + (now - self._cb_at) * rate
        )
        self._cb_at = now
        if self._cb_tokens >= 1.0:
            self._cb_tokens -= 1.0
            return False
        self.shed("refused.connect")
        return True

    # ------------------------------------------------- L3 force close

    def _kill_slow_subs(self) -> None:
        """L3: force-close the slow-subs board's top-K slowest
        subscribers (DISCONNECT server-busy — the `force_shutdown`
        analogue).  Their sessions survive per their expiry, so a
        persistent subscriber loses its socket, not its QoS1 state."""
        b = self.broker
        killed = 0
        seen: set = set()
        for entry in b.slow_subs.top():
            if killed >= int(self.cfg.slow_kill_max):
                break
            cid = entry["clientid"]
            if cid in seen:
                continue  # one board entry per DELIVERY: dedupe, or
                # one pathological client burns the whole kill budget
            seen.add(cid)
            channel = b.cm.channel(cid)
            if channel is None or getattr(channel, "_closing", False):
                continue
            try:
                channel.close("olp_overloaded")
            except Exception:
                log.exception("olp slow-sub close failed for %s", cid)
                continue
            killed += 1
            log.warning("olp L3 force-closed slow subscriber %s "
                        "(latency %.0f ms)", cid,
                        entry["latency_ms"])
        if killed:
            self.shed("killed.slow_subs", killed)

    # ----------------------------------------------------------- info

    def info(self) -> Dict[str, object]:
        """Operator surface (``GET /api/v5/olp``, ``ctl olp``)."""
        m = self.broker.metrics
        now = time.time()
        return {
            "enable": self.enabled,
            "level": self.level,
            "signals": {
                k: round(v, 4) for k, v in self._signals.items()
            },
            "thresholds": {
                k: list(v) for k, v in self._thresholds.items()
            },
            "exit_factor": float(self.cfg.exit_factor),
            "min_hold": float(self.cfg.min_hold),
            "hold_remaining": round(max(0.0, self._hold_until - now), 3),
            "window_cap": self.window_cap_now,
            "clamped": self._clamped,
            "retained_deferred": len(self._retained_defer),
            "shed": dict(self._shed_totals),
            "counters": {name: m.val(name) for name in COUNTERS},
            "transitions": list(self._transitions),
        }
