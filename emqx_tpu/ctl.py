"""`emqx_tpu.ctl` — the operator CLI against a running broker's
management API.

The `emqx_ctl` role (/root/reference/apps/emqx_ctl/src/emqx_ctl.erl:
command registry dispatched from bin/emqx_ctl via nodetool RPC); here
commands are HTTP calls to the REST surface, so the CLI works against
any reachable broker:

    python -m emqx_tpu.ctl status
    python -m emqx_tpu.ctl clients [kick <clientid>]
    python -m emqx_tpu.ctl subscriptions | topics | rules | metrics
    python -m emqx_tpu.ctl publish <topic> <payload> [--qos N]
    python -m emqx_tpu.ctl trace start <name> <type> <match> | stop <name>
    python -m emqx_tpu.ctl banned [add <as> <who>] [del <as> <who>]
    python -m emqx_tpu.ctl data export | import <archive.tar.gz>
    python -m emqx_tpu.ctl rebalance [start|stop|status]
    python -m emqx_tpu.ctl rebalance evacuation start|stop
    python -m emqx_tpu.ctl rebalance purge start|stop
    python -m emqx_tpu.ctl failpoints [list|set <name> <action> [k=v ...]
                                       |clear [name]]
    python -m emqx_tpu.ctl profiler [summary|windows|reset
                                     |trace [out.json]]
    python -m emqx_tpu.ctl tracing [status|on [rate]|off|rate <r>
                                    |filter <topic> ...|traces [n]
                                    |show <trace_id>|mid <hex>
                                    |perfetto [out.json] [peer-url ...]
                                    |reset]
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import sys
import urllib.error
import urllib.request
from typing import Any, Optional


class Ctl:
    def __init__(self, base: str, user: Optional[str] = None,
                 api_key: Optional[str] = None) -> None:
        """`user`/`api_key` are "name:secret" pairs; user logs in for a
        Bearer token, api_key goes as HTTP Basic (emqx_mgmt_auth)."""
        self.base = base.rstrip("/")
        # remembered so peer-node clients (tracing perfetto merge) can
        # authenticate the same way
        self._peer_user = user
        self._peer_api_key = api_key
        self._auth: Optional[str] = None
        if api_key:
            self._auth = "Basic " + base64.b64encode(
                api_key.encode()
            ).decode()
        elif user:
            username, _, password = user.partition(":")
            out = self._req("/api/v5/login", method="POST", body={
                "username": username, "password": password,
            })
            self._auth = "Bearer " + out["token"]

    def _req(
        self,
        path: str,
        method: str = "GET",
        body: Optional[dict] = None,
        raw: Optional[bytes] = None,
        timeout: float = 10.0,
    ) -> Any:
        if raw is not None:
            headers = {"Content-Type": "application/octet-stream"}
            data = raw
        else:
            headers = {"Content-Type": "application/json"}
            data = None if body is None else json.dumps(body).encode()
        if self._auth:
            headers["Authorization"] = self._auth
        req = urllib.request.Request(
            self.base + path, method=method, data=data, headers=headers
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                out = resp.read()
                return json.loads(out) if out else None
        except urllib.error.HTTPError as exc:
            detail = exc.read().decode(errors="replace")
            raise SystemExit(f"error {exc.code}: {detail}")
        except urllib.error.URLError as exc:
            raise SystemExit(f"cannot reach broker API at {self.base}: {exc}")

    # ------------------------------------------------------- commands

    def status(self) -> None:
        nodes = self._req("/api/v5/nodes")
        for n in nodes["data"]:
            print(
                f"node {n['node']} is {n['node_status']}; "
                f"uptime {n['uptime']}s; {n['connections']} connections"
                + (f"; olp level {n['olp_level']}"
                   if "olp_level" in n else "")
            )
            resume = n.get("resume")
            if resume:
                print(
                    f"  resume queue: {resume['active']} active / "
                    f"{resume['parked']} parked / "
                    f"{resume['paused']} paused "
                    f"(max_concurrent={resume['max_concurrent']}, "
                    f"park_cap={resume['park_queue_cap']}, "
                    f"windowed={resume['windowed']})"
                )
            dura = n.get("durability")
            if dura:
                print(
                    f"  durability: fsync={dura['fsync']}; "
                    f"{dura['sync_count']} syncs "
                    f"({dura['sync_errors']} errors), "
                    f"{dura['unsynced']} unsynced / "
                    f"{dura['parked']} parked acks; "
                    f"corruption: {dura['corrupt_records']} records "
                    f"quarantined, {dura['meta_corruption']} meta"
                )
                if dura.get("meta_rebuild"):
                    print(
                        "    census rebuild in progress: "
                        f"{dura.get('meta_rebuild_scanned', 0)}/"
                        f"{dura.get('meta_rebuild_total', 0)} streams"
                    )
                for row in dura.get("per_shard") or ():
                    print(
                        f"    shard {row.get('shard')}: "
                        f"{row.get('sync_count', 0)} syncs "
                        f"({row.get('sync_errors', 0)} errors), "
                        f"{row.get('unsynced', 0)} unsynced / "
                        f"{row.get('parked', 0)} parked; "
                        f"{row.get('corrupt_records', 0)} corrupt / "
                        f"{row.get('quarantined_segments', 0)} "
                        "quarantined segs"
                    )
            eg = n.get("egress")
            if eg:
                print(
                    f"  egress: {eg['sinks']} sinks, "
                    f"{eg['buffered']} buffered, "
                    f"{eg['batches']} batches flushed "
                    f"({eg['flush_deferred']} deferred); "
                    f"{eg['breakers_open']} breakers open"
                )
            mc = n.get("multicore")
            if mc:
                svc = mc.get("service") or {}
                ring = svc.get("ring") or {}
                state = "attached" if svc.get("attached") else "detached"
                print(
                    f"  multicore: worker {mc.get('worker_id')}"
                    f"/{mc.get('n_workers')} {state}"
                    + (f"; ring {ring.get('in_flight')}/"
                       f"{ring.get('slots')} in flight "
                       f"(hwm={ring.get('high_watermark')}, "
                       f"full={ring.get('full')})" if ring else "")
                )
                rstats = (svc.get("service") or {}).get("stats") or {}
                if rstats:
                    print(
                        "    matchsvc: "
                        + " ".join(f"{k}={rstats[k]}"
                                   for k in sorted(rstats))
                    )
            fl = n.get("flight")
            if fl:
                print(
                    f"  flight: armed; {fl.get('events_recorded')} "
                    f"events in ring; {fl.get('triggers')} triggers "
                    f"({fl.get('triggers_suppressed')} suppressed); "
                    f"last dump {fl.get('last_id') or '-'}"
                )
        cluster = nodes.get("cluster") or {}
        if cluster:
            print(
                f"cluster: peers={cluster.get('alive', [])} "
                f"down={cluster.get('down', [])} "
                f"routes={cluster.get('routes')}"
            )
            fwd = cluster.get("forward") or {}
            if fwd:
                print(
                    f"  forward: mode={fwd.get('mode')} "
                    f"quic_demotions={fwd.get('quic_demotions')}"
                )
                for peer, st in (fwd.get("peers") or {}).items():
                    print(
                        f"    {peer}: breaker={st['breaker']} "
                        f"unacked={st['unacked_frames']}f/"
                        f"{st['unacked_msgs']}m "
                        f"acked={st['acked_frames']} "
                        f"shed={st['shed_msgs']}"
                    )

    def clients(self, kick: Optional[str] = None) -> None:
        if kick:
            self._req(f"/api/v5/clients/{kick}", method="DELETE")
            print(f"kicked {kick}")
            return
        data = self._req("/api/v5/clients")
        for c in data["data"]:
            state = "connected" if c["connected"] else "detached"
            print(
                f"{c['clientid']}\t{state}\tsubs={c['subscriptions_cnt']}"
                f"\tmqueue={c['mqueue_len']}"
            )
        print(f"({data['meta']['count']} clients)")

    def subscriptions(self) -> None:
        data = self._req("/api/v5/subscriptions")
        for s in data["data"]:
            print(f"{s['clientid']}\t{s['topic']}")
        print(f"({data['meta']['count']} subscriptions)")

    def topics(self) -> None:
        data = self._req("/api/v5/topics")
        for t in data["data"]:
            print(f"{t['topic']}\t{t['node']}")
        print(f"({data['meta']['count']} topics)")

    def rules(self) -> None:
        for r in self._req("/api/v5/rules")["data"]:
            state = "enabled" if r["enabled"] else "disabled"
            print(f"{r['id']}\t{state}\tmatched={r['matched']}\t{r['sql']}")

    def metrics(self, name: Optional[str] = None) -> None:
        data = self._req("/api/v5/metrics")
        for k in sorted(data):
            if name is None or name in k:
                print(f"{k}\t{data[k]}")

    def stats(self) -> None:
        data = self._req("/api/v5/stats")
        for k in sorted(data):
            print(f"{k}\t{data[k]}")

    def publish(self, topic: str, payload: str, qos: int = 0) -> None:
        out = self._req(
            "/api/v5/publish",
            method="POST",
            body={"topic": topic, "payload": payload, "qos": qos},
        )
        print(f"delivered to {out['delivered']} subscribers")

    def trace(self, action: str, *args: str) -> None:
        if action == "list":
            for t in self._req("/api/v5/trace")["data"]:
                print(
                    f"{t['name']}\t{t['type']}={t['match']}\t"
                    f"hits={t['hits']}\t{t['file']}"
                )
        elif action == "start":
            name, kind, match = args[0], args[1], args[2]
            out = self._req(
                "/api/v5/trace",
                method="POST",
                body={"name": name, "type": kind, "match": match},
            )
            print(f"tracing to {out['file']}")
        elif action == "stop":
            self._req(f"/api/v5/trace/{args[0]}", method="DELETE")
            print(f"stopped {args[0]}")
        else:
            raise SystemExit(f"unknown trace action {action!r}")

    def data(self, action: str, *args: str) -> None:
        """Backup/restore (emqx ctl data export|import <file>)."""
        if action == "export":
            out = self._req("/api/v5/data/export", method="POST")
            print(f"exported {out['filename']}: {out['counts']}")
        elif action == "import":
            if not args:
                raise SystemExit("usage: data import <archive.tar.gz>")
            with open(args[0], "rb") as f:
                blob = f.read()
            report = self._req(
                "/api/v5/data/import", method="POST", raw=blob,
                timeout=60,
            )
            print(f"restored: {report['restored']}")
            if report.get("skipped"):
                print(f"skipped (reboot-only): {report['skipped']}")
            for err in report.get("errors", ()):
                print(f"error: {err}")
        else:
            raise SystemExit(f"unknown data action {action!r}")

    def rebalance(self, action: str = "status", *args: str) -> None:
        """Elastic ops (emqx ctl rebalance): evacuation, cluster
        balance, detached-session purge."""
        if action == "status":
            info = self._req("/api/v5/load_rebalance/status")
            for kind, d in info.items():
                line = "\t".join(f"{k}={v}" for k, v in d.items()
                                 if k != "plan")
                print(f"{kind}:\t{line}")
                if d.get("plan"):
                    print(f"\tplan: {json.dumps(d['plan'])}")
        elif action == "start":
            out = self._req("/api/v5/load_rebalance/start",
                            method="POST", body={})
            print(f"rebalance: {out['status']}")
            if out.get("plan"):
                print(f"plan: {json.dumps(out['plan'])}")
        elif action == "stop":
            self._req("/api/v5/load_rebalance/stop", method="POST")
            print("rebalance stopped")
        elif action == "evacuation":
            sub = args[0] if args else "status"
            if sub == "status":
                info = self._req("/api/v5/load_rebalance/status")
                print(json.dumps(info["evacuation"]))
            elif sub == "start":
                out = self._req(
                    "/api/v5/load_rebalance/evacuation/start",
                    method="POST", body={},
                )
                print(f"evacuation: {out['status']}")
            elif sub == "stop":
                out = self._req(
                    "/api/v5/load_rebalance/evacuation/stop",
                    method="POST",
                )
                print(f"evacuation: {out['status']} "
                      f"(evicted {out['evicted']})")
            else:
                raise SystemExit(f"unknown evacuation action {sub!r}")
        elif action == "purge":
            sub = args[0] if args else "status"
            if sub == "status":
                info = self._req("/api/v5/load_rebalance/status")
                print(json.dumps(info["purge"]))
            elif sub == "start":
                out = self._req(
                    "/api/v5/load_rebalance/purge/start",
                    method="POST", body={"cluster": True},
                )
                print(f"purge: {out['status']}")
            elif sub == "stop":
                out = self._req(
                    "/api/v5/load_rebalance/purge/stop",
                    method="POST", body={"cluster": True},
                )
                print(f"purge: {out['status']} "
                      f"(purged {out['purged']})")
            else:
                raise SystemExit(f"unknown purge action {sub!r}")
        else:
            raise SystemExit(f"unknown rebalance action {action!r}")

    def failpoints(self, action: str = "list", *args: str) -> None:
        """Chaos controls: list/arm/clear failpoints on a live broker.

            failpoints list
            failpoints set <name> <action> [prob=0.3] [delay=0.1]
                           [after=10] [times=5] [seed=7] [match=n0]
            failpoints clear [name]
        """
        if action == "list":
            info = self._req("/api/v5/failpoints")
            brk = info.get("engine_breaker", {})
            print(
                f"framework {'ARMED' if info['enabled'] else 'disabled'}"
                f"; engine breaker "
                f"{'OPEN' if brk.get('open') else 'closed'} "
                f"(trips={brk.get('trips')})"
            )
            for p in info["data"]:
                opts = " ".join(
                    f"{k}={p[k]}"
                    for k in ("prob", "delay", "after", "times",
                              "match", "seed")
                    if p.get(k) not in (None, "")
                )
                print(f"{p['name']}\t{p['action']}\t{opts}\t"
                      f"hits={p['hits']} fires={p['fires']}")
        elif action == "set":
            if len(args) < 2:
                raise SystemExit(
                    "usage: failpoints set <name> <action> [k=v ...]"
                )
            body = {"action": args[1]}
            for kv in args[2:]:
                k, _, v = kv.partition("=")
                body[k] = v
            out = self._req(
                f"/api/v5/failpoints/{args[0]}", method="PUT", body=body
            )
            print(f"armed {out['name']}: {out['action']}")
        elif action == "clear":
            path = "/api/v5/failpoints" + (f"/{args[0]}" if args else "")
            self._req(path, method="DELETE")
            print(f"cleared {args[0] if args else 'all failpoints'}")
        else:
            raise SystemExit(f"unknown failpoints action {action!r}")

    def profiler(self, action: str = "summary", *args: str) -> None:
        """Window-pipeline profiler: stage latencies, the flight
        recorder's recent windows, Perfetto trace export.

            profiler summary
            profiler windows [n]
            profiler trace [out.json] [n]
            profiler reset
        """
        if action == "summary":
            info = self._req("/api/v5/profiler")
            print(f"profiler {'on' if info['enabled'] else 'OFF'}")
            print("stage\tcount\tp50_us\tp95_us\tp99_us")
            for name, d in sorted(info["histograms_us"].items()):
                if not d["count"]:
                    continue
                print(f"{name}\t{d['count']}\t{d['p50']:.0f}"
                      f"\t{d['p95']:.0f}\t{d['p99']:.0f}")
            eng = info.get("engine", {})
            line = " ".join(
                f"{k}={eng[k]}"
                for k in ("base", "delta", "residual", "deep",
                          "auto_host_windows", "auto_dev_windows",
                          "breaker_open")
                if k in eng
            )
            print(f"engine: {line}")
        elif action == "windows":
            n = int(args[0]) if args else 16
            info = self._req(f"/api/v5/profiler?windows={n}")
            for w in info["windows"]:
                stages = " ".join(
                    f"{k}={v:.0f}us"
                    for k, v in w["stages_us"].items()
                )
                print(
                    f"#{w['seq']}\t{w['source']}\tmsgs={w['n_msgs']}"
                    f"\tdeliv={w['n_deliveries']}\tpath={w['path']}"
                    f"\t{stages}"
                )
        elif action == "trace":
            out_path = args[0] if args else "profiler_trace.json"
            q = f"?windows={args[1]}" if len(args) > 1 else ""
            trace = self._req(f"/api/v5/profiler/trace{q}")
            with open(out_path, "w") as f:
                json.dump(trace, f)
            print(
                f"wrote {len(trace['traceEvents'])} trace events to "
                f"{out_path}; open it at https://ui.perfetto.dev or "
                "chrome://tracing"
            )
        elif action == "reset":
            self._req("/api/v5/profiler", method="DELETE")
            print("profiler histograms + flight recorder reset")
        else:
            raise SystemExit(f"unknown profiler action {action!r}")

    def tracing(self, action: str = "status", *args: str) -> None:
        """Per-message lifecycle tracing: sampler control, trace/mid
        queries, merged multi-node Perfetto export.

            tracing status
            tracing on [rate] | off | rate <r> | filter <topic> ...
            tracing traces [n]
            tracing show <trace_id>
            tracing mid <message-id-hex>
            tracing perfetto [out.json] [peer-api-url ...]
            tracing reset
        """
        if action == "status":
            info = self._req("/api/v5/tracing")
            state = (
                ("ACTIVE" if info["sampling"]
                 else "on (adopting upstream contexts only)")
                if info["active"] else "off"
            )
            print(f"lifecycle tracing {state}; node {info['node']}")
            print(
                f"rate={info['sample_rate']} "
                f"filters={info['topic_filters']} "
                f"traces={info['traces']}/{info['store_max']} "
                f"spans={info['spans']} sampled={info['sampled']} "
                f"remote={info['remote']} forwards={info['forwards']} "
                f"evicted={info['evicted']}"
            )
        elif action in ("on", "off", "rate", "filter"):
            body: dict = {}
            if action == "on":
                body["enable"] = True
                if args:
                    body["sample_rate"] = float(args[0])
            elif action == "off":
                body["enable"] = False
            elif action == "rate":
                body["enable"] = True
                body["sample_rate"] = float(args[0])
            else:
                body["enable"] = True
                body["topic_filters"] = list(args)
            info = self._req("/api/v5/tracing", method="PUT", body=body)
            print(f"tracing {'ACTIVE' if info['active'] else 'off'}: "
                  f"rate={info['sample_rate']} "
                  f"filters={info['topic_filters']}")
        elif action == "traces":
            n = int(args[0]) if args else 32
            data = self._req(f"/api/v5/tracing/traces?limit={n}")["data"]
            for t in data:
                print(
                    f"{t['trace_id']}\t{t['topic']}\t"
                    f"{t['duration_ms']}ms\tspans={t['n_spans']}\t"
                    f"nodes={','.join(t['nodes'])}"
                )
            print(f"({len(data)} traces)")
        elif action == "show":
            out = self._req(f"/api/v5/tracing/traces/{args[0]}")
            self._print_spans(out["spans"])
        elif action == "mid":
            out = self._req(f"/api/v5/tracing/messages/{args[0]}")
            print(f"trace {out['trace_id']}")
            self._print_spans(out["spans"])
        elif action == "perfetto":
            from .tracecontext import chrome_trace

            out_path = args[0] if args else "tracing_timeline.json"
            spans = list(self._req("/api/v5/tracing/spans")["data"])
            # extra operands are PEER api base URLs: merge their span
            # dumps into ONE timeline (per-node process tracks + flow
            # events come from the spans' own node labels)
            for peer in args[1:]:
                peer_ctl = Ctl(peer, user=self._peer_user,
                               api_key=self._peer_api_key)
                spans.extend(
                    peer_ctl._req("/api/v5/tracing/spans")["data"]
                )
            trace = chrome_trace(spans)
            with open(out_path, "w") as f:
                json.dump(trace, f)
            print(
                f"wrote {len(trace['traceEvents'])} events "
                f"({len(spans)} spans) to {out_path}; open it at "
                "https://ui.perfetto.dev or chrome://tracing"
            )
        elif action == "reset":
            self._req("/api/v5/tracing", method="DELETE")
            print("trace store cleared")
        else:
            raise SystemExit(f"unknown tracing action {action!r}")

    @staticmethod
    def _print_spans(spans: list) -> None:
        spans = sorted(spans, key=lambda s: s["start_ns"])
        t0 = spans[0]["start_ns"] if spans else 0
        for s in spans:
            off_ms = (s["start_ns"] - t0) / 1e6
            dur_ms = (s["end_ns"] - s["start_ns"]) / 1e6
            a = s.get("attrs", {})
            extra = " ".join(
                f"{k}={a[k]}" for k in
                ("topic", "deliveries", "target", "ok", "path")
                if k in a
            )
            print(
                f"+{off_ms:8.3f}ms {dur_ms:8.3f}ms  {s['node']:<14} "
                f"{s['name']:<18} span={s['span_id'][:8]} "
                f"parent={(s.get('parent_id') or '-')[:8]} {extra}"
            )

    def flight(self, action: str = "status", *args: str) -> None:
        """Always-on flight recorder: status, manual dump, merged
        cross-process Perfetto export.

            flight status
            flight dump
            flight show <id> [out.json]
        """
        if action == "status":
            info = self._req("/api/v5/flight")
            st = info["status"]
            state = "armed" if st["armed"] else "DISARMED"
            print(
                f"flight recorder {state} [{st['role']} {st['node']} "
                f"pid={st['pid']}]; ring {st['events_recorded']}"
                f"/{st['ring_size']} events; "
                f"{st['triggers']} triggers "
                f"({st['triggers_suppressed']} suppressed, "
                f"debounce {st['min_dump_interval']}s)"
            )
            if st.get("slo_p99_ms"):
                print("  slo p99 (ms): " + " ".join(
                    f"{k}={v}" for k, v in
                    sorted(st["slo_p99_ms"].items())))
            dumps = info.get("dumps") or []
            if not dumps:
                print("  no dumps captured")
            for row in dumps:
                print(f"  dump {row['id']}: "
                      f"{len(row['files'])} process file(s)")
        elif action == "dump":
            out = self._req("/api/v5/flight/dump", method="POST",
                            body={})
            print(f"dump triggered: id {out['id']}")
        elif action == "show":
            if not args:
                raise SystemExit("usage: flight show <id> [out.json]")
            trig_id = args[0]
            out_path = args[1] if len(args) > 1 else (
                f"flight_{trig_id}.json")
            info = self._req(f"/api/v5/flight/{trig_id}")
            for p in info["processes"]:
                print(f"  {p['role']} {p['node']} pid={p['pid']} "
                      f"({p['reason']})")
            if info.get("torn"):
                print(f"  {info['torn']} torn dump file(s) skipped")
            trace = info["trace"]
            with open(out_path, "w") as f:
                json.dump(trace, f)
            print(
                f"wrote {len(trace['traceEvents'])} merged trace "
                f"events from {len(info['processes'])} process(es) to "
                f"{out_path}; open it at https://ui.perfetto.dev or "
                "chrome://tracing"
            )
        else:
            raise SystemExit(f"unknown flight action {action!r}")

    def olp(self, action: str = "status") -> None:
        """Overload-protection ladder: level, signals vs thresholds,
        shed/deferred/refused accounting, recent transitions.

            olp [status]
            olp history
        """
        info = self._req("/api/v5/olp")
        if action == "history":
            trans = info["transitions"]
            if not trans:
                print("no olp transitions recorded")
                return
            for t in trans:
                sig = " ".join(
                    f"{k}={v}" for k, v in sorted(
                        (t.get("signals") or {}).items())
                )
                print(f"L{t['from']} -> L{t['to']} at {t['at']:.3f}"
                      + (f"  [{sig}]" if sig else ""))
            return
        if action != "status":
            raise SystemExit(f"unknown olp action {action!r}")
        state = "enabled" if info["enable"] else "disabled"
        print(
            f"olp {state}; level {info['level']}"
            + (f" (hold {info['hold_remaining']}s)"
               if info["hold_remaining"] else "")
            + (f"; window_cap={info['window_cap']}"
               if info["window_cap"] else "")
            + ("; limiters clamped" if info["clamped"] else "")
        )
        ths = info["thresholds"]
        for name, val in sorted(info["signals"].items()):
            t = ths.get(name, [])
            print(f"  {name:>16} = {val}\t(L1/L2/L3: "
                  f"{'/'.join(str(x) for x in t)})")
        counters = {
            k: v for k, v in info["counters"].items() if v
        }
        if counters:
            print("  shed/deferred/refused:")
            for k, v in sorted(counters.items()):
                print(f"    {k} = {v}")
        if info["retained_deferred"]:
            print(f"  retained catch-up deferred: "
                  f"{info['retained_deferred']} jobs")
        for t in info["transitions"][-8:]:
            print(f"  transition {t['from']} -> {t['to']} at {t['at']:.1f}"
                  f" (signals {t['signals']})")

    def banned(self, action: str = "list", *args: str) -> None:
        if action == "list":
            for b in self._req("/api/v5/banned")["data"]:
                print(f"{b['as']}={b['who']}\tuntil={b['until']}")
        elif action == "add":
            self._req(
                "/api/v5/banned",
                method="POST",
                body={"as": args[0], "who": args[1]},
            )
            print(f"banned {args[0]}={args[1]}")
        elif action == "del":
            self._req(
                f"/api/v5/banned/{args[0]}/{args[1]}", method="DELETE"
            )
            print(f"unbanned {args[0]}={args[1]}")
        else:
            raise SystemExit(f"unknown banned action {action!r}")


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="emqx_tpu.ctl")
    ap.add_argument(
        "--api",
        default="http://127.0.0.1:18083",
        help="management API base URL",
    )
    ap.add_argument(
        "--user",
        default=os.environ.get("EMQX_CTL_USER", "admin:public"),
        help="admin credentials as user:password "
        "(env EMQX_CTL_USER; logs in for a Bearer token)",
    )
    ap.add_argument(
        "--api-key",
        default=os.environ.get("EMQX_CTL_API_KEY"),
        help="API key as key:secret (env EMQX_CTL_API_KEY; "
        "preferred over --user when set)",
    )
    ap.add_argument("command", help="status|clients|subscriptions|topics|"
                    "rules|metrics|stats|publish|trace|banned|data|"
                    "rebalance|failpoints|profiler|tracing|olp|flight")
    ap.add_argument("args", nargs="*")
    ap.add_argument("--qos", type=int, default=0)
    ns = ap.parse_args(argv)
    ctl = Ctl(ns.api, user=ns.user, api_key=ns.api_key)

    cmd = ns.command
    if cmd == "status":
        ctl.status()
    elif cmd == "clients":
        ctl.clients(kick=ns.args[1] if ns.args[:1] == ["kick"] else None)
    elif cmd == "subscriptions":
        ctl.subscriptions()
    elif cmd == "topics":
        ctl.topics()
    elif cmd == "rules":
        ctl.rules()
    elif cmd == "metrics":
        ctl.metrics(ns.args[0] if ns.args else None)
    elif cmd == "stats":
        ctl.stats()
    elif cmd == "publish":
        ctl.publish(ns.args[0], ns.args[1] if len(ns.args) > 1 else "",
                    qos=ns.qos)
    elif cmd == "trace":
        ctl.trace(ns.args[0] if ns.args else "list", *ns.args[1:])
    elif cmd == "banned":
        ctl.banned(ns.args[0] if ns.args else "list", *ns.args[1:])
    elif cmd == "failpoints":
        ctl.failpoints(ns.args[0] if ns.args else "list", *ns.args[1:])
    elif cmd == "profiler":
        ctl.profiler(ns.args[0] if ns.args else "summary", *ns.args[1:])
    elif cmd == "tracing":
        ctl.tracing(ns.args[0] if ns.args else "status", *ns.args[1:])
    elif cmd == "data":
        ctl.data(ns.args[0] if ns.args else "export", *ns.args[1:])
    elif cmd == "rebalance":
        ctl.rebalance(ns.args[0] if ns.args else "status",
                      *ns.args[1:])
    elif cmd == "olp":
        ctl.olp(ns.args[0] if ns.args else "status")
    elif cmd == "flight":
        ctl.flight(ns.args[0] if ns.args else "status", *ns.args[1:])
    else:
        raise SystemExit(f"unknown command {cmd!r}")


if __name__ == "__main__":
    main()
