"""Broker-internal message representation.

The reference converts wire packets into `#message{}` records before
routing (`emqx_packet:to_message`, /root/reference/apps/emqx/src/
emqx_packet.erl:467-498; record fields in emqx/include/emqx.hrl).  Here
the analogue is a small dataclass carrying the routing-relevant fields
plus MQTT 5 properties; payload stays opaque bytes.
"""

from __future__ import annotations

import itertools
import os
import struct
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

_guid_counter = itertools.count()
_guid_node = os.getpid() & 0xFFFF


def new_guid() -> bytes:
    """Monotonic-ish 16-byte message id: (ns timestamp, pid, counter).
    Plays the role of `emqx_guid:gen/0` (apps/emqx/src/emqx_guid.erl) —
    unique per broker process, roughly time-ordered."""
    return struct.pack(
        ">QHHI",
        time.time_ns() & 0xFFFFFFFFFFFFFFFF,
        _guid_node,
        0,
        next(_guid_counter) & 0xFFFFFFFF,
    )


@dataclass
class Message:
    topic: str
    payload: bytes = b""
    qos: int = 0
    retain: bool = False
    from_client: str = ""
    from_username: Optional[str] = None
    mid: bytes = field(default_factory=new_guid)
    timestamp: float = field(default_factory=time.time)
    properties: Dict[str, object] = field(default_factory=dict)
    # broker-internal metadata that never reaches the wire (the
    # reference's #message.headers)
    headers: Dict[str, object] = field(default_factory=dict)
    # broker-internal flags (sys: $SYS self-publishes skip some hooks;
    # dup: redelivery)
    sys: bool = False
    dup: bool = False

    def expired(self, now: Optional[float] = None) -> bool:
        """MQTT 5 message-expiry-interval check (emqx_message:is_expired,
        apps/emqx/src/emqx_message.erl:270-283)."""
        interval = self.properties.get("message_expiry_interval")
        if interval is None:
            return False
        return (now if now is not None else time.time()) > (
            self.timestamp + float(interval)  # type: ignore[arg-type]
        )

    def remaining_expiry(self, now: Optional[float] = None) -> Optional[int]:
        """Expiry seconds left (to rewrite the property on delivery, per
        MQTT 5 [MQTT-3.3.2-6])."""
        interval = self.properties.get("message_expiry_interval")
        if interval is None:
            return None
        left = self.timestamp + float(interval) - (  # type: ignore[arg-type]
            now if now is not None else time.time()
        )
        return max(0, int(left))
